"""Update guard: veto optimizer steps that would corrupt the policy.

One NaN gradient is enough to zero a run — Adam moments absorb the
non-finite update and every subsequent step inherits it, silently.
The guard sits between ``train_step``'s metrics and the decision to
ADOPT the new state (training/rl_loop.py, trainer.train_step_guarded):
it never touches device buffers, it just reads the already-synced host
floats and answers "keep or revert".

Three tripwires, checked in order:

1. non-finite loss (NaN/Inf),
2. non-finite global grad norm,
3. loss spike — rolling z-score of the loss against the last
   ``spike_window`` ACCEPTED losses (rejected losses never enter the
   history, so one spike can't poison the baseline that judges the
   next).

Every trip increments ``senweaver_grpo_updates_skipped_total{reason=}``
AND the dashboard-facing ``senweaver_guard_skips_total{reason=}`` (the
Resilience tile reads the latter per-reason), and is appended to
:attr:`UpdateGuard.skipped` for the round capture.

:class:`HealthMitigator` is the PR-9 companion: where the guard vetoes
a single poisoned STEP, the mitigator reshapes the OBJECTIVE when the
training-health detectors (obs/training_health.py) trip persistently —
RLOO leave-one-out baselines, token-level credit, group-size
rescheduling — with streak hysteresis, hard config gates, and every
enable/disable/veto counted and surfaced as a round event.
"""

from __future__ import annotations

import collections
import math
import threading

from ..obs.incidents import emit_event
from typing import Dict, Iterable, List, Optional, Tuple

from ..obs.training_health import (TRIGGER_CREDIT_COLLAPSE,
                                   TRIGGER_GRAD_SPARSITY,
                                   TRIGGER_RANK_COLLAPSE,
                                   TRIGGER_STALENESS_DRIFT,
                                   TRIGGER_ZERO_GROUPS)
from .faults import ResilienceConfig

REASON_NONFINITE_LOSS = "nonfinite_loss"
REASON_NONFINITE_GRAD = "nonfinite_grad_norm"
REASON_LOSS_SPIKE = "loss_spike"


class UpdateGuard:
    """Stateful keep-or-revert decision over per-update metrics.

    One guard instance spans a RUN (the rolling loss history is the
    whole point) — construct it once per loop, not per round."""

    def __init__(self, *, spike_zscore: float = 6.0,
                 spike_window: int = 16, spike_min_history: int = 5,
                 spike_min_std: float = 1e-3, registry=None):
        if registry is None:
            from ..obs import get_registry
            registry = get_registry()
        self.spike_zscore = float(spike_zscore)
        self.spike_min_history = int(spike_min_history)
        self.spike_min_std = float(spike_min_std)
        self._history: collections.deque = collections.deque(
            maxlen=int(spike_window))
        self._lock = threading.Lock()
        self._skipped_total = registry.counter(
            "senweaver_grpo_updates_skipped_total",
            "GRPO optimizer steps vetoed by the update guard",
            labelnames=("reason",))
        self._skips_total = registry.counter(
            "senweaver_guard_skips_total",
            "Update-guard skips by reason (dashboard Resilience tile).",
            labelnames=("reason",))
        self.skipped: List[Tuple[str, Optional[float]]] = []

    @classmethod
    def from_config(cls, config: ResilienceConfig,
                    registry=None) -> Optional["UpdateGuard"]:
        if not config.guard_updates:
            return None
        return cls(spike_zscore=config.spike_zscore,
                   spike_window=config.spike_window,
                   spike_min_history=config.spike_min_history,
                   spike_min_std=config.spike_min_std, registry=registry)

    def check(self, metrics: Dict[str, float]) -> Optional[str]:
        """Returns a skip reason, or None to accept (and the accepted
        loss joins the spike baseline)."""
        loss = metrics.get("loss")
        grad_norm = metrics.get("grad_norm")
        reason = None
        with self._lock:
            if loss is None or not math.isfinite(loss):
                reason = REASON_NONFINITE_LOSS
            elif grad_norm is not None and not math.isfinite(grad_norm):
                reason = REASON_NONFINITE_GRAD
            elif len(self._history) >= self.spike_min_history:
                mean = sum(self._history) / len(self._history)
                var = sum((x - mean) ** 2 for x in self._history) \
                    / len(self._history)
                std = max(math.sqrt(var), self.spike_min_std)
                if abs(loss - mean) / std > self.spike_zscore:
                    reason = REASON_LOSS_SPIKE
            if reason is None:
                self._history.append(float(loss))
                return None
            self.skipped.append((reason, loss))
        self._skipped_total.inc(reason=reason)
        self._skips_total.inc(reason=reason)
        return reason

    @property
    def history(self) -> List[float]:
        with self._lock:
            return list(self._history)


# Mitigation names — the {mitigation=} label values and round-event
# suffixes. Each maps to the detector triggers that motivate it.
MITIGATION_LEAVE_ONE_OUT = "leave_one_out"
MITIGATION_TOKEN_LEVEL = "token_level_advantages"
MITIGATION_GROUP_SIZE = "group_size"
MITIGATION_LOCKSTEP_FALLBACK = "lockstep_fallback"

_MITIGATION_TRIGGERS: Dict[str, Tuple[str, ...]] = {
    # Rank collapse / tied groups: std-normalization couples every
    # trajectory to its own group's spread — RLOO decouples it.
    MITIGATION_LEAVE_ONE_OUT: (TRIGGER_RANK_COLLAPSE,
                               TRIGGER_ZERO_GROUPS),
    # Credit concentrating on a few tokens / sparse gradients: spread
    # sequence advantage with gamma-decay token credit.
    MITIGATION_TOKEN_LEVEL: (TRIGGER_CREDIT_COLLAPSE,
                             TRIGGER_GRAD_SPARSITY),
    # Mostly-tied groups also mean the group size is too small to
    # separate rewards — grow it (scheduler lives in training/rl_loop).
    MITIGATION_GROUP_SIZE: (TRIGGER_ZERO_GROUPS,
                            TRIGGER_GRAD_SPARSITY),
    # Streaming learner running too far off-policy: drop back to
    # lockstep (train only on current-version batches and block on
    # publish convergence) until staleness quiets. No config field on
    # GRPOConfig — the streaming learner polls
    # :meth:`HealthMitigator.lockstep_fallback_active`, exactly the
    # group_size pattern.
    MITIGATION_LOCKSTEP_FALLBACK: (TRIGGER_STALENESS_DRIFT,),
}


class HealthMitigator:
    """Streak-hysteresis gate from health triggers to GRPO mitigations.

    One instance spans a run (like :class:`UpdateGuard`). Per round,
    :meth:`apply` folds the PRE-step triggers (plus any post-step
    triggers noted last round via :meth:`note_post_step` — grad
    sparsity, entropy and KL only exist after the update) into
    per-mitigation streaks: ``trigger_rounds`` consecutive firing
    rounds enable a mitigation, the same count of quiet rounds disable
    it. A mitigation whose config gate is off (master
    ``health_mitigations`` or its sub-gate) is VETOED instead of
    enabled — counted once per streak in
    ``senweaver_grpo_health_mitigations_total{action="vetoed"}`` so a
    run that WOULD have self-modified is visible without it actually
    doing so. All transitions are returned as round events
    (``mitigation_<action>:<name>``)."""

    def __init__(self, *, enabled: bool = False,
                 allow: Optional[Dict[str, bool]] = None,
                 trigger_rounds: int = 2, registry=None):
        if registry is None:
            from ..obs import get_registry
            registry = get_registry()
        self.enabled = bool(enabled)
        self.allow = {m: True for m in _MITIGATION_TRIGGERS}
        if allow:
            self.allow.update(allow)
        self.trigger_rounds = max(1, int(trigger_rounds))
        self.active: Dict[str, bool] = {m: False
                                        for m in _MITIGATION_TRIGGERS}
        self._streak_on = {m: 0 for m in _MITIGATION_TRIGGERS}
        self._streak_off = {m: 0 for m in _MITIGATION_TRIGGERS}
        self._vetoed_this_streak = {m: False for m in _MITIGATION_TRIGGERS}
        self._pending_post: set = set()
        self._lock = threading.Lock()
        self._transitions = registry.counter(
            "senweaver_grpo_health_mitigations_total",
            "Health-mitigation transitions (enabled/disabled/vetoed).",
            labelnames=("mitigation", "action"))

    @classmethod
    def from_config(cls, config: ResilienceConfig,
                    registry=None) -> "HealthMitigator":
        return cls(
            enabled=config.health_mitigations,
            allow={
                MITIGATION_LEAVE_ONE_OUT: config.mitigate_leave_one_out,
                MITIGATION_TOKEN_LEVEL: config.mitigate_token_level,
                MITIGATION_GROUP_SIZE: config.mitigate_group_size,
                MITIGATION_LOCKSTEP_FALLBACK:
                    config.mitigate_lockstep_fallback,
            },
            trigger_rounds=config.health_trigger_rounds,
            registry=registry)

    def effective(self, grpo_config):
        """The config CURRENTLY in force (active mitigations applied,
        no streak folding) — what the round's diagnostics should mirror
        before this round's triggers are known."""
        with self._lock:
            loo = self.active[MITIGATION_LEAVE_ONE_OUT]
            tok = self.active[MITIGATION_TOKEN_LEVEL]
        out = grpo_config
        if loo and not out.leave_one_out:
            out = out._replace(leave_one_out=True)
        if tok and not out.token_level_advantages:
            out = out._replace(token_level_advantages=True)
        return out

    def note_post_step(self, triggers: Iterable[str]) -> None:
        """Feed POST-step triggers (grad sparsity / entropy / KL drift)
        into the NEXT round's streak accounting."""
        with self._lock:
            self._pending_post.update(triggers)

    def apply(self, grpo_config, triggers: Iterable[str]):
        """Fold one round's triggers; returns ``(effective_config,
        events)`` where the config has active mitigations switched on
        via ``_replace`` (the caller's config object is never mutated;
        group_size has no config field — poll :meth:`group_size_active`
        or read ``active``)."""
        events: List[str] = []
        with self._lock:
            trig = set(triggers) | self._pending_post
            self._pending_post = set()
            for mit, names in _MITIGATION_TRIGGERS.items():
                fired = any(t in trig for t in names)
                if fired:
                    self._streak_on[mit] += 1
                    self._streak_off[mit] = 0
                else:
                    self._streak_off[mit] += 1
                    self._streak_on[mit] = 0
                    self._vetoed_this_streak[mit] = False
                if (not self.active[mit]
                        and self._streak_on[mit] >= self.trigger_rounds):
                    if self.enabled and self.allow.get(mit, False):
                        self.active[mit] = True
                        self._transitions.inc(mitigation=mit,
                                              action="enabled")
                        emit_event("health_mitigation", action="enabled",
                                   mitigation=mit)
                        events.append(f"mitigation_enabled:{mit}")
                    elif not self._vetoed_this_streak[mit]:
                        self._vetoed_this_streak[mit] = True
                        self._transitions.inc(mitigation=mit,
                                              action="vetoed")
                        emit_event("health_mitigation", action="vetoed",
                                   mitigation=mit)
                        events.append(f"mitigation_vetoed:{mit}")
                elif (self.active[mit]
                        and self._streak_off[mit] >= self.trigger_rounds):
                    self.active[mit] = False
                    self._transitions.inc(mitigation=mit,
                                          action="disabled")
                    emit_event("health_mitigation", action="disabled",
                               mitigation=mit)
                    events.append(f"mitigation_disabled:{mit}")
            loo = self.active[MITIGATION_LEAVE_ONE_OUT]
            tok = self.active[MITIGATION_TOKEN_LEVEL]
        effective = grpo_config
        if loo and not effective.leave_one_out:
            effective = effective._replace(leave_one_out=True)
        if tok and not effective.token_level_advantages:
            effective = effective._replace(token_level_advantages=True)
        return effective, events

    def group_size_active(self) -> bool:
        with self._lock:
            return self.active[MITIGATION_GROUP_SIZE]

    def lockstep_fallback_active(self) -> bool:
        """True while the staleness-drift streak holds — the streaming
        learner polls this each step and runs lockstep (synchronous
        publish, zero-staleness batches) until the detector quiets."""
        with self._lock:
            return self.active[MITIGATION_LOCKSTEP_FALLBACK]
