"""Shared retry policy + circuit breaker for every network-ish edge.

Before this module the repo had the same retry loop written twice with
different bugs available to each copy: ``traces/uploader.py`` retried
transient 5xx in-call with jittered backoff, and ``serve/router.py``
retried orphaned requests across replica deaths with the episode
boundary's backoff shape. The remote-replica transport would have been
a third copy. This is the one policy object they all share:

- :class:`RetryPolicy` — how many retries, what backoff shape (the
  ``episode_retry_delay_s`` 1.5x exponential, so serving and training
  degrade identically), whether to jitter, and an optional total
  deadline across attempts.
- :class:`RetryBudget` — per-operation accounting: ``next_delay()``
  either returns how long to back off before the next attempt or None
  when the budget (attempts OR deadline) is spent. Understands
  server-provided ``Retry-After`` floors: backoff never undercuts what
  the server asked for.
- :class:`CircuitBreaker` — per-target CLOSED → OPEN → HALF_OPEN
  machine. Consecutive failures past the threshold open the circuit
  (callers fail fast instead of burning timeouts against a dead host);
  after ``reset_timeout_s`` one probe call is let through (HALF_OPEN)
  and its outcome closes or re-opens the circuit. Time is always passed
  in by the caller (``now``), never read from a wall clock, so every
  breaker test runs on a fake clock.

None of this sleeps or reads clocks on its own — callers own time and
sleeping, which keeps the policy pure and the chaos tests hermetic.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Optional

from .faults import episode_retry_delay_s

# Breaker states (gauge-friendly codes: 0 closed, 1 half-open, 2 open).
BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"
BREAKER_STATE_CODE = {BREAKER_CLOSED: 0, BREAKER_HALF_OPEN: 1,
                      BREAKER_OPEN: 2}


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """How one logical operation retries: attempts, backoff, deadline.

    ``max_retries`` counts retries BEYOND the first attempt (0 = one
    attempt, no retry). ``deadline_s``, when set, bounds the total time
    budget across attempts — an operation whose next backoff would land
    past the deadline gives up early instead of sleeping into it.
    """

    max_retries: int = 2
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    jitter: bool = True
    deadline_s: Optional[float] = None

    def backoff_s(self, attempt: int) -> float:
        """Raw (unjittered) backoff before retry ``attempt`` (1-based) —
        the same 1.5x exponential the episode fault boundary uses."""
        return episode_retry_delay_s(attempt, base_s=self.base_delay_s,
                                     max_s=self.max_delay_s)


class RetryBudget:
    """Attempt/deadline accounting for ONE operation under a policy.

    Usage::

        budget = RetryBudget(policy, now=clock())
        while True:
            try:
                return do_call()
            except TransientError:
                delay = budget.next_delay(now=clock())
                if delay is None:
                    raise            # budget spent
                sleep(delay)
    """

    def __init__(self, policy: RetryPolicy, *, now: float, rng=None):
        self.policy = policy
        self.started_at = now
        self.attempt = 0            # retries consumed so far
        self._rng = rng

    def next_delay(self, *, now: float,
                   retry_after_s: Optional[float] = None
                   ) -> Optional[float]:
        """Consume one retry; returns the backoff to wait, or None when
        the budget is spent. ``retry_after_s`` (a server's Retry-After)
        is a FLOOR: the delay is at least that, never jittered below."""
        self.attempt += 1
        if self.attempt > self.policy.max_retries:
            return None
        delay = self.policy.backoff_s(self.attempt)
        if self.policy.jitter and self._rng is not None:
            delay *= 0.5 + self._rng.random()
        if retry_after_s is not None:
            delay = max(delay, float(retry_after_s))
        if self.policy.deadline_s is not None:
            remaining = self.policy.deadline_s - (now - self.started_at)
            if delay >= remaining:
                return None         # would sleep past the deadline
        return delay


def parse_retry_after(value) -> Optional[float]:
    """Seconds to wait from a Retry-After header value (delta-seconds or
    HTTP-date), or None when absent/unparseable. HTTP-dates in the past
    collapse to 0 (retry immediately is what the server asked for)."""
    if value is None:
        return None
    s = str(value).strip()
    try:
        return max(0.0, float(s))
    except ValueError:
        pass
    try:
        import email.utils
        import time as _time
        dt = email.utils.parsedate_to_datetime(s)
        if dt is None:
            return None
        return max(0.0, dt.timestamp() - _time.time())
    except (TypeError, ValueError, OverflowError):
        return None


class CircuitBreaker:
    """Per-target failure gate: CLOSED → OPEN → HALF_OPEN → CLOSED.

    ``failure_threshold`` CONSECUTIVE failures open the circuit; while
    open, :meth:`allow` returns False until ``reset_timeout_s`` has
    passed, at which point exactly one caller is admitted as the
    half-open probe. A success closes the circuit; a failure re-opens it
    for another full timeout. All time arrives via ``now`` arguments.
    """

    def __init__(self, *, failure_threshold: int = 5,
                 reset_timeout_s: float = 30.0,
                 on_state_change: Optional[Callable[[str], None]] = None):
        self.failure_threshold = max(1, int(failure_threshold))
        self.reset_timeout_s = float(reset_timeout_s)
        self.state = BREAKER_CLOSED         # guarded-by: _lock
        self.failures = 0                   # guarded-by: _lock
        self.opened_at: Optional[float] = None  # guarded-by: _lock
        self.opens_total = 0                # guarded-by: _lock
        self._probe_inflight = False        # guarded-by: _lock
        self._on_state_change = on_state_change
        self._lock = threading.Lock()

    def _set_state(self, state: str) -> None:
        """Caller holds the lock."""
        if state == self.state:
            return
        self.state = state
        if state == BREAKER_OPEN:
            self.opens_total += 1
        if self._on_state_change is not None:
            self._on_state_change(state)

    def allow(self, now: float) -> bool:
        """May a call proceed right now? Transitions OPEN → HALF_OPEN
        when the reset timeout has elapsed (admitting one probe)."""
        with self._lock:
            if self.state == BREAKER_CLOSED:
                return True
            if self.state == BREAKER_OPEN:
                if (self.opened_at is not None
                        and now - self.opened_at >= self.reset_timeout_s):
                    self._set_state(BREAKER_HALF_OPEN)
                    self._probe_inflight = True
                    return True
                return False
            # HALF_OPEN: one probe at a time.
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def would_allow(self, now: float) -> bool:
        """Passive :meth:`allow` — same answer, no state transition, no
        probe-slot consumption. For routing decisions (``accepting``)
        that must not spend the half-open probe they aren't making."""
        with self._lock:
            if self.state == BREAKER_CLOSED:
                return True
            if self.state == BREAKER_OPEN:
                return (self.opened_at is not None
                        and now - self.opened_at >= self.reset_timeout_s)
            return not self._probe_inflight

    def record_success(self, now: float) -> None:
        with self._lock:
            self.failures = 0
            self._probe_inflight = False
            self._set_state(BREAKER_CLOSED)

    def record_failure(self, now: float) -> None:
        with self._lock:
            self._probe_inflight = False
            if self.state == BREAKER_HALF_OPEN:
                # The probe failed: straight back to OPEN for another
                # full reset timeout.
                self.opened_at = now
                self._set_state(BREAKER_OPEN)
                return
            self.failures += 1
            if (self.state == BREAKER_CLOSED
                    and self.failures >= self.failure_threshold):
                self.opened_at = now
                self._set_state(BREAKER_OPEN)

    @property
    def state_code(self) -> int:
        return BREAKER_STATE_CODE[self.state]
