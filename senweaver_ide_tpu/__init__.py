"""senweaver_ide_tpu — TPU-native (JAX/XLA/Pallas/pjit) online-RL framework.

A ground-up rebuild of the capabilities of senweaver/senweaver-ide's APO
online-RL engine (reference: /root/reference, snapshot 2026-02-13):

- ``traces``   — conversation-trace collection (8 span types, bounded store,
                 WAL persistence); semantics of ``common/traceCollectorService.ts``.
- ``rewards``  — jit-compiled, vmappable 9-dimension chatMode-adaptive reward
                 head; semantics of ``traceCollectorService.ts:668-788``.
- ``apo``      — effectiveness reports, 6 problem-pattern detectors, textual
                 gradient + beam-search prompt optimization executed against a
                 local TPU-hosted policy; semantics of ``common/apoService.ts``.
- ``models``   — decoder-only policy LLMs (Qwen2/DeepSeek-coder families) as
                 shard-annotated JAX pytrees.
- ``ops``      — core TPU ops: attention (Pallas flash kernels + XLA fallback),
                 RoPE, RMSNorm, sampling.
- ``parallel`` — device mesh, named shardings, DP/FSDP/TP/SP/PP/EP layouts,
                 ring attention over ICI.
- ``training`` — GRPO trainer (group-relative advantages, PPO-clip objective)
                 under pjit with Orbax checkpointing.
- ``rollout``  — TPU sampler (sharded KV cache) + hermetic agent loop and tool
                 sandbox reproducing ``browser/chatThreadService.ts`` semantics.
- ``agents``   — declarative agent registry/scheduler (``common/agentService.ts``).
- ``context``  — context engineering: priority window, compaction, message
                 fitting (``common/smartContextManager.ts``).

The reference defines the *semantics*; every compute and distributed component
here is designed TPU-first, not ported.
"""

__version__ = "0.1.0"
