"""Aux services: skills, extension tool servers, metrics, runtime config.

TPU-build analogues of the reference's L9 services (SURVEY.md §2.5):
skillService.ts, mcpService.ts/mcpChannel.ts, metricsService.ts, and the
tiered config system (product.json / settings / online config).
"""

from .collaboration import CollabCoordinator, CollabSession
from .config import (BUILD_DEFAULTS, GatedPolicyClient, ModelAccessError,
                     RuntimeConfig, install_config_channel)
from .dashboard import DashboardService
from .extensions import (ExtensionServer, ExtensionServerError,
                         ExtensionTool, ExtensionToolRegistry)
from .metrics import MetricsService, load_jsonl_metrics
from .model_refresh import (CustomApiService, RefreshModelService,
                            fetch_model_list)
from .onboarding import OnboardingService, install_onboarding_channel
from .perf_monitor import (DEFAULT_THRESHOLDS_MS, PerformanceMonitor,
                           profile_capture)
from .scm import GitRepo, SCMService, extract_commit_message
from .skills import SkillInfo, SkillService

__all__ = [
    "CollabCoordinator", "CollabSession",
    "BUILD_DEFAULTS", "GatedPolicyClient", "ModelAccessError",
    "RuntimeConfig", "install_config_channel",
    "ExtensionServer", "ExtensionServerError", "ExtensionTool",
    "DashboardService",
    "ExtensionToolRegistry", "MetricsService", "load_jsonl_metrics",
    "CustomApiService", "RefreshModelService", "fetch_model_list",
    "OnboardingService", "install_onboarding_channel",
    "GitRepo", "SCMService", "extract_commit_message",
    "SkillInfo", "SkillService",
]
