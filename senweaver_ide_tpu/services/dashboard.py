"""Operator dashboard: the L6 surface over the trainer control plane.

The reference ships ~20.3k LoC of React (``browser/react/src/`` — sidebar
chat, settings panes, trace/APO dashboards). The TPU-first re-design keeps
the operator surface but not the IDE chrome: one stdlib HTTP server
rendering a single self-contained page (zero egress — no CDN, no build
step) over the SAME stats surfaces the services already expose:

- trace statistics        → ``TraceCollector.get_stats()``
  (``traceCollectorService.ts:577-628`` getTraceStatistics analogue)
- APO state               → ``APOService.get_stats()`` / latest report /
  optimized rules (``apoService.ts:1470-1508`` getAPOStatistics)
- serving counters        → ``RolloutEngine.stats()``
- job queue               → ``ControlServer.list_jobs()``
- training curves         → the metrics JSONL sink's "GRPO Round Done" /
  "Async GRPO Round" events (``services/metrics.py``)

Everything is pluggable and optional: a dashboard over just a metrics
file is as valid as one over a live ``JobRunner`` stack. ``/api/state``
serves the JSON the page polls; tests consume it directly.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional


def _training_curves(metrics_path: Optional[str],
                     limit: int = 200) -> Dict[str, List[Any]]:
    """Per-round series from the metrics JSONL (newest ``limit`` rounds)."""
    if not metrics_path:
        return {"rounds": [], "reward_mean": [], "loss": []}
    from .metrics import load_jsonl_metrics
    try:
        events = load_jsonl_metrics(metrics_path)
    except Exception:
        events = []
    rounds: List[Dict[str, Any]] = [
        e.get("properties", e) for e in events
        if e.get("event") in ("GRPO Round Done", "Async GRPO Round")]
    total = len(rounds)
    rounds = rounds[-limit:]
    return {
        # True round indices survive truncation: a 300-round run shows
        # rounds 100-299, not a relabeled 0-199.
        "rounds": list(range(total - len(rounds), total)),
        "reward_mean": [r.get("reward_mean") for r in rounds],
        "loss": [r.get("loss") for r in rounds],
        "collect_s": [r.get("collect_s") for r in rounds],
        "episodes": [r.get("episodes") for r in rounds],
    }


class DashboardService:
    """Aggregates live service state and serves the operator page."""

    def __init__(self, *, collector=None, apo=None, engine=None,
                 control=None, metrics_path: Optional[str] = None,
                 onboarding=None, title: str = "senweaver-tpu trainer",
                 control_socket: Optional[str] = None,
                 tracer=None, registry=None, slo=None, incidents=None):
        self.collector = collector
        self.apo = apo
        self.engine = engine
        self.control = control
        self.metrics_path = metrics_path
        self.onboarding = onboarding
        self.title = title
        # Optional SLOTracker (obs/slo.py): the registry carries the
        # histograms/counters either way, but exemplar timelines live
        # only on the tracker object — pass the fleet's to see them.
        self.slo = slo
        # Optional IncidentCorrelator (obs/incidents.py): the fleet
        # tile's counters/gauges are registry-read, but the last
        # incident's one-liner lives only on the correlator object.
        self.incidents = incidents
        # Observability plane: defaults to the process-global tracer +
        # registry (obs/), so an instrumented trainer's spans and
        # telemetry show up with zero wiring; tests pass their own.
        if tracer is None or registry is None:
            from ..obs import get_registry, get_tracer
            tracer = tracer or get_tracer()
            registry = registry or get_registry()
        self.tracer = tracer
        self.registry = registry
        # Operator actions go over the control-plane SOCKET (never by
        # calling the services directly): the dashboard holds no
        # credentials — the operator's token travels request → RPC auth
        # field → ControlServer validation, so the HTTP port grants
        # nothing the socket wouldn't.
        self.control_socket = control_socket or getattr(
            control, "socket_path", None)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- state assembly ----------------------------------------------------
    def state(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"title": self.title}
        if self.collector is not None:
            try:
                out["traces"] = self.collector.get_stats()
            except Exception as e:
                out["traces"] = {"error": str(e)}
        if self.engine is not None:
            try:
                out["engine"] = self.engine.stats()
            except Exception as e:
                out["engine"] = {"error": str(e)}
        if self.apo is not None:
            try:
                apo_state: Dict[str, Any] = dict(self.apo.get_stats())
                apo_state["optimized_rules"] = self.apo.get_optimized_rules()
                report = self.apo.get_latest_report()
                # Suggestion rows with IDs: the action buttons need them
                # (apply/reject/revert go over the control plane by id).
                apo_state["suggestions"] = [
                    {"id": s.id, "status": s.status,
                     "priority": s.priority,
                     "description": s.description}
                    for s in self.apo.segments.suggestions]
                if report is not None:
                    apo_state["latest_report"] = {
                        "good_rate": report.good_rate,
                        "total_conversations": report.total_conversations,
                        "patterns": [
                            {"description": p.description,
                             "frequency": p.frequency,
                             "severity": p.severity}
                            for p in report.patterns],
                        "suggestions": [
                            {"description": s.description,
                             "priority": s.priority, "status": s.status}
                            for s in report.suggestions],
                        "avg_reward": report.avg_reward,
                    }
                out["apo"] = apo_state
            except Exception as e:
                out["apo"] = {"error": str(e)}
        if self.control is not None:
            try:
                out["jobs"] = self.control.list_jobs()
            except Exception as e:
                out["jobs"] = [{"error": str(e)}]
        if self.onboarding is not None:
            try:
                out["onboarding"] = self.onboarding.status()
            except Exception as e:
                out["onboarding"] = {"error": str(e)}
        out["training"] = _training_curves(self.metrics_path)
        out["obs"] = self._obs_summary()
        out["training_health"] = self._training_health_summary()
        out["resilience"] = self._resilience_summary()
        out["serving"] = self._serving_summary()
        out["kv_pool"] = self._kv_pool_summary()
        out["speculation"] = self._speculation_summary()
        out["adapters"] = self._adapter_summary()
        out["slo"] = self._slo_summary()
        out["runtime"] = self._runtime_summary()
        out["fleet"] = self._fleet_summary()
        return out

    def _resilience_summary(self) -> Dict[str, Any]:
        """Fault-boundary counter totals (resilience/ + rl_loop): the
        operator's at-a-glance degradation picture — all zeros on a
        healthy run. Labeled counters (skip/fail reasons, chaos kinds)
        sum across their label cells."""
        def total(name: str) -> float:
            m = self.registry.get(name)
            if m is None:
                return 0
            return sum(float(v) for v in m.samples().values())

        try:
            return {
                "episodes_failed":
                    total("senweaver_grpo_episodes_failed_total"),
                "episode_retries":
                    total("senweaver_grpo_episode_retries_total"),
                "groups_dropped":
                    total("senweaver_grpo_task_groups_dropped_total"),
                "rounds_skipped":
                    total("senweaver_grpo_rounds_skipped_total"),
                "updates_skipped":
                    total("senweaver_grpo_updates_skipped_total"),
                "uploader_retries":
                    total("senweaver_uploader_retries_total"),
                "chaos_injected":
                    total("senweaver_chaos_faults_injected_total"),
                # Per-reason guard skips (PR 9): which tripwire fired —
                # nonfinite_loss vs nonfinite_grad_norm vs loss_spike.
                "guard_skip_reasons": self._label_totals(
                    "senweaver_guard_skips_total"),
            }
        except Exception as e:
            return {"error": str(e)}

    def _label_totals(self, name: str) -> Dict[str, float]:
        """A single-label counter's cells as ``{label_value: total}``."""
        m = self.registry.get(name)
        if m is None:
            return {}
        return {k[0]: float(v) for k, v in m.samples().items() if k}

    def _training_health_summary(self) -> Dict[str, Any]:
        """GRPO training-health tile row, read straight off the
        registry's ``senweaver_grpo_health_*`` series (zero wiring —
        any loop publishing through StepTelemetry.record_round shows
        up; all None/zero without one)."""
        def gauge(name: str) -> Optional[float]:
            m = self.registry.get(f"senweaver_grpo_health_{name}")
            return float(m.value()) if m is not None else None

        try:
            rounds = self.registry.get("senweaver_grpo_health_rounds_total")
            group_size = self.registry.get("senweaver_grpo_group_size")
            mit = self.registry.get(
                "senweaver_grpo_health_mitigations_total")
            return {
                "rounds": float(rounds.value()) if rounds else 0,
                "score": gauge("score"),
                "rank_fraction": gauge("rank_fraction"),
                "effective_rank": gauge("effective_rank"),
                "zero_group_fraction":
                    gauge("zero_advantage_group_fraction"),
                "credit_entropy": gauge("credit_entropy"),
                "grad_sparsity": gauge("grad_sparsity"),
                "policy_entropy": gauge("policy_entropy"),
                "kl_to_anchor": gauge("kl_to_anchor"),
                "nonfinite_fraction": gauge("nonfinite_reward_fraction"),
                "group_size": (float(group_size.value())
                               if group_size else None),
                "triggers": self._label_totals(
                    "senweaver_grpo_health_triggers_total"),
                "mitigations": ({"/".join(k): float(v)
                                 for k, v in mit.samples().items()}
                                if mit is not None else {}),
            }
        except Exception as e:
            return {"error": str(e)}

    def _serving_summary(self) -> Dict[str, Any]:
        """Serving-fleet tile row, read straight off the registry's
        ``senweaver_serve_*`` series (zero wiring — any ServingFleet in
        the process shows up; all None/zero without one). Labeled
        counters sum across cells; the TTFT/e2e histograms collapse to
        their running means."""
        def total(name: str) -> float:
            m = self.registry.get(name)
            if m is None:
                return 0
            return sum(float(v) for v in m.samples().values())

        def total_where(name: str, idx: int, want: str) -> float:
            """Sum only the cells whose ``idx``-th label == ``want``."""
            m = self.registry.get(name)
            if m is None:
                return 0
            return sum(float(v) for k, v in m.samples().items()
                       if len(k) > idx and k[idx] == want)

        def hist_mean(name: str) -> Optional[float]:
            m = self.registry.get(name)
            if m is None:
                return None
            s = c = 0.0
            for cell in m.samples().values():
                s += cell[-2]
                c += cell[-1]
            return (s / c) if c else None

        try:
            live = self.registry.get("senweaver_serve_replicas_live")
            versions = self.registry.get("senweaver_serve_weight_version")
            skew = self.registry.get(
                "senweaver_serve_weight_version_skew")
            return {
                "replicas_live": (None if live is None
                                  else live.value()),
                "queue_depth": total("senweaver_serve_queue_depth"),
                "completed": total("senweaver_serve_completed_total"),
                "shed": total("senweaver_serve_shed_total"),
                "retries": total("senweaver_serve_retries_total"),
                "replica_deaths":
                    total("senweaver_serve_replica_deaths_total"),
                "publishes": total("senweaver_serve_publishes_total"),
                "weight_version": (
                    max((float(v) for v in versions.samples().values()),
                        default=0) if versions is not None else 0),
                "version_skew": (skew.value()
                                 if skew is not None else 0),
                "ttft_ms_mean": hist_mean("senweaver_serve_ttft_ms"),
                "e2e_ms_mean": hist_mean("senweaver_serve_e2e_ms"),
                "prefix_broadcasts":
                    total("senweaver_serve_prefix_broadcasts_total"),
                "prefix_prefills_avoided": total(
                    "senweaver_serve_prefix_prefills_avoided_total"),
                "prefix_broadcast_failures": total(
                    "senweaver_serve_prefix_broadcast_failures_total"),
                "prefix_install_ms_mean":
                    hist_mean("senweaver_serve_prefix_install_ms"),
                "decode_tokens_outstanding": total(
                    "senweaver_serve_replica_decode_tokens"),
                "remote_rpcs": total(
                    "senweaver_serve_remote_rpcs_total"),
                "remote_rpc_retries": total(
                    "senweaver_serve_remote_rpc_retries_total"),
                "remote_rpc_errors": total(
                    "senweaver_serve_remote_rpc_errors_total"),
                "breaker_opens": total(
                    "senweaver_serve_remote_breaker_opens_total"),
                "probes_dead": total_where(
                    "senweaver_serve_remote_probes_total", 1, "dead"),
                "continuation_replays": total(
                    "senweaver_serve_continuation_replays_total"),
                "publish_quarantined": total(
                    "senweaver_serve_publish_quarantined_total"),
                "stale_publishes": total(
                    "senweaver_serve_stale_publish_total"),
                "lease_epoch": total("senweaver_lease_epoch"),
                "learner_rounds": total(
                    "senweaver_learner_rounds_total"),
                "learner_publishes": total(
                    "senweaver_learner_publishes_total"),
                "learner_publish_failures": total(
                    "senweaver_learner_publish_failures_total"),
                "learner_resume_republishes": total(
                    "senweaver_learner_resume_republishes_total"),
                "learner_lease_lost": total(
                    "senweaver_learner_lease_lost_total"),
                "learner_idle_fraction": total(
                    "senweaver_learner_idle_fraction"),
                "learner_streaming_mode": total(
                    "senweaver_learner_streaming_mode"),
                "stream_steps_streaming": total_where(
                    "senweaver_learner_stream_steps_total", 0,
                    "streaming"),
                "stream_steps_lockstep": total_where(
                    "senweaver_learner_stream_steps_total", 0,
                    "lockstep"),
                "experience_queue_depth": total(
                    "senweaver_learner_experience_queue_depth"),
                "experience_ready_groups": total(
                    "senweaver_learner_experience_ready_groups"),
                "stale_episodes": total(
                    "senweaver_learner_stale_episodes_total"),
                "duplicate_episodes": total(
                    "senweaver_learner_duplicate_episodes_total"),
                "collector_stall_fraction": total(
                    "senweaver_collector_stall_fraction"),
                "autoscale_adds": total_where(
                    "senweaver_serve_autoscale_actions_total", 0, "add"),
                "autoscale_drains": total_where(
                    "senweaver_serve_autoscale_actions_total", 0,
                    "drain"),
                "autoscale_shed_rate": total(
                    "senweaver_serve_autoscale_shed_rate"),
            }
        except Exception as e:
            return {"error": str(e)}

    def _kv_pool_summary(self) -> Dict[str, Any]:
        """Paged-KV pool tile, read straight off the registry's
        ``senweaver_kv_*`` series (zero wiring — any BlockAllocator in
        the process shows up; all None/zero under the slot layout).
        Block gauges sum across allocators; the utilization and
        fragmentation ratios report the WORST pool, since one starved
        engine stalls its replica no matter how empty the others are."""
        def total(name: str) -> float:
            m = self.registry.get(name)
            if m is None:
                return 0
            return sum(float(v) for v in m.samples().values())

        def worst(name: str) -> Optional[float]:
            m = self.registry.get(name)
            if m is None:
                return None
            vals = [float(v) for v in m.samples().values()]
            return max(vals) if vals else None

        try:
            return {
                "blocks_total": total("senweaver_kv_blocks_total"),
                "blocks_free": total("senweaver_kv_blocks_free"),
                "utilization": worst("senweaver_kv_pool_utilization"),
                "fragmentation": worst("senweaver_kv_fragmentation"),
                "cow_copies": total("senweaver_kv_cow_copies_total"),
                "prefix_grafts":
                    total("senweaver_kv_prefix_grafts_total"),
                "install_copies":
                    total("senweaver_kv_install_copies_total"),
                "exhaustion_rejections": total(
                    "senweaver_kv_exhaustion_rejections_total"),
                # memory-pressure ladder: how often each rung fired,
                # how much KV currently lives in the host tier, and
                # whether admission is shedding on pool pressure
                "pressure": worst("senweaver_kv_pressure"),
                "evictions": total("senweaver_kv_evictions_total"),
                "swaps_out": total("senweaver_kv_swaps_out_total"),
                "swaps_in": total("senweaver_kv_swaps_in_total"),
                "swapped_blocks": total("senweaver_kv_swapped_blocks"),
                # quantized-ladder byte ledger: device KV held by live
                # blocks and KV parked in the host tier, at whatever
                # rung each pool runs (int8 pools report ~3x fewer
                # bytes per block than bf16)
                "bytes_device": total("senweaver_kv_bytes_device"),
                "bytes_host": total("senweaver_kv_bytes_host"),
                "preemption_storms": total(
                    "senweaver_kv_preemption_storms_total"),
                "kv_gated": total("senweaver_serve_kv_gated"),
            }
        except Exception as e:
            return {"error": str(e)}

    def _speculation_summary(self) -> Dict[str, Any]:
        """Speculation tile, read straight off the registry's
        ``senweaver_spec_*`` series (zero wiring — all None/zero when
        no engine enabled speculation). Depth/load/staleness report
        the most recently stepped engine's gauge; acceptance reports
        the WORST replica, since the depth controller throttles on the
        replica that's wasting the most verify compute."""
        def total(name: str) -> float:
            m = self.registry.get(name)
            if m is None:
                return 0
            return sum(float(v) for v in m.samples().values())

        def gauge(name: str, pick=max) -> Optional[float]:
            m = self.registry.get(name)
            if m is None:
                return None
            vals = [float(v) for v in m.samples().values()]
            return pick(vals) if vals else None

        try:
            return {
                "depth": gauge("senweaver_spec_depth"),
                "controller_load":
                    gauge("senweaver_spec_controller_load"),
                "depth_changes":
                    total("senweaver_spec_depth_changes_total"),
                "acceptance_rate":
                    gauge("senweaver_spec_acceptance_rate", min),
                "draft_staleness":
                    gauge("senweaver_spec_draft_staleness"),
                "wasted_draft_tokens":
                    total("senweaver_spec_wasted_draft_tokens_total"),
                "distill_steps":
                    total("senweaver_spec_distill_steps_total"),
                "distill_loss": gauge("senweaver_spec_distill_loss"),
                "draft_publishes":
                    total("senweaver_serve_draft_publishes_total"),
                "draft_install_failures": total(
                    "senweaver_serve_draft_install_failures_total"),
                "draft_blocks_free":
                    total("senweaver_spec_draft_kv_blocks_free"),
            }
        except Exception as e:
            return {"error": str(e)}

    def _adapter_summary(self) -> Dict[str, Any]:
        """Multi-tenant tile: adapter-pool occupancy and churn, publish
        traffic (pool-level and fleet-level), tenant version skew, and
        the gathered-step overhead — all off the registry (the pool and
        WeightPublisher register these at construction)."""
        def total(name: str) -> float:
            m = self.registry.get(name)
            if m is None:
                return 0
            return sum(float(v) for v in m.samples().values())

        def gauge(name: str, pick=max) -> Optional[float]:
            m = self.registry.get(name)
            if m is None:
                return None
            vals = [float(v) for v in m.samples().values()]
            return pick(vals) if vals else None

        try:
            return {
                "pool_slots":
                    total("senweaver_serve_adapter_pool_slots"),
                "pool_resident":
                    total("senweaver_serve_adapter_pool_resident"),
                "publishes":
                    total("senweaver_serve_adapter_publishes_total"),
                "fleet_publishes": total(
                    "senweaver_serve_adapter_fleet_publishes_total"),
                "installs":
                    total("senweaver_serve_adapter_installs_total"),
                "evictions":
                    total("senweaver_serve_adapter_evictions_total"),
                "install_failures": total(
                    "senweaver_serve_adapter_install_failures_total"),
                "affinity_hits": total(
                    "senweaver_serve_adapter_affinity_hits_total"),
                "version_skew":
                    gauge("senweaver_serve_adapter_version_skew"),
                "gather_overhead": gauge(
                    "senweaver_serve_adapter_gather_overhead_ratio"),
            }
        except Exception as e:
            return {"error": str(e)}

    def _slo_summary(self) -> Dict[str, Any]:
        """SLO tile: request/violation totals, burn ratio, and the
        running means of the per-priority seconds histograms — all read
        off the registry (zero wiring). Exemplar timelines are only
        reachable through a live SLOTracker, so the worst-request rows
        appear when the fleet's tracker was passed at construction."""
        def total(name: str) -> float:
            m = self.registry.get(name)
            if m is None:
                return 0
            return sum(float(v) for v in m.samples().values())

        def hist_mean_s(name: str) -> Optional[float]:
            m = self.registry.get(name)
            if m is None:
                return None
            s = c = 0.0
            for cell in m.samples().values():
                s += cell[-2]
                c += cell[-1]
            return (s / c) if c else None

        try:
            burn = self.registry.get("senweaver_serve_slo_burn_ratio")
            # Per-priority gauge; the tile shows the WORST class's burn.
            burn_cells = ([float(v) for v in burn.samples().values()]
                          if burn is not None else [])
            out: Dict[str, Any] = {
                "requests": total("senweaver_serve_slo_requests_total"),
                "violations": total(
                    "senweaver_serve_slo_violations_total"),
                "burn_ratio": max(burn_cells) if burn_cells else None,
                "ttft_s_mean":
                    hist_mean_s("senweaver_serve_ttft_seconds"),
                "tpot_s_mean":
                    hist_mean_s("senweaver_serve_tpot_seconds"),
                "queue_wait_s_mean":
                    hist_mean_s("senweaver_serve_queue_wait_seconds"),
                "e2e_s_mean":
                    hist_mean_s("senweaver_serve_e2e_seconds"),
                "timelines_finished":
                    total("senweaver_serve_timelines_total"),
                "timelines_evicted":
                    total("senweaver_serve_timelines_evicted_total"),
                "publish_windows":
                    total("senweaver_serve_publish_windows_total"),
                "spans_dropped":
                    total("senweaver_obs_spans_dropped_total"),
            }
            if self.slo is not None:
                out["exemplars"] = [
                    {"ticket": e.get("ticket"),
                     "priority": e.get("priority"),
                     "violations": ",".join(e.get("violations") or [])
                                   or None,
                     "e2e_s": (e.get("derived") or {}).get("e2e_s"),
                     "ttft_s": (e.get("derived") or {}).get("ttft_s"),
                     "trace_id": e.get("trace_id")}
                    for e in self.slo.exemplars()[:5]]
            return out
        except Exception as e:
            return {"error": str(e)}

    def _fleet_summary(self) -> Dict[str, Any]:
        """Fleet-health tile: federation peer counts, worst-replica KV
        pressure, per-window SLO burn, and alerting state — read
        straight off the ``senweaver_fleet_*`` series any
        FleetMetricsStore / AlertManager in the process publishes (zero
        wiring). The last incident's one-liner needs the live
        correlator object, so it appears when one was passed at
        construction."""
        def gauge(name: str) -> Optional[float]:
            m = self.registry.get(name)
            return float(m.value()) if m is not None else None

        def cell(name: str, *labels: str) -> Optional[float]:
            m = self.registry.get(name)
            if m is None:
                return None
            v = m.samples().get(tuple(labels))
            return float(v) if v is not None else None

        try:
            active = self.registry.get("senweaver_fleet_alert_active")
            firing = sorted(
                k[0] for k, v in (active.samples().items()
                                  if active is not None else ())
                if float(v) >= 1.0)
            fired = self.registry.get(
                "senweaver_fleet_alerts_fired_total")
            out: Dict[str, Any] = {
                "peers": gauge("senweaver_fleet_peers"),
                "peers_stale": gauge("senweaver_fleet_peers_stale"),
                "worst_kv_pressure": cell(
                    "senweaver_fleet_rollup",
                    "senweaver_kv_pressure", "max"),
                "burn_fast": cell("senweaver_fleet_burn_ratio",
                                  "slo_burn_fast", "fast"),
                "burn_slow": cell("senweaver_fleet_burn_ratio",
                                  "slo_burn_fast", "slow"),
                "alerts_active": len(firing),
                "alerts_firing": firing,
                "alerts_fired": (sum(float(v) for v in
                                     fired.samples().values())
                                 if fired is not None else 0),
                "incidents": None,
                "last_incident": None,
            }
            if self.incidents is not None:
                inc = self.incidents.summary()
                out["incidents"] = inc.get("incidents")
                out["last_incident"] = inc.get("last")
            return out
        except Exception as e:
            return {"error": str(e)}

    def _runtime_summary(self) -> Dict[str, Any]:
        """Runtime observatory tile: compile/retrace ledger, transfer
        bytes, and HBM watermarks from the global
        :class:`~..obs.runtime_profile.RuntimeProfiler` (zero wiring —
        any ProfiledFunction in the process shows up). Totals come from
        the ledger itself rather than the ``senweaver_runtime_*``
        series so the tile works even when profiling ran against a
        since-swapped registry; the watermark gauges are registry-read
        because memory sampling is per-backend."""
        def label_max(name: str) -> Optional[float]:
            m = self.registry.get(name)
            if m is None:
                return None
            vals = [float(v) for v in m.samples().values()]
            return max(vals) if vals else None

        try:
            from ..obs.runtime_profile import get_profiler
            mb = 1024.0 * 1024.0
            rows = []
            calls = compiles = storms = h2d = d2h = 0
            for name, snap in sorted(get_profiler().ledger().items()):
                calls += snap["calls"]
                compiles += snap["compiles"]
                storms += snap["storms"]
                h2d += snap["h2d_bytes"]
                d2h += snap["d2h_bytes"]
                rows.append({
                    "fn": name, "calls": snap["calls"],
                    "compiles": snap["compiles"],
                    "signatures": len(snap["signatures"]),
                    "compile_ms": snap["compile_ms"],
                    "last_step_ms": snap["last_step_ms"],
                    "storms": snap["storms"],
                })
            wm = label_max("senweaver_runtime_hbm_watermark_bytes")
            live = label_max("senweaver_runtime_live_buffer_bytes")
            return {
                "calls": calls, "compiles": compiles,
                "retrace_storms": storms,
                "h2d_mb": round(h2d / mb, 3),
                "d2h_mb": round(d2h / mb, 3),
                "hbm_watermark_mb":
                    round(wm / mb, 1) if wm is not None else None,
                "live_buffer_mb":
                    round(live / mb, 3) if live is not None else None,
                "roofline_utilization":
                    label_max("senweaver_runtime_roofline_utilization"),
                "functions": rows,
            }
        except Exception as e:
            return {"error": str(e)}

    def _obs_summary(self) -> Dict[str, Any]:
        """Span counts, top-5 slowest spans, and the live throughput
        gauges — the obs tile's data (and /api/state's view of what the
        /metrics endpoint serves in full)."""
        try:
            summary = self.tracer.summary(top=5)
            tps = self.registry.get("senweaver_tokens_per_sec")
            if tps is not None:
                summary["tokens_per_sec"] = tps.value(phase="train")
                summary["collect_tokens_per_sec"] = \
                    tps.value(phase="collect")
            else:
                summary["tokens_per_sec"] = None
            mfu = self.registry.get("senweaver_train_mfu")
            summary["mfu"] = mfu.value() if mfu is not None else None
            rounds = self.registry.get("senweaver_rounds_total")
            summary["rounds_total"] = (rounds.value()
                                       if rounds is not None else 0)
            return summary
        except Exception as e:
            return {"error": str(e)}

    # -- http --------------------------------------------------------------
    def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Serve in a daemon thread; returns the bound port (0 = ephemeral)."""
        service = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib casing)
                if self.path.startswith("/api/state"):
                    body = json.dumps(service.state()).encode()
                    ctype = "application/json"
                elif self.path == "/metrics":
                    # Prometheus text exposition of the obs registry —
                    # scrape-ready (format v0.0.4).
                    body = service.registry.render().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path == "/" or self.path.startswith("/index"):
                    body = _PAGE.replace("__TITLE__", service.title).encode()
                    ctype = "text/html; charset=utf-8"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):  # noqa: N802 (stdlib casing)
                if self.path != "/api/action":
                    self.send_error(404)
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    method = req.get("method", "")
                    params = req.get("params")
                except Exception as e:
                    self._reply(400, {"ok": False,
                                      "error": f"bad request: {e}"})
                    return
                if not service.control_socket:
                    self._reply(503, {"ok": False,
                                      "error": "no control socket wired"})
                    return
                from ..runtime.control import ControlClient, ControlError
                token = self.headers.get("X-Auth-Token") or None
                try:
                    result = ControlClient(service.control_socket).call(
                        method, params, token=token)
                    self._reply(200, {"ok": True, "result": result})
                except ControlError as e:
                    status = 401 if e.code == -32001 else 400
                    self._reply(status, {"ok": False, "code": e.code,
                                         "error": str(e)})
                except (OSError, ValueError) as e:
                    # ValueError covers json.JSONDecodeError from an
                    # empty/truncated control-plane reply — every failure
                    # path must return the structured {ok: false} body.
                    self._reply(502, {"ok": False,
                                      "error": f"control plane: {e}"})

            def _reply(self, status: int, payload: Dict[str, Any]) -> None:
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet: metrics JSONL is the log
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="senweaver-dashboard",
                                        daemon=True)
        self._thread.start()
        return self._httpd.server_address[1]

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# Single-file page. Design per the repo's dataviz conventions: role-based
# CSS custom properties with selected light AND dark values, one accent
# series hue, text in text tokens (never series color), thin marks, a
# recessive grid, hover crosshair + tooltip on the curves, and a table
# view of the recent rounds under the charts.
_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8">
<title>__TITLE__</title>
<style>
:root { color-scheme: light dark; }
body {
  margin: 0; font: 14px/1.45 system-ui, sans-serif;
  background: #fcfcfb; color: #0b0b0b;
  --surface-2: #f1f0ee; --border: #dddcd8;
  --text-2: #52514e; --series-1: #2a78d6; --series-3: #1baf7a;
  --good: #008300; --bad: #e34948; --warn: #eda100;
}
@media (prefers-color-scheme: dark) { body {
  background: #1a1a19; color: #ffffff;
  --surface-2: #242423; --border: #3a3a38;
  --text-2: #c3c2b7; --series-1: #3987e5; --series-3: #199e70;
  --good: #00a300; --bad: #e66767; --warn: #c98500;
}}
header { padding: 14px 20px; border-bottom: 1px solid var(--border); }
header h1 { font-size: 16px; margin: 0; }
header .sub { color: var(--text-2); font-size: 12px; }
main { padding: 16px 20px; max-width: 1100px; }
section { margin-bottom: 22px; }
h2 { font-size: 13px; text-transform: uppercase; letter-spacing: .04em;
     color: var(--text-2); margin: 0 0 8px; }
.tiles { display: flex; flex-wrap: wrap; gap: 10px; }
.tile { background: var(--surface-2); border: 1px solid var(--border);
        border-radius: 8px; padding: 10px 14px; min-width: 120px; }
.tile .v { font-size: 22px; font-weight: 600; }
.tile .l { font-size: 11px; color: var(--text-2); }
table { border-collapse: collapse; font-size: 12.5px; }
td, th { padding: 3px 10px 3px 0; text-align: left; }
th { color: var(--text-2); font-weight: 500; }
tr { border-bottom: 1px solid var(--border); }
.chart-wrap { position: relative; display: inline-block; }
.tooltip { position: absolute; pointer-events: none; display: none;
           background: var(--surface-2); border: 1px solid var(--border);
           border-radius: 6px; padding: 4px 8px; font-size: 12px; }
.status { font-size: 12px; }
.status::before { content: "● "; }
.status.done::before, .status.good::before { color: var(--good); }
.status.failed::before, .status.stopped::before { color: var(--bad); }
.status.running::before, .status.queued::before { color: var(--warn); }
.muted { color: var(--text-2); }
.rules li { margin-bottom: 2px; }
button { font: inherit; font-size: 12px; padding: 2px 10px;
         border: 1px solid var(--border); border-radius: 6px;
         background: var(--surface-2); color: inherit; cursor: pointer; }
button:hover { border-color: var(--text-2); }
input[type=text], input[type=password], textarea {
  font: inherit; font-size: 12.5px; color: inherit;
  background: var(--surface-2); border: 1px solid var(--border);
  border-radius: 6px; padding: 3px 8px; }
.actionbar { display: flex; gap: 8px; align-items: center;
             flex-wrap: wrap; margin: 6px 0; }
#action-status { font-size: 12px; }
#action-status.err { color: var(--bad); }
#action-status.okk { color: var(--good); }
</style></head><body>
<header><h1>__TITLE__</h1>
<div class="sub">operator dashboard · polls /api/state
<span id="updated" class="muted"></span></div>
<div class="actionbar">
<label class="muted" for="tok">auth token</label>
<input type="password" id="tok" size="18"
  placeholder="control-plane token">
<span id="action-status"></span></div></header>
<main>
<section><h2>Traces</h2><div id="traces" class="tiles"></div></section>
<section><h2>Training</h2>
<div id="charts"></div>
<div id="rounds-table"></div></section>
<section><h2>Observability</h2>
<div id="obs" class="tiles"></div>
<div id="obs-spans"></div></section>
<section><h2>Training health</h2>
<div id="training-health" class="tiles"></div>
<div id="health-triggers"></div></section>
<section><h2>Resilience</h2><div id="resilience" class="tiles"></div>
<div id="guard-skips"></div></section>
<section><h2>Serving</h2><div id="serving" class="tiles"></div></section>
<section><h2>Speculation</h2><div id="speculation" class="tiles"></div></section>
<section><h2>Multi-tenant</h2><div id="adapters" class="tiles"></div></section>
<section><h2>SLO</h2>
<div id="slo" class="tiles"></div>
<div id="slo-exemplars"></div></section>
<section><h2>Fleet health</h2>
<div id="fleet" class="tiles"></div>
<div id="fleet-incident"></div></section>
<section><h2>Learner &amp; autoscaler</h2>
<div id="learner" class="tiles"></div></section>
<section><h2>Streaming experience</h2>
<div id="streaming" class="tiles"></div></section>
<section><h2>Runtime</h2>
<div id="runtime" class="tiles"></div>
<div id="runtime-fns"></div></section>
<section><h2>Engine serving counters</h2><div id="engine"></div></section>
<section><h2>APO</h2>
<div class="actionbar">
<button onclick="act('apo.analyze')">analyze now</button>
<button onclick="act('apo.gradient')">request gradient</button></div>
<div id="apo-suggestions"></div>
<div id="apo"></div></section>
<section><h2>Jobs</h2>
<div class="actionbar">
<input type="text" id="job-params" size="32"
  placeholder='job params JSON, e.g. {"kind": "grpo"}'>
<button onclick="submitJob()">submit job</button></div>
<div id="jobs"></div></section>
<section><h2>Live config</h2>
<div class="actionbar">
<input type="text" id="cfg-json" size="44"
  placeholder='config JSON, e.g. {"allowed_models": ["tiny-test"]}'>
<button onclick="pushConfig()">push config</button></div></section>
<section><h2>Setup</h2><div id="onboarding"></div></section>
</main>
<script>
"use strict";
// Everything rendered into innerHTML passes through esc(): APO rules and
// suggestion text come from an LLM — stored-XSS surface without it.
const esc = v => String(v).replace(/[&<>"']/g, c => ({
  "&": "&amp;", "<": "&lt;", ">": "&gt;",
  '"': "&quot;", "'": "&#39;"}[c]));
const fmt = v => v == null ? "–"
  : (typeof v === "number" && !Number.isInteger(v) ? v.toFixed(3) : esc(v));

function tiles(el, pairs) {
  el.innerHTML = pairs.map(([l, v]) =>
    `<div class="tile"><div class="v">${fmt(v)}</div>` +
    `<div class="l">${esc(l)}</div></div>`).join("");
}

// Rows are escaped per-cell; a cell may opt out via {html: "..."} for
// markup the PAGE generated itself (status spans) — never raw data.
function table(rows, headers) {
  if (!rows.length) return '<span class="muted">no data yet</span>';
  const cell = c => (c && typeof c === "object" && "html" in c)
    ? c.html : esc(fmt(c));
  const h = headers.map(x => `<th>${esc(x)}</th>`).join("");
  const b = rows.map(r =>
    `<tr>${r.map(c => `<td>${cell(c)}</td>`).join("")}</tr>`).join("");
  return `<table><tr>${h}</tr>${b}</table>`;
}

const statusSpan = s =>
  ({html: `<span class="status ${esc(s)}">${esc(s)}</span>`});

// Operator actions: POST /api/action → control-plane JSON-RPC. The
// token never persists server-side; it rides each request's header and
// the ControlServer validates it (no token → unauthorized).
const tokEl = () => document.getElementById("tok");
window.addEventListener("DOMContentLoaded", () => {
  tokEl().value = localStorage.getItem("senweaver-token") || "";
  tokEl().addEventListener("change", () =>
    localStorage.setItem("senweaver-token", tokEl().value));
});
async function act(method, params) {
  const st = document.getElementById("action-status");
  st.className = ""; st.textContent = `${method} …`;
  let body;
  try {
    const r = await fetch("/api/action", {
      method: "POST",
      headers: {"Content-Type": "application/json",
                "X-Auth-Token": tokEl().value},
      body: JSON.stringify({method, params})});
    body = await r.json();
  } catch (e) { body = {ok: false, error: String(e)}; }
  st.className = body.ok ? "okk" : "err";
  st.textContent = body.ok ? `${method}: ok`
    : `${method}: ${body.error || "failed"}`;
  refresh();
  return body;
}
function submitJob() {
  let p = document.getElementById("job-params").value.trim();
  try { p = p ? JSON.parse(p) : {}; }
  catch (e) {
    const st = document.getElementById("action-status");
    st.className = "err"; st.textContent = `params: ${e}`; return;
  }
  act("submit", p);
}
function pushConfig() {
  let p = document.getElementById("cfg-json").value.trim();
  try { p = JSON.parse(p || "{}"); }
  catch (e) {
    const st = document.getElementById("action-status");
    st.className = "err"; st.textContent = `config: ${e}`; return;
  }
  act("config.push", p);
}
// Action buttons carry ids via data- attributes (never inline JS with
// interpolated data — the id is LLM-adjacent data, same XSS posture).
document.addEventListener("click", e => {
  const b = e.target.closest("button[data-act]");
  if (!b) return;
  act(b.dataset.act, {id: b.dataset.id, job_id: b.dataset.id});
});
const actBtn = (method, id, label) =>
  ({html: `<button data-act="${esc(method)}" data-id="${esc(id)}">` +
          `${esc(label)}</button>`});

// Single-series line chart: thin 2px line, recessive grid, hover
// crosshair + tooltip, no legend (the title names the series).
function lineChart(xs, ys, label, color) {
  const W = 420, H = 120, P = 28;
  const pts = xs.map((x, i) => [x, ys[i]]).filter(p => p[1] != null);
  if (pts.length < 2)
    return `<div class="muted">${esc(label)}: need ≥2 rounds</div>`;
  const yv = pts.map(p => p[1]);
  const ymin = Math.min(...yv), ymax = Math.max(...yv);
  const yr = (ymax - ymin) || 1;
  const sx = i => P + (W - 2 * P) * i / (pts.length - 1);
  const sy = v => H - P - (H - 2 * P) * (v - ymin) / yr;
  const path = pts.map((p, i) =>
    `${i ? "L" : "M"}${sx(i).toFixed(1)},${sy(p[1]).toFixed(1)}`).join("");
  const grid = [ymin, (ymin + ymax) / 2, ymax].map(v =>
    `<line x1="${P}" x2="${W - P}" y1="${sy(v)}" y2="${sy(v)}"
      stroke="var(--border)" stroke-width="1"/>` +
    `<text x="${P - 4}" y="${sy(v) + 4}" text-anchor="end"
      font-size="10" fill="var(--text-2)">${v.toFixed(2)}</text>`).join("");
  const id = "c" + Math.random().toString(36).slice(2, 8);
  setTimeout(() => hoverLayer(id, pts, sx, sy, label), 0);
  return `<div class="chart-wrap" id="${id}">
    <svg width="${W}" height="${H}" role="img" aria-label="${esc(label)}">
    <text x="${P}" y="14" font-size="11"
      fill="var(--text-2)">${esc(label)}</text>
    ${grid}
    <path d="${path}" fill="none" stroke="${color}" stroke-width="2"/>
    <line class="xh" y1="${P}" y2="${H - P}" stroke="var(--text-2)"
      stroke-width="1" style="display:none"/>
    <circle class="pt" r="4" fill="${color}" stroke="var(--surface-2)"
      stroke-width="2" style="display:none"/>
    </svg><div class="tooltip"></div></div>`;
}

function hoverLayer(id, pts, sx, sy, label) {
  const wrap = document.getElementById(id);
  if (!wrap) return;
  const svg = wrap.querySelector("svg"), tip = wrap.querySelector(".tooltip");
  const xh = svg.querySelector(".xh"), dot = svg.querySelector(".pt");
  svg.addEventListener("mousemove", e => {
    const r = svg.getBoundingClientRect();
    const x = e.clientX - r.left;
    let best = 0, bd = 1e9;
    pts.forEach((p, i) => { const d = Math.abs(sx(i) - x);
                            if (d < bd) { bd = d; best = i; } });
    const px = sx(best), py = sy(pts[best][1]);
    xh.setAttribute("x1", px); xh.setAttribute("x2", px);
    xh.style.display = ""; dot.style.display = "";
    dot.setAttribute("cx", px); dot.setAttribute("cy", py);
    tip.style.display = "block";
    tip.style.left = (px + 10) + "px"; tip.style.top = (py - 10) + "px";
    tip.textContent =
      `round ${pts[best][0]} · ${label} ${fmt(pts[best][1])}`;
  });
  svg.addEventListener("mouseleave", () => {
    xh.style.display = "none"; dot.style.display = "none";
    tip.style.display = "none";
  });
}

async function refresh() {
  let s;
  try { s = await (await fetch("/api/state")).json(); }
  catch (e) { return; }
  document.getElementById("updated").textContent =
    " · updated " + new Date().toLocaleTimeString();
  const t = s.traces || {};
  tiles(document.getElementById("traces"), [
    ["traces", t.total_traces], ["spans", t.total_spans],
    ["good fb", t.good_feedbacks], ["bad fb", t.bad_feedbacks],
    ["tool success", t.tool_success_rate],
    ["avg finalReward", t.avg_final_reward]]);
  const tr = s.training || {rounds: []};
  document.getElementById("charts").innerHTML =
    lineChart(tr.rounds, tr.reward_mean || [], "reward_mean",
              "var(--series-1)") + " " +
    lineChart(tr.rounds, tr.loss || [], "loss", "var(--series-3)");
  // rounds holds TRUE indices (they survive truncation); the series are
  // positional — iterate positions and use rounds[pos] as the label.
  const nR = (tr.rounds || []).length;
  const positions = [...Array(nR).keys()].slice(-12);
  document.getElementById("rounds-table").innerHTML = table(
    positions.map(p => [tr.rounds[p], fmt((tr.reward_mean || [])[p]),
                        fmt((tr.loss || [])[p]),
                        fmt((tr.episodes || [])[p]),
                        fmt((tr.collect_s || [])[p])]),
    ["round", "reward_mean", "loss", "episodes", "collect_s"]);
  const ob_ = s.obs || {};
  tiles(document.getElementById("obs"), [
    ["tracing", ob_.enabled ? "on" : "off"],
    ["spans", ob_.total_spans],
    ["rounds", ob_.rounds_total],
    ["tokens/s train", ob_.tokens_per_sec],
    ["tokens/s collect", ob_.collect_tokens_per_sec],
    ["mfu", ob_.mfu]]);
  document.getElementById("obs-spans").innerHTML = table(
    (ob_.slowest || []).map(x => [x.name, x.duration_ms]),
    ["slowest span", "ms"]);
  const th = s.training_health || {};
  tiles(document.getElementById("training-health"), [
    ["health rounds", th.rounds],
    ["health score", th.score],
    ["rank fraction", th.rank_fraction],
    ["effective rank", th.effective_rank],
    ["zero-adv groups", th.zero_group_fraction],
    ["credit entropy", th.credit_entropy],
    ["grad sparsity", th.grad_sparsity],
    ["policy entropy", th.policy_entropy],
    ["kl to anchor", th.kl_to_anchor],
    ["nonfinite frac", th.nonfinite_fraction],
    ["group size", th.group_size]]);
  document.getElementById("health-triggers").innerHTML = table(
    Object.entries(th.triggers || {}).map(([k, v]) => [k, v])
      .concat(Object.entries(th.mitigations || {})
        .map(([k, v]) => ["mitigation " + k, v])),
    ["trigger / mitigation", "count"]);
  const res = s.resilience || {};
  tiles(document.getElementById("resilience"), [
    ["failed episodes", res.episodes_failed],
    ["episode retries", res.episode_retries],
    ["groups dropped", res.groups_dropped],
    ["rounds skipped", res.rounds_skipped],
    ["updates skipped", res.updates_skipped],
    ["uploader retries", res.uploader_retries],
    ["chaos injected", res.chaos_injected]]);
  document.getElementById("guard-skips").innerHTML = table(
    Object.entries(res.guard_skip_reasons || {}).map(([k, v]) => [k, v]),
    ["guard skip reason", "count"]);
  const sv = s.serving || {};
  tiles(document.getElementById("serving"), [
    ["replicas live", sv.replicas_live],
    ["queue depth", sv.queue_depth],
    ["completed", sv.completed],
    ["shed", sv.shed],
    ["retries", sv.retries],
    ["weight version", sv.weight_version],
    ["version skew", sv.version_skew],
    ["ttft ms (mean)", sv.ttft_ms_mean],
    ["e2e ms (mean)", sv.e2e_ms_mean],
    ["remote rpcs", sv.remote_rpcs],
    ["rpc retries", sv.remote_rpc_retries],
    ["breaker opens", sv.breaker_opens],
    ["probes dead", sv.probes_dead],
    ["continuation replays", sv.continuation_replays],
    ["publish quarantined", sv.publish_quarantined]]);
  const spec = s.speculation || {};
  tiles(document.getElementById("speculation"), [
    ["depth", spec.depth],
    ["controller load", spec.controller_load],
    ["depth changes", spec.depth_changes],
    ["acceptance (worst)", spec.acceptance_rate],
    ["draft staleness", spec.draft_staleness],
    ["wasted draft tokens", spec.wasted_draft_tokens],
    ["distill steps", spec.distill_steps],
    ["distill loss", spec.distill_loss],
    ["draft publishes", spec.draft_publishes],
    ["draft install failures", spec.draft_install_failures],
    ["draft blocks free", spec.draft_blocks_free]]);
  const ad = s.adapters || {};
  tiles(document.getElementById("adapters"), [
    ["pool slots", ad.pool_slots],
    ["resident", ad.pool_resident],
    ["publishes", ad.publishes],
    ["fleet publishes", ad.fleet_publishes],
    ["installs", ad.installs],
    ["evictions", ad.evictions],
    ["install failures", ad.install_failures],
    ["affinity hits", ad.affinity_hits],
    ["version skew", ad.version_skew],
    ["gather overhead", ad.gather_overhead]]);
  const slo = s.slo || {};
  tiles(document.getElementById("slo"), [
    ["slo requests", slo.requests],
    ["slo violations", slo.violations],
    ["burn ratio", slo.burn_ratio],
    ["ttft s (mean)", slo.ttft_s_mean],
    ["tpot s (mean)", slo.tpot_s_mean],
    ["queue wait s (mean)", slo.queue_wait_s_mean],
    ["e2e s (mean)", slo.e2e_s_mean],
    ["timelines", slo.timelines_finished],
    ["timelines evicted", slo.timelines_evicted],
    ["publish windows", slo.publish_windows],
    ["spans dropped", slo.spans_dropped]]);
  document.getElementById("slo-exemplars").innerHTML = table(
    (slo.exemplars || []).map(x => [x.ticket, x.priority, x.violations,
                                    x.ttft_s, x.e2e_s, x.trace_id]),
    ["worst request", "priority", "violated", "ttft_s", "e2e_s",
     "trace"]);
  const fl = s.fleet || {};
  tiles(document.getElementById("fleet"), [
    ["federated peers", fl.peers],
    ["stale peers", fl.peers_stale],
    ["worst kv pressure", fl.worst_kv_pressure],
    ["burn (fast 5m)", fl.burn_fast],
    ["burn (slow 1h)", fl.burn_slow],
    ["active alerts", fl.alerts_active],
    ["alerts fired", fl.alerts_fired],
    ["incidents", fl.incidents]]);
  document.getElementById("fleet-incident").innerHTML =
    (fl.alerts_firing || []).length || fl.last_incident
      ? table([[esc((fl.alerts_firing || []).join(", ") || "none"),
                esc(fl.last_incident || "none")]],
              ["firing", "last incident"])
      : "";
  tiles(document.getElementById("learner"), [
    ["lease epoch", sv.lease_epoch],
    ["learner rounds", sv.learner_rounds],
    ["learner publishes", sv.learner_publishes],
    ["publish failures", sv.learner_publish_failures],
    ["resume republishes", sv.learner_resume_republishes],
    ["lease lost", sv.learner_lease_lost],
    ["stale publishes fenced", sv.stale_publishes],
    ["autoscale adds", sv.autoscale_adds],
    ["autoscale drains", sv.autoscale_drains],
    ["shed rate (1/s)", sv.autoscale_shed_rate]]);
  tiles(document.getElementById("streaming"), [
    ["mode (1=streaming)", sv.learner_streaming_mode],
    ["learner idle fraction", sv.learner_idle_fraction],
    ["steps (streaming)", sv.stream_steps_streaming],
    ["steps (lockstep)", sv.stream_steps_lockstep],
    ["queue depth", sv.experience_queue_depth],
    ["ready groups", sv.experience_ready_groups],
    ["stale episodes dropped", sv.stale_episodes],
    ["duplicate episodes", sv.duplicate_episodes],
    ["collector stall fraction", sv.collector_stall_fraction]]);
  const rt = s.runtime || {};
  tiles(document.getElementById("runtime"), [
    ["profiled calls", rt.calls],
    ["compiles", rt.compiles],
    ["retrace storms", rt.retrace_storms],
    ["h2d MB", rt.h2d_mb],
    ["d2h MB", rt.d2h_mb],
    ["hbm watermark MB", rt.hbm_watermark_mb],
    ["live buffers MB", rt.live_buffer_mb],
    ["roofline util", rt.roofline_utilization]]);
  document.getElementById("runtime-fns").innerHTML = table(
    (rt.functions || []).map(f => [f.fn, f.calls, f.compiles,
                                   f.signatures, f.compile_ms,
                                   f.last_step_ms, f.storms]),
    ["profiled fn", "calls", "compiles", "sigs", "compile ms",
     "last step ms", "storms"]);
  const eng = s.engine || {};
  document.getElementById("engine").innerHTML = table(
    Object.entries(eng).map(([k, v]) => [k, fmt(v)]), ["counter", "value"]);
  const a = s.apo || {};
  let apoHtml = table(
    Object.entries(a).filter(([k, v]) => typeof v !== "object")
      .map(([k, v]) => [k, fmt(v)]), ["stat", "value"]);
  if ((a.optimized_rules || []).length)
    apoHtml += "<ul class='rules'>" + a.optimized_rules.map(r =>
      `<li>${esc(r)}</li>`).join("") + "</ul>";
  // (report-snapshot suggestions are NOT rendered here: the live
  // actionable table above supersedes them, and snapshot statuses go
  // stale the moment an apply/reject lands.)
  document.getElementById("apo").innerHTML = apoHtml;
  document.getElementById("apo-suggestions").innerHTML = table(
    (a.suggestions || []).map(x => [
      statusSpan(x.status), x.priority, x.description,
      x.status === "pending" ? actBtn("apo.apply", x.id, "apply") : "",
      x.status === "pending" ? actBtn("apo.reject", x.id, "reject")
        : (x.status === "applied" ? actBtn("apo.revert", x.id, "revert")
                                  : "")]),
    ["status", "priority", "suggestion", "", ""]);
  document.getElementById("jobs").innerHTML = table(
    (s.jobs || []).map(j =>
      [j.job_id, statusSpan(j.status),
       new Date(j.submitted_at * 1000).toLocaleTimeString(),
       ["queued", "running"].includes(j.status)
         ? actBtn("stop", j.job_id, "stop") : ""]),
    ["job", "status", "submitted", ""]);
  const ob = s.onboarding;
  document.getElementById("onboarding").innerHTML = !ob ? "" :
    ob.error ? `<p>onboarding source error: ${esc(ob.error)}</p>` :
    (ob.complete ? "<p>onboarding complete</p>"
                 : `<p>current step: <b>${esc(ob.current)}</b> — ` +
                   `${esc(ob.prompt || "")}</p>`) +
    table((ob.steps || []).map(st =>
      [st.name, st.done ? "done" : (st.optional ? "optional" : "pending"),
       String((ob.answers || {})[st.name] ?? "")]),
      ["step", "state", "answer"]);
}
refresh();
setInterval(refresh, 2500);
</script></body></html>
"""
