"""Extension tool servers: MCP-style stdio JSON-RPC clients.

Mirrors `common/mcpService.ts` (365) + `electron-main/mcpChannel.ts`
(398): external tool servers are child processes speaking JSON-RPC over
stdio (StdioClientTransport, mcpChannel.ts:13,:202); the client manages
lifecycle (_createClient :239, close/recreate on failure :144-151) and
bridges the servers' tools into the agent loop
(chatThreadService.ts:1096-1107).

Protocol (newline-delimited JSON-RPC 2.0, MCP-shaped):
  → {method: "initialize"}                        ← {result: {name, ...}}
  → {method: "tools/list"}                        ← {result: {tools: [...]}}
  → {method: "tools/call", params: {name, arguments}}  ← {result: ...}

Tools are namespaced ``<server>.<tool>`` when bridged, so extension tools
can never shadow builtin names.
"""

from __future__ import annotations

import dataclasses
import json
import subprocess
import threading
import time as _time
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class ExtensionTool:
    server: str
    name: str
    description: str = ""
    params: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def full_name(self) -> str:
        return f"{self.server}.{self.name}"


class ExtensionServerError(RuntimeError):
    """Application-level failure (a JSON-RPC error response)."""


class ExtensionTransportError(ExtensionServerError):
    """Transport failure (dead process, closed/unresponsive stream) — the
    only class that justifies a server restart."""


class ExtensionServer:
    """One stdio child process + JSON-RPC session."""

    def __init__(self, name: str, command: List[str], *,
                 timeout_s: float = 10.0):
        self.name = name
        self.command = command
        self.timeout_s = timeout_s
        self._proc: Optional[subprocess.Popen] = None
        self._lock = threading.Lock()
        self._next_id = 1
        self._rx_buf = b""          # bytes read past the current line
        self.tools: List[ExtensionTool] = []

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._proc = subprocess.Popen(
            self.command, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, bufsize=1)
        self._request("initialize", {"client": "senweaver_ide_tpu"})
        result = self._request("tools/list", {})
        self.tools = [
            ExtensionTool(server=self.name, name=t["name"],
                          description=t.get("description", ""),
                          params=t.get("inputSchema",
                                       t.get("params", {})))
            for t in result.get("tools", [])]

    @property
    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def restart(self) -> None:
        """close/recreate on failure (mcpChannel.ts:144-151)."""
        self.stop()
        self._rx_buf = b""
        self.start()

    def stop(self) -> None:
        if self._proc is not None:
            try:
                self._proc.terminate()
                self._proc.wait(timeout=2)
            except (OSError, subprocess.TimeoutExpired):
                self._proc.kill()
            self._proc = None

    # -- rpc ---------------------------------------------------------------
    def _read_line_with_timeout(self, deadline: float) -> str:
        """Deadline-bounded readline on the child's stdout — a wedged
        server must raise, not hang the agent loop with the lock held.
        Bytes past the newline stay in ``_rx_buf`` for the next line (a
        server may flush several lines at once)."""
        import os as _os
        import selectors as _selectors
        assert self._proc and self._proc.stdout
        if b"\n" in self._rx_buf:
            line, self._rx_buf = self._rx_buf.split(b"\n", 1)
            return line.decode(errors="replace")
        fd = self._proc.stdout.fileno()
        _os.set_blocking(fd, False)
        sel = _selectors.DefaultSelector()
        sel.register(fd, _selectors.EVENT_READ)
        try:
            while True:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    raise ExtensionTransportError(
                        f"{self.name}: no response within "
                        f"{self.timeout_s:.0f}s")
                if not sel.select(timeout=min(remaining, 0.2)):
                    continue
                data = _os.read(fd, 65536)
                if not data:
                    raise ExtensionTransportError(
                        f"{self.name}: server closed the stream")
                self._rx_buf += data
                if b"\n" in self._rx_buf:
                    line, self._rx_buf = self._rx_buf.split(b"\n", 1)
                    return line.decode(errors="replace")
        finally:
            sel.close()

    def _request(self, method: str, params: Any) -> Any:
        with self._lock:
            if not self.alive:
                raise ExtensionTransportError(
                    f"extension server {self.name} is not running")
            rid = self._next_id
            self._next_id += 1
            msg = json.dumps({"jsonrpc": "2.0", "id": rid,
                              "method": method, "params": params})
            assert self._proc and self._proc.stdin and self._proc.stdout
            try:
                self._proc.stdin.write(msg + "\n")
                self._proc.stdin.flush()
            except OSError as e:
                raise ExtensionTransportError(
                    f"{self.name}: io error: {e}")
            deadline = _time.monotonic() + self.timeout_s
            while True:
                line = self._read_line_with_timeout(deadline)
                try:
                    resp = json.loads(line)
                except json.JSONDecodeError:
                    continue     # stray log line on stdout: skip it
                if resp.get("id") != rid:
                    continue     # late response from a timed-out call
                if "error" in resp:
                    raise ExtensionServerError(
                        f"{self.name}: {resp['error'].get('message')}")
                return resp.get("result")

    def call_tool(self, tool: str, arguments: Dict[str, Any]) -> Any:
        return self._request("tools/call",
                             {"name": tool, "arguments": arguments})


class ExtensionToolRegistry:
    """Manages servers and bridges their tools into a ToolsService."""

    def __init__(self):
        self.servers: Dict[str, ExtensionServer] = {}

    def add_server(self, name: str, command: List[str]) -> ExtensionServer:
        server = ExtensionServer(name, command)
        server.start()
        self.servers[name] = server
        return server

    def remove_server(self, name: str) -> None:
        server = self.servers.pop(name, None)
        if server:
            server.stop()

    def all_tools(self) -> List[ExtensionTool]:
        return [t for s in self.servers.values() for t in s.tools]

    def call(self, full_name: str, arguments: Dict[str, Any]) -> Any:
        server_name, _, tool = full_name.partition(".")
        server = self.servers.get(server_name)
        if server is None:
            raise KeyError(f"unknown extension server: {server_name}")
        try:
            return server.call_tool(tool, arguments)
        except ExtensionTransportError:
            # One recreate attempt on TRANSPORT failure only (the
            # reference's close/recreate, mcpChannel.ts:144-151);
            # application error responses must not kill a healthy,
            # possibly stateful server.
            server.restart()
            return server.call_tool(tool, arguments)

    def close(self) -> None:
        for name in list(self.servers):
            self.remove_server(name)
