"""Tiered runtime configuration with live overrides + model gating.

Mirrors the reference's four config tiers (SURVEY.md §5):
(1) build-time product config (product.json senweaverApiConfig),
(2) persisted user settings (SenweaverSettingsService: per-feature model
    selection, chatMode, autoApprove map),
(3) live online config pushed at runtime with model-access gating
    (senweaverOnlineConfigContribution.ts:53-76 isOwnProviderEnabled),
(4) const tables (context/token_config.py, manager_types.py — already
    their own modules).

Resolution order: live override > user setting > build default.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Callable, Dict, List, Optional

# Tier 1: build-time defaults (the product.json analogue).
BUILD_DEFAULTS: Dict[str, Any] = {
    "chat_mode": "agent",
    "auto_approve": {"edits": True, "terminal": True, "MCP tools": True},
    "feature_models": {
        # Per-feature model selection (settings tier 2 overrides).
        "chat": "qwen2.5-coder-1.5b",
        "autocomplete": "qwen2.5-coder-1.5b",
        "quick_edit": "qwen2.5-coder-1.5b",
        "apply": "qwen2.5-coder-1.5b",
        "scm": "qwen2.5-coder-1.5b",
    },
    "rollout": {"num_slots": 8, "max_len": 4096},
    "train": {"learning_rate": 1e-5, "group_size": 8},
}


class RuntimeConfig:
    def __init__(self, *, settings_path: Optional[str] = None):
        self._settings_path = settings_path
        self._user: Dict[str, Any] = {}
        self._live: Dict[str, Any] = {}
        self._allowed_models: Optional[List[str]] = None   # None = all
        self._lock = threading.Lock()
        self._listeners: List[Callable[[], None]] = []
        if settings_path and os.path.exists(settings_path):
            try:
                with open(settings_path) as f:
                    self._user = json.load(f)
            except (OSError, json.JSONDecodeError):
                self._user = {}

    # -- resolution ("live > user > default") ------------------------------
    def get(self, dotted_key: str, default: Any = None) -> Any:
        with self._lock:
            for tier in (self._live, self._user, BUILD_DEFAULTS):
                v: Any = tier
                for part in dotted_key.split("."):
                    if not isinstance(v, dict) or part not in v:
                        v = _MISSING
                        break
                    v = v[part]
                if v is not _MISSING:
                    return v
            return default

    # -- tier 2: user settings --------------------------------------------
    def get_user(self, dotted_key: str, default: Any = None) -> Any:
        """Read the user tier ONLY (no live-tier shadowing) — for
        read-modify-write persistence where resolving through the live
        tier would copy transient pushed values into user settings."""
        with self._lock:
            v: Any = self._user
            for part in dotted_key.split("."):
                if not isinstance(v, dict) or part not in v:
                    return default
                v = v[part]
            return v

    def set_user(self, dotted_key: str, value: Any) -> None:
        with self._lock:
            d = self._user
            parts = dotted_key.split(".")
            for p in parts[:-1]:
                d = d.setdefault(p, {})
            d[parts[-1]] = value
            self._persist()
        self._notify()

    def _persist(self) -> None:
        if not self._settings_path:
            return
        tmp = self._settings_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(self._user, f, indent=2)
            os.replace(tmp, self._settings_path)
        except OSError:
            pass

    # -- tier 3: live online config ---------------------------------------
    def apply_live_config(self, config: Dict[str, Any]) -> None:
        """The WS-push path (senweaverOnlineConfigContribution): replaces
        the live tier atomically; 'allowed_models' gates model access."""
        with self._lock:
            self._live = dict(config)
            am = config.get("allowed_models")
            self._allowed_models = list(am) if am is not None else None
        self._notify()

    def is_model_allowed(self, model_name: str) -> bool:
        """Model-access gating (isOwnProviderEnabled semantics)."""
        with self._lock:
            if self._allowed_models is None:
                return True
            return any(a in model_name for a in self._allowed_models)

    def snapshot(self) -> Dict[str, Any]:
        """Consistent view of the live tier + gating (single lock hold)."""
        with self._lock:
            return {"live_keys": sorted(self._live.keys()),
                    "model_gating": (list(self._allowed_models)
                                     if self._allowed_models is not None
                                     else None)}

    # -- change notification ----------------------------------------------
    def on_change(self, fn: Callable[[], None]) -> None:
        self._listeners.append(fn)

    def _notify(self) -> None:
        for fn in list(self._listeners):
            try:
                fn()
            except Exception:
                pass


class _Missing:
    pass


_MISSING = _Missing()


class ModelAccessError(PermissionError):
    """A live-config gate (allowed_models) blocks this model."""


class GatedPolicyClient:
    """PolicyClient wrapper that honors live model-access gating.

    The reference enforces isOwnProviderEnabled at the point of use —
    a pushed config change affects the NEXT request, not a restart
    (``senweaverOnlineConfigContribution.ts:53-76``). Wrapping the policy
    client is the session-layer equivalent: every chat() re-checks the
    gate against the CURRENT live tier, so a ``config.push`` lands on a
    running trainer/session mid-rollout. The agent loop's error path
    turns a gated call into an errored episode (record_error → trace
    hasErrors) rather than a crash of the surrounding job."""

    def __init__(self, inner, config: "RuntimeConfig", *,
                 model_name: Optional[str] = None):
        self.inner = inner
        self.config = config
        self.model_name = model_name or getattr(inner, "model_name", "") \
            or "local-policy"

    def chat(self, messages, **kw):
        if not self.config.is_model_allowed(self.model_name):
            raise ModelAccessError(
                f"model '{self.model_name}' is gated by live config "
                f"(allowed_models={self.config.snapshot()['model_gating']})")
        return self.inner.chat(messages, **kw)

    def __getattr__(self, name):
        # call_log, release_held_slot, tokenizer … pass through so the
        # RL data pipeline sees the real client underneath.
        return getattr(self.inner, name)


def install_config_channel(server, config: "RuntimeConfig"):
    """Online-config push channel over the trainer's JSON-RPC socket.

    The reference pushes live config over a global WebSocket and reports
    model usage back on the same channel
    (browser/senweaverOnlineConfigContribution.ts:53-76
    isOwnProviderEnabled / sendModelUsageReport). Here the trainer's
    control server (runtime/control.py) IS the push channel: an operator
    (or the C++ senweaver-ctl CLI) can push overrides into the live tier
    at runtime without restarting training.

    Registers three methods and returns the usage-report sink:
      - ``config.push {..overrides.., allowed_models?}`` → replaces the
        live tier atomically (model gating included)
      - ``config.get {"key": dotted}`` → resolved value ("live > user >
        default"); no key → {"live_keys": [...], "model_gating": [...]}
      - ``config.usage_report {model, tokens, ...}`` → appended to the
        returned deque (the sendModelUsageReport analogue), bounded at
        1000 entries so a long-running trainer doesn't leak
    """
    from collections import deque
    usage_reports: Any = deque(maxlen=1000)

    def _push(params: Any) -> Dict[str, Any]:
        if not isinstance(params, dict):
            raise ValueError("config.push expects an object of overrides")
        config.apply_live_config(params)
        return {"ok": True, "keys": sorted(params.keys())}

    def _get(params: Any) -> Any:
        if isinstance(params, dict) and "key" in params:
            return config.get(str(params["key"]))
        return config.snapshot()

    def _usage(params: Any) -> Dict[str, Any]:
        if not isinstance(params, dict):
            raise ValueError("config.usage_report expects an object")
        usage_reports.append(dict(params))
        return {"ok": True, "count": len(usage_reports)}

    server.register("config.push", _push)
    server.register("config.get", _get)
    server.register("config.usage_report", _usage)
    return usage_reports
