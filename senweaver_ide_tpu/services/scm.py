"""SCM service: AI-generated git commit messages.

Counterpart of the reference's GenerateCommitMessageService
(browser/senweaverSCMService.ts, 230 LoC) + its main-process git helper
(electron-main/senweaverSCMMainService.ts). Semantics kept exactly:

- staged changes are preferred over the working tree when any exist
  (senweaverSCMMainService.ts hasStagedChanges)
- context = diff --stat, sampled per-file diffs of the top
  MAX_DIFF_FILES=10 files by added+removed lines with each diff capped
  at MAX_DIFF_LENGTH=8000 chars (unified=0), current branch, and the
  last 5 non-merge commits (%h|%s|%ad)
- the model answers in <output>/<reasoning> tags; the commit message is
  the <output> body (senweaverSCMService.ts onFinalMessage regex)

The prompt texts are ported as semantic fixtures
(prompts.ts:1724 gitCommitMessage_systemMessage, :1770
gitCommitMessage_userMessage) — same category as the APO gradient
prompts SURVEY.md §7 step 4 mandates porting verbatim.
"""

from __future__ import annotations

import re
import subprocess
from typing import List, Optional, Tuple

from ..agents.llm import ChatMessage, PolicyClient

MAX_DIFF_LENGTH = 8000    # senweaverSCMMainService.ts:19
MAX_DIFF_FILES = 10       # senweaverSCMMainService.ts:20

COMMIT_MESSAGE_SYSTEM = """\
You are an expert software engineer AI assistant responsible for writing \
clear and concise Git commit messages that summarize the **purpose** and \
**intent** of the change. Try to keep your commit messages to one \
sentence. If necessary, you can use two sentences.

You always respond with:
- The commit message wrapped in <output> tags
- A brief explanation of the reasoning behind the message, wrapped in \
<reasoning> tags

Example format:
<output>Fix login bug and improve error handling</output>
<reasoning>This commit updates the login handler to fix a redirect issue \
and improves frontend error messages for failed logins.</reasoning>

Do not include anything else outside of these tags.
Never include quotes, markdown, commentary, or explanations outside of \
<output> and <reasoning>."""


def commit_message_user_prompt(stat: str, sampled_diffs: str, branch: str,
                               log: str) -> str:
    """gitCommitMessage_userMessage (prompts.ts:1770)."""
    return f"""\
Based on the following Git changes, write a clear, concise commit message \
that accurately summarizes the intent of the code changes.

Section 1 - Summary of Changes (git diff --stat):

{stat}

Section 2 - Sampled File Diffs (Top changed files):

{sampled_diffs}

Section 3 - Current Git Branch:

{branch}

Section 4 - Last 5 Commits (excluding merges):

{log}"""


def extract_commit_message(full_text: str) -> str:
    """The <output> body (senweaverSCMService.ts onFinalMessage)."""
    m = re.search(r"<output>([\s\S]*?)</output>", full_text, re.I)
    return m.group(1).strip() if m else ""


class GitRepo:
    """Thin shell-out layer (the senweaverSCMMainService.ts role)."""

    def __init__(self, path: str):
        self.path = path

    def _git(self, *args: str) -> str:
        proc = subprocess.run(["git", *args], cwd=self.path,
                              capture_output=True, text=True, timeout=30)
        if proc.returncode != 0:
            raise RuntimeError(proc.stderr.strip()
                               or f"git {' '.join(args)} failed")
        return proc.stdout.strip()

    def has_staged_changes(self) -> bool:
        return bool(self._git("diff", "--staged", "--name-only"))

    def stat(self, staged: bool) -> str:
        return self._git("diff", "--stat",
                         *(["--staged"] if staged else []))

    def numstat(self, staged: bool) -> List[Tuple[str, int]]:
        """[(file, added+removed)] for changed files."""
        out = self._git("diff", "--numstat",
                        *(["--staged"] if staged else []))
        rows: List[Tuple[str, int]] = []
        for line in out.split("\n"):
            parts = line.split("\t")
            if len(parts) != 3:
                continue
            added = int(parts[0]) if parts[0].isdigit() else 0
            removed = int(parts[1]) if parts[1].isdigit() else 0
            rows.append((parts[2], added + removed))
        return rows

    def sampled_diff(self, file: str, staged: bool) -> str:
        diff = self._git("diff", "--unified=0", "--no-color",
                         *(["--staged"] if staged else []), "--", file)
        return diff[:MAX_DIFF_LENGTH]

    def branch(self) -> str:
        return self._git("branch", "--show-current")

    def log(self) -> str:
        return self._git("log", "--pretty=format:%h|%s|%ad",
                         "--date=short", "--no-merges", "-n", "5")


class SCMService:
    """generateCommitMessage over the local policy (or any PolicyClient)."""

    def __init__(self, client: PolicyClient):
        self.client = client

    def gather_context(self, repo: GitRepo) -> Tuple[str, str, str, str]:
        staged = repo.has_staged_changes()
        stat = repo.stat(staged)
        top = sorted(repo.numstat(staged), key=lambda fc: -fc[1])
        top = top[:MAX_DIFF_FILES]
        sampled = "\n\n".join(
            f"==== {file} ====\n{repo.sampled_diff(file, staged)}"
            for file, _count in top)
        try:
            log = repo.log()
        except RuntimeError:     # repo with no commits yet
            log = ""
        return stat, sampled, repo.branch(), log

    def generate_commit_message(self, repo_path: str, *,
                                temperature: float = 0.0) -> str:
        repo = GitRepo(repo_path)
        stat, sampled, branch, log = self.gather_context(repo)
        if not stat:
            raise RuntimeError("no changes to describe (clean tree)")
        resp = self.client.chat(
            [ChatMessage("system", COMMIT_MESSAGE_SYSTEM),
             ChatMessage("user", commit_message_user_prompt(
                 stat, sampled, branch, log))],
            temperature=temperature)
        message = extract_commit_message(resp.text)
        if not message:
            raise RuntimeError(
                "model response carried no <output> commit message")
        return message
