"""Collaboration channel: device-code rooms over a local TCP coordinator.

TPU-build counterpart of the reference's RemoteCollaborationService
(browser/remoteCollaborationService.ts, 1612 LoC): WebRTC P2P remote
control with WS signaling rooms keyed by device codes (:52), 30 s
heartbeats, ≤5 reconnect attempts, and an HTTP-polling fallback (:231).

Re-scoped for the trainer: instead of sharing an editor screen, a room
shares a live training/rollout session between processes — a trainer
host broadcasts progress events and accepts control messages (pause,
checkpoint-now, config nudges) from followers on the same machine or
over an SSH-forwarded port. The transport is line-delimited JSON over
TCP; semantics kept from the reference:

- rooms are keyed by short device codes a human can read over a shoulder
- participants heartbeat; silent peers are evicted after a timeout and
  the room is told (``peer_left``)
- clients auto-reconnect up to ``MAX_RECONNECTS`` times, then drop to
  POLLING mode: short-lived connections that drain their queue — the
  reference's HTTP-polling fallback
"""

from __future__ import annotations

import json
import secrets
import socket
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional

HEARTBEAT_INTERVAL_S = 30.0      # remoteCollaborationService.ts heartbeat
MAX_RECONNECTS = 5               # reconnect ceiling before polling fallback
ROOM_CODE_ALPHABET = "23456789ABCDEFGHJKMNPQRSTUVWXYZ"  # unambiguous
MAX_QUEUE = 1000


def _make_room_code() -> str:
    return "".join(secrets.choice(ROOM_CODE_ALPHABET) for _ in range(6))


class _Participant:
    def __init__(self, pid: str):
        self.pid = pid
        self.queue: Deque[Dict[str, Any]] = deque(maxlen=MAX_QUEUE)
        self.last_seen = time.time()
        self.conn: Optional[socket.socket] = None     # live push channel
        self.conn_lock = threading.Lock()

    def push(self, msg: Dict[str, Any]) -> None:
        """Push to the live connection if any; queue otherwise (the queue
        also backs the polling fallback)."""
        with self.conn_lock:
            conn = self.conn
            if conn is not None:
                try:
                    conn.sendall((json.dumps(msg) + "\n").encode())
                    return
                except OSError:
                    self.conn = None
        self.queue.append(msg)


class _Room:
    def __init__(self, code: str, host_pid: str):
        self.code = code
        self.host_pid = host_pid
        self.participants: Dict[str, _Participant] = {}
        self.created_at = time.time()


class CollabCoordinator:
    """The signaling/relay server (one per machine or per job)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 heartbeat_timeout_s: float = 3 * HEARTBEAT_INTERVAL_S):
        self._host = host
        self._port = port
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.rooms: Dict[str, _Room] = {}
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._running = False
        self._threads: List[threading.Thread] = []

    @property
    def address(self) -> tuple:
        assert self._sock is not None, "coordinator not started"
        return self._sock.getsockname()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self._host, self._port))
        self._sock.listen(16)
        self._sock.settimeout(0.2)
        self._running = True
        for target in (self._serve, self._reap):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._running = False
        for t in self._threads:
            t.join(timeout=2)
        if self._sock is not None:
            self._sock.close()

    # -- accept/serve ------------------------------------------------------
    def _serve(self) -> None:
        while self._running:
            try:
                conn, _ = self._sock.accept()   # type: ignore[union-attr]
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            t.start()

    def _handle(self, conn: socket.socket) -> None:
        part: Optional[_Participant] = None
        try:
            conn.settimeout(0.5)
            buf = b""
            while self._running:
                try:
                    chunk = conn.recv(65536)
                except socket.timeout:
                    continue
                except OSError:
                    break
                if not chunk:
                    break
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line.strip():
                        continue
                    part = self._dispatch(conn, line, part)
        finally:
            if part is not None:
                with part.conn_lock:
                    if part.conn is conn:
                        part.conn = None
            try:
                conn.close()
            except OSError:
                pass

    # -- protocol ----------------------------------------------------------
    def _dispatch(self, conn: socket.socket, raw: bytes,
                  part: Optional[_Participant]) -> Optional[_Participant]:
        try:
            req = json.loads(raw.decode(errors="replace"))
        except json.JSONDecodeError:
            self._reply(conn, {"type": "error", "error": "bad json"})
            return part
        if not isinstance(req, dict):
            # Valid JSON, wrong shape (e.g. a bare number): without this
            # guard the .get below raised and killed the CONNECTION
            # thread (found by the adversarial frame test).
            self._reply(conn, {"type": "error", "error": "bad request"})
            return part
        rid = req.get("id")
        op = req.get("op", "")
        pid = req.get("client_id", "")
        try:
            if op == "create_room":
                with self._lock:
                    code = _make_room_code()
                    while code in self.rooms:
                        code = _make_room_code()
                    room = _Room(code, pid)
                    p = room.participants[pid] = _Participant(pid)
                    self.rooms[code] = room
                if not req.get("polling"):
                    with p.conn_lock:
                        p.conn = conn
                self._reply(conn, {"type": "ok", "id": rid, "room": code})
                return p
            if op == "join_room":
                room = self._required_room(req)
                with self._lock:
                    p = room.participants.get(pid)
                    if p is None:
                        p = room.participants[pid] = _Participant(pid)
                p.last_seen = time.time()
                if not req.get("polling"):
                    with p.conn_lock:
                        p.conn = conn
                self._relay(room, pid, {"type": "peer_joined", "peer": pid})
                self._reply(conn, {"type": "ok", "id": rid,
                                   "room": room.code,
                                   "peers": sorted(room.participants)})
                return p
            if op == "send":
                room = self._required_room(req)
                self._touch(room, pid, conn, req)
                self._relay(room, pid, {"type": "message", "from": pid,
                                        "payload": req.get("payload")})
                self._reply(conn, {"type": "ok", "id": rid})
                return part
            if op == "poll":
                room = self._required_room(req)
                p = self._touch(room, pid, conn, req)
                msgs: List[Dict[str, Any]] = []
                while p.queue:
                    msgs.append(p.queue.popleft())
                self._reply(conn, {"type": "ok", "id": rid,
                                   "messages": msgs})
                return part
            if op == "heartbeat":
                room = self._required_room(req)
                self._touch(room, pid, conn, req)
                self._reply(conn, {"type": "ok", "id": rid})
                return part
            if op == "leave":
                room = self._required_room(req)
                with self._lock:
                    room.participants.pop(pid, None)
                    empty = not room.participants
                    if empty:
                        self.rooms.pop(room.code, None)
                if not empty:
                    self._relay(room, pid, {"type": "peer_left",
                                            "peer": pid})
                self._reply(conn, {"type": "ok", "id": rid})
                return part
            raise ValueError(f"unknown op: {op}")
        except KeyError as e:
            self._reply(conn, {"type": "error", "id": rid,
                               "error": f"unknown room: {e}"})
        except Exception as e:
            self._reply(conn, {"type": "error", "id": rid,
                               "error": f"{type(e).__name__}: {e}"})
        return part

    def _room(self, code: str) -> _Room:
        with self._lock:
            room = self.rooms.get(code)
        if room is None:
            raise KeyError(code)
        return room

    def _required_room(self, req: Dict[str, Any]) -> _Room:
        code = req.get("room")
        if not code:
            # Distinct from "unknown room": the request itself is malformed.
            raise ValueError("missing 'room' field")
        return self._room(code)

    def _touch(self, room: _Room, pid: str,
               conn: Optional[socket.socket] = None,
               req: Optional[Dict[str, Any]] = None) -> _Participant:
        """Refresh liveness; transparently re-admit an evicted participant.

        If a heartbeat-evicted peer keeps talking over its still-open
        persistent connection, it is re-created here — and must get its
        push channel back (conn) plus a peer_joined broadcast, or every
        later relay would silently queue server-side while the client
        believes it is in push mode.
        """
        with self._lock:
            p = room.participants.get(pid)
            readmitted = p is None
            if readmitted:
                p = room.participants[pid] = _Participant(pid)
        p.last_seen = time.time()
        if readmitted:
            if conn is not None and not (req or {}).get("polling"):
                with p.conn_lock:
                    p.conn = conn
            self._relay(room, pid, {"type": "peer_joined", "peer": pid,
                                    "reason": "readmitted"})
        return p

    def _relay(self, room: _Room, sender: str, msg: Dict[str, Any]) -> None:
        with self._lock:
            targets = [p for pid, p in room.participants.items()
                       if pid != sender]
        for p in targets:
            p.push(msg)

    @staticmethod
    def _reply(conn: socket.socket, msg: Dict[str, Any]) -> None:
        try:
            conn.sendall((json.dumps(msg) + "\n").encode())
        except OSError:
            pass

    # -- liveness ----------------------------------------------------------
    def _reap(self) -> None:
        while self._running:
            time.sleep(min(1.0, self.heartbeat_timeout_s / 4))
            now = time.time()
            with self._lock:
                dead = [(room, pid, p)
                        for room in self.rooms.values()
                        for pid, p in room.participants.items()
                        if now - p.last_seen > self.heartbeat_timeout_s]
                for room, pid, _ in dead:
                    room.participants.pop(pid, None)
            for room, pid, _ in dead:
                self._relay(room, pid, {"type": "peer_left", "peer": pid,
                                        "reason": "heartbeat_timeout"})
            with self._lock:
                for code in [c for c, r in self.rooms.items()
                             if not r.participants]:
                    self.rooms.pop(code, None)


class CollabSession:
    """A participant: trainer host or follower.

    Holds a persistent connection for push delivery; heartbeats on an
    interval; on connection loss retries up to ``max_reconnects`` times,
    then degrades to POLLING mode (short-lived connections draining the
    server-side queue).
    """

    def __init__(self, host: str, port: int, client_id: str, *,
                 heartbeat_interval_s: float = HEARTBEAT_INTERVAL_S,
                 max_reconnects: int = MAX_RECONNECTS,
                 on_message: Optional[Callable[[Dict[str, Any]], None]] = None):
        self._addr = (host, port)
        self.client_id = client_id
        self.heartbeat_interval_s = heartbeat_interval_s
        self.max_reconnects = max_reconnects
        self.on_message = on_message
        self.room: Optional[str] = None
        self.polling = False
        self.reconnects_used = 0
        self.events: Deque[Dict[str, Any]] = deque(maxlen=MAX_QUEUE)
        self._conn: Optional[socket.socket] = None
        self._conn_lock = threading.Lock()
        self._pending: Dict[int, Dict[str, Any]] = {}
        self._waiting: set = set()     # rids a _request still awaits
        self._pending_cv = threading.Condition()
        self._next_id = 1
        self._running = False
        self._reconnect_lock = threading.Lock()
        self._stop_event = threading.Event()
        self._threads: List[threading.Thread] = []

    # -- connection --------------------------------------------------------
    def connect(self) -> None:
        self._conn = socket.create_connection(self._addr, timeout=5)
        self._conn.settimeout(0.5)
        self._running = True
        self._stop_event.clear()
        if not self._threads:
            for target in (self._read_loop, self._heartbeat_loop):
                t = threading.Thread(target=target, daemon=True)
                t.start()
                self._threads.append(t)

    def close(self) -> None:
        self._running = False
        self._stop_event.set()
        for t in self._threads:
            t.join(timeout=2)
        self._threads = []
        with self._conn_lock:
            if self._conn is not None:
                try:
                    self._conn.close()
                except OSError:
                    pass
                self._conn = None

    # -- API ---------------------------------------------------------------
    def create_room(self) -> str:
        resp = self._request({"op": "create_room"})
        self.room = resp["room"]
        return self.room

    def join(self, room: str) -> List[str]:
        resp = self._request({"op": "join_room", "room": room})
        self.room = room
        return resp.get("peers", [])

    def send(self, payload: Any) -> None:
        assert self.room, "join a room first"
        self._request({"op": "send", "room": self.room, "payload": payload})

    def poll(self) -> List[Dict[str, Any]]:
        """Drain queued messages (polling fallback; also usable any time)."""
        assert self.room, "join a room first"
        resp = self._request({"op": "poll", "room": self.room})
        msgs = resp.get("messages", [])
        for m in msgs:
            self._deliver(m)
        return msgs

    def leave(self) -> None:
        """Best-effort: the room may already be gone (eviction/reap)."""
        if self.room:
            try:
                self._request({"op": "leave", "room": self.room})
            except (OSError, TimeoutError, RuntimeError):
                pass
            self.room = None

    # -- internals ---------------------------------------------------------
    def _request(self, req: Dict[str, Any],
                 _attempt: int = 0) -> Dict[str, Any]:
        req = dict(req)
        req["client_id"] = self.client_id
        if self.polling:
            return self._oneshot(req)
        with self._pending_cv:
            rid = self._next_id
            self._next_id += 1
            self._waiting.add(rid)
        req["id"] = rid
        line = (json.dumps(req) + "\n").encode()
        try:
            with self._conn_lock:
                conn = self._conn
                if conn is None:
                    raise OSError("not connected")
                conn.sendall(line)
        except OSError:
            with self._pending_cv:
                self._waiting.discard(rid)
            self._handle_disconnect(conn)
            # Bounded per-request retries: a flapping coordinator that
            # accepts then drops each connection would otherwise recurse
            # forever (each successful reconnect restores the outage
            # budget, so that alone never terminates this loop).
            if _attempt + 1 >= max(self.max_reconnects, 1):
                raise OSError(
                    f"request {req.get('op')!r} failed after "
                    f"{_attempt + 1} attempts")
            return self._request({k: v for k, v in req.items()
                                  if k not in ("id", "client_id")},
                                 _attempt + 1)
        with self._pending_cv:
            try:
                deadline = time.time() + 5
                while rid not in self._pending:
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"no response for {req.get('op')}")
                    self._pending_cv.wait(remaining)
                resp = self._pending.pop(rid)
            finally:
                # Abandoned rid: the read loop must drop (not store) a
                # reply that straggles in after this timeout/raise.
                self._waiting.discard(rid)
                self._pending.pop(rid, None)
        if resp.get("type") == "error":
            raise RuntimeError(resp.get("error", "collab error"))
        return resp

    def _oneshot(self, req: Dict[str, Any]) -> Dict[str, Any]:
        """Polling fallback: one short-lived connection per request."""
        req = dict(req)
        req["polling"] = True
        with socket.create_connection(self._addr, timeout=5) as c:
            c.sendall((json.dumps(req) + "\n").encode())
            c.settimeout(5)
            buf = b""
            while b"\n" not in buf:
                chunk = c.recv(65536)
                if not chunk:
                    break
                buf += chunk
        line = buf.split(b"\n", 1)[0].strip()
        if not line:
            # Coordinator closed without replying (e.g. mid-shutdown).
            # Surface as OSError so best-effort callers' catches apply.
            raise OSError("no reply from coordinator")
        try:
            resp = json.loads(line.decode(errors="replace"))
        except json.JSONDecodeError as e:
            raise OSError(f"malformed reply from coordinator: {e}") from e
        if resp.get("type") == "error":
            raise RuntimeError(resp.get("error", "collab error"))
        return resp

    def _read_loop(self) -> None:
        buf = b""
        last_conn: Optional[socket.socket] = None
        while self._running:
            with self._conn_lock:
                conn = self._conn
            if conn is not last_conn:
                # New transport: a partial line from the dead socket must
                # not prefix (and corrupt) the first reply on this one.
                buf = b""
                last_conn = conn
            if conn is None:
                if self.polling:
                    return
                time.sleep(0.05)
                continue
            try:
                chunk = conn.recv(65536)
            except socket.timeout:
                continue
            except OSError:
                self._handle_disconnect(conn)
                continue
            if not chunk:
                self._handle_disconnect(conn)
                continue
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                if not line.strip():
                    continue
                try:
                    msg = json.loads(line.decode(errors="replace"))
                except json.JSONDecodeError:
                    continue
                if "id" in msg and msg["id"] is not None:
                    with self._pending_cv:
                        if msg["id"] in self._waiting:
                            self._pending[msg["id"]] = msg
                            self._pending_cv.notify_all()
                        # else: straggler reply for an abandoned request
                elif msg.get("type") not in ("ok", "error"):
                    # id-less ok/error replies come from fire-and-forget
                    # rejoins after a reconnect — not room traffic.
                    self._deliver(msg)

    def _deliver(self, msg: Dict[str, Any]) -> None:
        self.events.append(msg)
        if self.on_message is not None:
            try:
                self.on_message(msg)
            except Exception:
                pass

    def _heartbeat_loop(self) -> None:
        while self._running:
            # Event-based wait so close() interrupts a 30 s sleep instantly.
            if self._stop_event.wait(self.heartbeat_interval_s):
                return
            if not self._running or not self.room:
                continue
            try:
                self._request({"op": "heartbeat", "room": self.room})
            except (OSError, TimeoutError, RuntimeError):
                pass

    def _handle_disconnect(self, failed: Optional[socket.socket] = None
                           ) -> None:
        """Reconnect with rejoin, ≤max_reconnects, else polling fallback.

        Idempotent per failed connection: the read loop and a sender can
        both observe the same dead socket, but only the first caller acts
        — a later caller whose ``failed`` socket is no longer current
        must NOT close the healthy replacement connection.

        The rejoin is fire-and-forget (no id): this may run on the read
        loop's own thread, which cannot simultaneously wait for the
        response it is responsible for delivering.
        """
        with self._reconnect_lock:
            with self._conn_lock:
                if self._conn is not failed:
                    return            # already handled by another thread
                if self._conn is not None:
                    try:
                        self._conn.close()
                    except OSError:
                        pass
                    self._conn = None
            if self.polling:
                return
            while (self._running and not self._stop_event.is_set()
                   and self.reconnects_used < self.max_reconnects):
                self.reconnects_used += 1
                try:
                    conn = socket.create_connection(self._addr, timeout=2)
                    conn.settimeout(0.5)
                    if self.room:
                        conn.sendall((json.dumps(
                            {"op": "join_room", "room": self.room,
                             "client_id": self.client_id}) + "\n").encode())
                    with self._conn_lock:
                        self._conn = conn
                    # The budget is per outage, not per session lifetime:
                    # a successful reconnect restores the full allowance.
                    self.reconnects_used = 0
                    return
                except (OSError, TimeoutError):
                    time.sleep(0.1 * self.reconnects_used)
            self.polling = True   # degraded mode; poll() still works
