"""PerformanceMonitor: threshold-checked stage timings + profiler capture.

The trainer-side analogue of ``common/performanceMonitor.ts`` (271 LoC;
DEFAULT_THRESHOLDS :46 — system-message prep 2 s / 4k tokens): named
stages are timed, compared against thresholds, and over-threshold events
are captured to MetricsService as warnings. The TPU addition is
:func:`profile_capture` — a ``jax.profiler.trace`` context producing a
TensorBoard-loadable device trace of any monitored region (SURVEY.md §5
asks for jax.profiler hookup, which r1 lacked).
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Iterator, Optional

# Reference thresholds (performanceMonitor.ts:46-50) + trainer-side ones.
DEFAULT_THRESHOLDS_MS: Dict[str, float] = {
    "system_message_prep": 2_000.0,    # ref: sysmsg build 2 s
    "message_fitting": 2_000.0,
    "rollout_collect": 600_000.0,      # a full collection phase
    "batch_build": 5_000.0,
    "train_step": 300_000.0,
}
DEFAULT_TOKEN_THRESHOLDS: Dict[str, int] = {
    "system_message_tokens": 4_000,    # ref: sysmsg 4k tokens
}


class PerformanceMonitor:
    """Stage timing with threshold warnings, metric-bridged.

    Every recorded stage lands in the ``obs.MetricsRegistry`` plane:
    ``senweaver_stage_ms{stage=...}`` histograms and a
    ``senweaver_perf_warnings_total{stage=...}`` counter. ``registry``
    defaults to the PROCESS-GLOBAL registry (``obs.get_registry()``) so
    there is ONE exporter — the monitor's legacy snapshot()/warnings
    surface and the /metrics endpoint always describe the same data.
    Pass an explicit registry to bridge elsewhere, or ``registry=False``
    to keep a monitor off the metrics plane entirely. Instruments are
    cached at construction (the documented ``_reset_for_tests``
    contract: bridged monitors keep their registry by design)."""

    def __init__(self, metrics=None,
                 thresholds_ms: Optional[Dict[str, float]] = None,
                 token_thresholds: Optional[Dict[str, int]] = None,
                 registry=None):
        self.metrics = metrics
        self.thresholds_ms = {**DEFAULT_THRESHOLDS_MS,
                              **(thresholds_ms or {})}
        self.token_thresholds = {**DEFAULT_TOKEN_THRESHOLDS,
                                 **(token_thresholds or {})}
        self.timings: Dict[str, float] = {}       # last value per stage
        self.warnings: list = []
        self._stage_hist = self._warn_counter = None
        if registry is None:
            from ..obs import get_registry
            registry = get_registry()
        if registry is not False:
            self._stage_hist = registry.histogram(
                "senweaver_stage_ms",
                "PerformanceMonitor stage wall times.",
                labelnames=("stage",))
            self._warn_counter = registry.counter(
                "senweaver_perf_warnings_total",
                "Stages observed over their threshold.",
                labelnames=("stage",))

    @contextlib.contextmanager
    def stage(self, name: str, **extra: Any) -> Iterator[None]:
        t0 = time.monotonic()
        try:
            yield
        finally:
            ms = (time.monotonic() - t0) * 1000.0
            self.record_ms(name, ms, **extra)

    def record_ms(self, name: str, ms: float, **extra: Any) -> None:
        self.timings[name] = ms
        if self._stage_hist is not None:
            self._stage_hist.observe(ms, stage=name)
        limit = self.thresholds_ms.get(name)
        if limit is not None and ms > limit:
            self._warn(name, ms, limit, "ms", extra)

    def record_tokens(self, name: str, tokens: int, **extra: Any) -> None:
        # Token stages land in timings too — snapshot() must show every
        # recorded stage, not silently omit the token-threshold ones.
        self.timings[name] = float(tokens)
        limit = self.token_thresholds.get(name)
        if limit is not None and tokens > limit:
            self._warn(name, float(tokens), float(limit), "tokens", extra)

    def _warn(self, name: str, value: float, limit: float, unit: str,
              extra: Dict[str, Any]) -> None:
        record = {"stage": name, "value": round(value, 1),
                  "threshold": limit, "unit": unit, **extra}
        self.warnings.append(record)
        del self.warnings[:-100]
        if self._warn_counter is not None:
            self._warn_counter.inc(stage=name)
        if self.metrics is not None:
            self.metrics.capture("Performance Threshold Exceeded", record)

    def snapshot(self) -> Dict[str, float]:
        return {k: round(v, 1) for k, v in self.timings.items()}


@contextlib.contextmanager
def profile_capture(log_dir: Optional[str]) -> Iterator[None]:
    """``jax.profiler.trace`` over the wrapped region when ``log_dir`` is
    set (no-op otherwise). The trace is TensorBoard-loadable and includes
    device timelines — the trainer's self-observability hook."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield
