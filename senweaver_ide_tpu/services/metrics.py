"""MetricsService: event capture with opt-out + pluggable sink.

Mirrors `common/metricsService.ts` + `electron-main/metricsMainService.ts`
(162): ``capture(event, properties)`` flows to a sink (PostHog in the
reference, :30-40) unless the user opted out (OPT_OUT_KEY). Here the
default sink is a JSONL file; any callable(dict) works (e.g. a real
telemetry client).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional


class MetricsService:
    def __init__(self, sink: Optional[Callable[[Dict[str, Any]], None]]
                 = None, *, jsonl_path: Optional[str] = None,
                 opted_out: bool = False,
                 common_properties: Optional[Dict[str, Any]] = None):
        self._sink = sink
        self._jsonl_path = jsonl_path
        self.opted_out = opted_out
        self.common = dict(common_properties or {})
        self._lock = threading.Lock()
        self.captured_count = 0
        self._buffer: List[Dict[str, Any]] = []   # kept when no sink set

    def set_opt_out(self, opted_out: bool) -> None:
        self.opted_out = opted_out

    def capture(self, event: str,
                properties: Optional[Dict[str, Any]] = None) -> None:
        """Fire-and-forget: never raises into the caller
        (metricsMainService.ts catch-all)."""
        if self.opted_out:
            return
        record = {"event": event, "ts": time.time(),
                  **self.common, **(properties or {})}
        # Bookkeeping under the lock; sink/file I/O outside it — a slow
        # (or reentrant) sink must not serialize or deadlock capturers.
        with self._lock:
            self.captured_count += 1
            if self._sink is None and not self._jsonl_path:
                self._buffer.append(record)
                if len(self._buffer) > 10_000:
                    del self._buffer[:5_000]
                return
        try:
            if self._sink is not None:
                self._sink(record)
            elif self._jsonl_path:
                with open(self._jsonl_path, "a") as f:
                    f.write(json.dumps(record) + "\n")
        except Exception:
            pass

    def drain(self) -> List[Dict[str, Any]]:
        with self._lock:
            out, self._buffer = self._buffer, []
            return out


def load_jsonl_metrics(path: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    pass           # torn tail line (crash mid-write)
    return out
