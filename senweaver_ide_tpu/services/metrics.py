"""MetricsService: event capture with opt-out + pluggable sink.

Mirrors `common/metricsService.ts` + `electron-main/metricsMainService.ts`
(162): ``capture(event, properties)`` flows to a sink (PostHog in the
reference, :30-40) unless the user opted out (OPT_OUT_KEY). Here the
default sink is a JSONL file; any callable(dict) works (e.g. a real
telemetry client).

The JSONL sink keeps a cached append handle (flushed per capture so
tails/readers see live data; ``close()`` releases it) instead of
reopening the file per event, and an optional ``registry``
(obs.MetricsRegistry) additionally counts every capture into
``senweaver_events_total{event=...}`` — the bridge that lets legacy
captures show up on the new ``/metrics`` endpoint.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional


class MetricsService:
    def __init__(self, sink: Optional[Callable[[Dict[str, Any]], None]]
                 = None, *, jsonl_path: Optional[str] = None,
                 opted_out: bool = False,
                 common_properties: Optional[Dict[str, Any]] = None,
                 registry=None):
        self._sink = sink
        self._jsonl_path = jsonl_path
        self.opted_out = opted_out
        self.common = dict(common_properties or {})
        self._lock = threading.Lock()
        self.captured_count = 0
        self._buffer: List[Dict[str, Any]] = []   # kept when no sink set
        # Cached append handle for the JSONL sink — opened lazily on
        # first capture, flushed per event, closed via close(). Its own
        # lock so slow disk I/O never serializes capture bookkeeping.
        self._fh = None
        self._io_lock = threading.Lock()
        self._events_counter = None
        if registry is not None:
            self._events_counter = registry.counter(
                "senweaver_events_total",
                "Events captured by MetricsService.",
                labelnames=("event",))

    def set_opt_out(self, opted_out: bool) -> None:
        self.opted_out = opted_out

    def capture(self, event: str,
                properties: Optional[Dict[str, Any]] = None) -> None:
        """Fire-and-forget: never raises into the caller
        (metricsMainService.ts catch-all)."""
        if self.opted_out:
            return
        record = {"event": event, "ts": time.time(),
                  **self.common, **(properties or {})}
        # Bookkeeping under the lock; sink/file I/O outside it — a slow
        # (or reentrant) sink must not serialize or deadlock capturers.
        with self._lock:
            self.captured_count += 1
            buffered = self._sink is None and not self._jsonl_path
            if buffered:
                self._buffer.append(record)
                if len(self._buffer) > 10_000:
                    del self._buffer[:5_000]
        try:
            if self._events_counter is not None:
                self._events_counter.inc(event=event)
            if buffered:
                return
            if self._sink is not None:
                self._sink(record)
            elif self._jsonl_path:
                self._write_jsonl(record)
        except Exception:
            pass

    def _write_jsonl(self, record: Dict[str, Any]) -> None:
        with self._io_lock:
            if self._fh is None:
                self._fh = open(self._jsonl_path, "a")
            self._fh.write(json.dumps(record) + "\n")
            self._fh.flush()

    def close(self) -> None:
        """Release the cached JSONL handle (captures after close simply
        reopen it)."""
        with self._io_lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None

    def __enter__(self) -> "MetricsService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def drain(self) -> List[Dict[str, Any]]:
        with self._lock:
            out, self._buffer = self._buffer, []
            return out


def load_jsonl_metrics(path: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    pass           # torn tail line (crash mid-write)
    return out
