"""SkillService: named, on-demand instruction bundles.

Mirrors `common/skillService.ts` (522 LoC): skills live either in a
``skills.json`` config (name → {description, content}) or as
``<dir>/<name>/SKILL.md`` files (:99-100); the catalog (name +
description) is cheap and always available, full content loads on demand
when the policy calls the ``skill`` tool (:22-46). The catalog is rendered
into the system prompt; loading a skill injects its content into the
conversation as a tool result.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional

SKILL_FILE_NAME = "SKILL.md"
SKILLS_CONFIG_FILE_NAME = "skills.json"


@dataclasses.dataclass
class SkillInfo:
    """SkillInfo (skillService.ts:22-27)."""
    name: str
    description: str
    location: str = ""
    content: Optional[str] = None        # loaded on demand


class SkillService:
    def __init__(self, skills_dir: Optional[str] = None):
        self.skills_dir = skills_dir
        self._skills: Dict[str, SkillInfo] = {}
        self.error: Optional[str] = None
        if skills_dir:
            self.reload()

    # -- discovery ---------------------------------------------------------
    def reload(self) -> None:
        self._skills.clear()
        self.error = None
        d = self.skills_dir
        if not d or not os.path.isdir(d):
            return
        cfg = os.path.join(d, SKILLS_CONFIG_FILE_NAME)
        if os.path.exists(cfg):
            try:
                with open(cfg) as f:
                    data = json.load(f)
                for name, v in data.get("skills", {}).items():
                    self._skills[name] = SkillInfo(
                        name=name, description=v.get("description", ""),
                        location=cfg, content=v.get("content"))
            except (OSError, json.JSONDecodeError) as e:
                self.error = f"skills.json: {e}"
        for entry in sorted(os.listdir(d)):
            md = os.path.join(d, entry, SKILL_FILE_NAME)
            if os.path.isfile(md) and entry not in self._skills:
                desc = self._first_heading_line(md)
                self._skills[entry] = SkillInfo(name=entry,
                                                description=desc,
                                                location=md)

    @staticmethod
    def _first_heading_line(path: str) -> str:
        try:
            with open(path) as f:
                for line in f:
                    s = line.strip().lstrip("#").strip()
                    if s:
                        return s[:200]
        except OSError:
            pass
        return ""

    def register(self, name: str, description: str, content: str) -> None:
        """Programmatic registration (tests, in-memory skills)."""
        self._skills[name] = SkillInfo(name=name, description=description,
                                       content=content)

    # -- access ------------------------------------------------------------
    def get_all_skills(self) -> List[SkillInfo]:
        return list(self._skills.values())

    def get_skill(self, name: str) -> Optional[SkillInfo]:
        return self._skills.get(name)

    def load_skill_content(self, name: str) -> Optional[str]:
        """loadSkillContent (skillService.ts:68): lazy file read."""
        info = self._skills.get(name)
        if info is None:
            return None
        if info.content is None and info.location and \
                os.path.isfile(info.location):
            try:
                info.content = open(info.location).read()
            except OSError:
                return None
        return info.content

    # -- integration -------------------------------------------------------
    def catalog_for_prompt(self) -> str:
        """The catalog section for the system message."""
        if not self._skills:
            return ""
        lines = ["# Skills",
                 "Load a skill's full instructions with the skill tool:"]
        for s in self._skills.values():
            lines.append(f"- {s.name}: {s.description}")
        return "\n".join(lines)

    def tool_handler(self, params: Dict) -> Dict:
        """Handler for ToolsService.register_handler('skill', ...)."""
        name = params.get("name", "")
        content = self.load_skill_content(name)
        if content is None:
            known = ", ".join(self._skills) or "(none)"
            raise KeyError(f"unknown skill: {name}. Available: {known}")
        return {"name": name, "content": content}
