"""First-run onboarding: a resumable setup wizard for the trainer host.

The reference ships an onboarding surface that walks a new user through
provider keys, model choice, and feature opt-ins before the IDE is
usable (`browser/senweaverOnboarding*` — the last IDE-chrome row of
SURVEY §2.5 without a TPU-side analogue). Re-centered for this build,
onboarding is OPERATOR-facing: before a training/serving job is
launched, the host needs a validated workspace, a resolvable model
preset, a provider whose capabilities entry exists, and an accelerator
posture ("tpu" vs "cpu-only") — exactly the things that otherwise fail
deep inside a job with an opaque traceback.

Design:
  - A fixed ordered list of steps, each with a validator; answers land
    in ``RuntimeConfig``'s user tier (the same tier the IDE's settings
    UI writes) so every later subsystem reads them the normal way.
  - State (current step, answers, completion stamp) persists as JSON
    next to the settings file — the wizard is resumable across
    restarts, like the reference's onboarding local-storage state.
  - ``install_onboarding_channel`` exposes the whole flow over the
    trainer's JSON-RPC control socket: status/answer/skip/reset. The
    C++ senweaver-ctl CLI or the dashboard can drive it remotely.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

ONBOARDING_VERSION = 1


@dataclass(frozen=True)
class Step:
    name: str
    prompt: str
    # validate(value, service) -> normalized value; raises ValueError
    validate: Callable[[Any, "OnboardingService"], Any]
    config_key: Optional[str] = None     # user-tier destination
    optional: bool = False


def _v_workspace(value: Any, svc: "OnboardingService") -> str:
    path = os.path.abspath(os.path.expanduser(str(value)))
    os.makedirs(path, exist_ok=True)
    if not os.access(path, os.W_OK):
        raise ValueError(f"workspace {path!r} is not writable")
    return path


def _v_model(value: Any, svc: "OnboardingService") -> str:
    from ..models.config import PRESETS
    name = str(value)
    if name not in PRESETS:
        raise ValueError(f"unknown model preset {name!r}; "
                         f"available: {sorted(PRESETS)}")
    return name


def _v_provider(value: Any, svc: "OnboardingService") -> str:
    from ..models.capabilities import _DEFAULT, get_model_capabilities
    from ..transport.providers import PROVIDERS
    name = str(value)
    if name not in PROVIDERS:
        raise ValueError(f"unknown provider {name!r}; "
                         f"available: {sorted(PROVIDERS)}")
    default_model = PROVIDERS[name].default_model
    if default_model:
        # get_model_capabilities never raises — it falls back to a
        # generic 128k entry; identity-check against the fallback, same
        # as the provider conformance test, so a provider whose default
        # model has no real DB entry fails HERE, not deep inside a job
        if get_model_capabilities(default_model) is _DEFAULT:
            raise ValueError(
                f"provider {name!r} default model {default_model!r} has "
                f"no capabilities entry (models/capabilities.py)")
    return name


def _v_accelerator(value: Any, svc: "OnboardingService") -> str:
    mode = str(value)
    if mode not in ("tpu", "cpu"):
        raise ValueError("accelerator must be 'tpu' or 'cpu'")
    if mode == "tpu" and not svc.probe_accelerator():
        raise ValueError("accelerator probe failed: no non-CPU JAX "
                         "device reachable (wedged tunnel?); pick 'cpu' "
                         "or fix the platform and retry")
    return mode


def _v_metrics(value: Any, svc: "OnboardingService") -> bool:
    if isinstance(value, bool):
        return value
    s = str(value).lower()
    if s in ("true", "yes", "on", "1"):
        return True
    if s in ("false", "no", "off", "0"):
        return False
    raise ValueError("metrics opt-in must be a boolean")


STEPS: List[Step] = [
    Step("workspace", "Directory for job workspaces and traces",
         _v_workspace, config_key="workspace.root"),
    Step("model", "Policy model preset to train/serve",
         _v_model, config_key="model.preset"),
    Step("provider", "LLM provider for APO gradient/critique calls",
         _v_provider, config_key="transport.provider"),
    Step("accelerator", "Compute posture: 'tpu' (probed) or 'cpu'",
         _v_accelerator, config_key="runtime.accelerator"),
    Step("metrics", "Opt in to local metrics JSONL (true/false)",
         _v_metrics, config_key="metrics.enabled", optional=True),
]


class OnboardingService:
    """Drives the step list; persists progress; writes validated
    answers into the RuntimeConfig user tier."""

    def __init__(self, config, state_path: Optional[str] = None, *,
                 accelerator_probe: Optional[Callable[[], bool]] = None):
        self._config = config
        base = getattr(config, "_settings_path", None)
        self._state_path = state_path or (
            os.path.join(os.path.dirname(base), "onboarding.json")
            if base else os.path.abspath("onboarding.json"))
        self._probe = accelerator_probe
        self._state = self._load()

    # -- accelerator probe (injectable for hermetic tests) ---------------
    def probe_accelerator(self, timeout_s: float = 60.0) -> bool:
        """Probe in a KILLABLE SUBPROCESS, never in-process: a wedged
        accelerator tunnel hangs backend init forever inside C++, and
        this runs on the control server's single serve thread — an
        in-process jax.devices() there would wedge every subsequent RPC
        (the exact failure bench.py's subprocess probe exists for)."""
        if self._probe is not None:
            return bool(self._probe())
        import subprocess
        import sys
        code = ("import jax; "
                "raise SystemExit(0 if jax.devices()[0].platform != 'cpu' "
                "else 1)")
        try:
            return subprocess.run([sys.executable, "-c", code],
                                  capture_output=True,
                                  timeout=timeout_s).returncode == 0
        except Exception:
            return False

    # -- state ------------------------------------------------------------
    def _load(self) -> Dict[str, Any]:
        try:
            with open(self._state_path) as f:
                st = json.load(f)
            if (isinstance(st, dict)
                    and st.get("version") == ONBOARDING_VERSION):
                return st
        except Exception:
            pass
        return {"version": ONBOARDING_VERSION, "answers": {},
                "completed_at": None}

    def _save(self) -> None:
        tmp = self._state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._state, f, indent=1)
        os.replace(tmp, self._state_path)

    # -- wizard API --------------------------------------------------------
    @property
    def complete(self) -> bool:
        return self._state["completed_at"] is not None

    def current_step(self) -> Optional[Step]:
        for step in STEPS:
            if step.name not in self._state["answers"]:
                return step
        return None

    def status(self) -> Dict[str, Any]:
        cur = self.current_step()
        return {
            "complete": self.complete,
            "current": cur.name if cur else None,
            "prompt": cur.prompt if cur else None,
            "steps": [{"name": s.name, "optional": s.optional,
                       "done": s.name in self._state["answers"]}
                      for s in STEPS],
            "answers": dict(self._state["answers"]),
        }

    def answer(self, step_name: str, value: Any) -> Dict[str, Any]:
        step = next((s for s in STEPS if s.name == step_name), None)
        if step is None:
            raise ValueError(f"unknown onboarding step {step_name!r}")
        if value is None:
            # str(None) would validate as the literal answer "None"
            # (e.g. a workspace directory named None); a missing value
            # is a caller error, not an answer — skip() is the explicit
            # way to decline an optional step
            raise ValueError(f"step {step_name!r} requires a value")
        normalized = step.validate(value, self)
        self._state["answers"][step.name] = normalized
        if step.config_key is not None:
            self._config.set_user(step.config_key, normalized)
        self._maybe_complete()
        self._save()
        return self.status()

    def skip(self, step_name: str) -> Dict[str, Any]:
        step = next((s for s in STEPS if s.name == step_name), None)
        if step is None:
            raise ValueError(f"unknown onboarding step {step_name!r}")
        if not step.optional:
            raise ValueError(f"step {step_name!r} is required")
        self._state["answers"][step.name] = None
        self._maybe_complete()
        self._save()
        return self.status()

    def reset(self) -> None:
        self._state = {"version": ONBOARDING_VERSION, "answers": {},
                       "completed_at": None}
        self._save()

    def _maybe_complete(self) -> None:
        if all(s.name in self._state["answers"] for s in STEPS):
            self._state["completed_at"] = time.time()


def install_onboarding_channel(server, svc: OnboardingService) -> None:
    """Expose the wizard over the trainer's JSON-RPC control socket:
    onboarding.status / onboarding.answer {step, value} /
    onboarding.skip {step} / onboarding.reset."""

    def _status(params: Any) -> Dict[str, Any]:
        return svc.status()

    def _answer(params: Any) -> Dict[str, Any]:
        if not isinstance(params, dict) or "step" not in params:
            raise ValueError("onboarding.answer expects {step, value}")
        return svc.answer(str(params["step"]), params.get("value"))

    def _skip(params: Any) -> Dict[str, Any]:
        if not isinstance(params, dict) or "step" not in params:
            raise ValueError("onboarding.skip expects {step}")
        return svc.skip(str(params["step"]))

    def _reset(params: Any) -> Dict[str, Any]:
        svc.reset()
        return svc.status()

    server.register("onboarding.status", _status)
    server.register("onboarding.answer", _answer)
    server.register("onboarding.skip", _skip)
    server.register("onboarding.reset", _reset)
