"""Model-list refresh + user-defined API endpoints.

``RefreshModelService`` is the counterpart of the reference's
RefreshModelService (common/refreshModelService.ts, 222 LoC): poll an
openai-compatible provider's ``GET /models`` endpoint, keep a
per-provider state machine (init → refreshing → finished_success |
finished_error), notify listeners on change, and optionally auto-poll on
an interval — the mechanism the reference uses to discover locally
served models (Ollama / vLLM / LM Studio).

``CustomApiService`` is the counterpart of CustomApiService
(common/customApiService.ts, 216 LoC): user-defined openai-compatible
endpoints, persisted in the user config tier and registered as live
providers so the transport layer (transport/http_client.py) can drive
them by name.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional

from ..transport.providers import PROVIDERS, ProviderSettings, get_provider
from .config import RuntimeConfig

# Providers whose model list is meaningfully dynamic (locally served
# engines), mirroring the reference's refreshable set.
REFRESHABLE_PROVIDERS = (
    "ollama", "vllm", "lmstudio", "litellm", "openai-compatible")

STATE_INIT = "init"
STATE_REFRESHING = "refreshing"
STATE_SUCCESS = "finished_success"
STATE_ERROR = "finished_error"


def fetch_model_list(settings: ProviderSettings, *,
                     timeout_s: float = 5.0) -> List[str]:
    """GET ``{base_url}/models`` and return model ids.

    Accepts both the openai-compatible shape ``{"data": [{"id": ...}]}``
    and the bare ``{"models": [{"name"|"id": ...}]}`` shape some local
    engines return.
    """
    if not settings.base_url:
        raise ValueError(f"provider {settings.name} has no base_url")
    url = settings.base_url.rstrip("/") + "/models"
    req = urllib.request.Request(url, headers={"Accept": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        payload = json.loads(resp.read().decode("utf-8", errors="replace"))
    if isinstance(payload, list):            # bare-array shape
        entries = payload
    else:
        entries = payload.get("data") or payload.get("models") or []
    out: List[str] = []
    for e in entries:
        if isinstance(e, str):
            out.append(e)
        elif isinstance(e, dict):
            mid = e.get("id") or e.get("name")
            if mid:
                out.append(str(mid))
    return out


class RefreshModelService:
    """Per-provider model-list polling with a refresh state machine."""

    def __init__(self, *, fetcher: Optional[Callable[
            [ProviderSettings], List[str]]] = None):
        self._fetch = fetcher or fetch_model_list
        self._states: Dict[str, str] = {}
        self._models: Dict[str, List[str]] = {}
        self._errors: Dict[str, str] = {}
        self._listeners: List[Callable[[str], None]] = []
        self._lock = threading.Lock()
        self._timer: Optional[threading.Timer] = None
        self._auto_gen = 0
        self._auto_providers: List[str] = []
        self._interval_s = 0.0

    # -- state inspection --------------------------------------------------
    def state_of(self, provider: str) -> str:
        with self._lock:
            return self._states.get(provider, STATE_INIT)

    def models_of(self, provider: str) -> List[str]:
        with self._lock:
            return list(self._models.get(provider, []))

    def error_of(self, provider: str) -> Optional[str]:
        with self._lock:
            return self._errors.get(provider)

    def on_change(self, fn: Callable[[str], None]) -> None:
        self._listeners.append(fn)

    # -- refresh -----------------------------------------------------------
    def refresh(self, provider: str) -> List[str]:
        """Synchronously refresh one provider's model list."""
        settings = get_provider(provider)
        if settings is None:
            raise KeyError(f"unknown provider: {provider}")
        with self._lock:
            self._states[provider] = STATE_REFRESHING
        self._notify(provider)
        try:
            models = self._fetch(settings)
        except Exception as e:
            with self._lock:
                self._states[provider] = STATE_ERROR
                self._errors[provider] = f"{type(e).__name__}: {e}"
            self._notify(provider)
            return []
        with self._lock:
            self._states[provider] = STATE_SUCCESS
            self._models[provider] = list(models)
            self._errors.pop(provider, None)
        self._notify(provider)
        return list(models)

    def refresh_all(self,
                    providers: Optional[List[str]] = None) -> Dict[str, List[str]]:
        names = providers if providers is not None else [
            p for p in REFRESHABLE_PROVIDERS if p in PROVIDERS]
        return {name: self.refresh(name) for name in names}

    # -- auto-poll ---------------------------------------------------------
    # A generation counter makes start/stop race-free: each start_auto
    # invalidates every timer chain from earlier generations, so a slow
    # in-flight _tick from a previous chain cannot reschedule itself
    # alongside the new one.
    def start_auto(self, providers: List[str], interval_s: float) -> None:
        with self._lock:
            self._auto_gen = self._auto_gen + 1
            gen = self._auto_gen
            self._auto_providers = list(providers)
            self._interval_s = interval_s
            if self._timer is not None:
                self._timer.cancel()
        self._schedule(gen)

    def _schedule(self, gen: int) -> None:
        with self._lock:
            if gen != self._auto_gen:
                return
            self._timer = threading.Timer(self._interval_s, self._tick,
                                          args=(gen,))
            self._timer.daemon = True
            self._timer.start()

    def _tick(self, gen: int) -> None:
        with self._lock:
            if gen != self._auto_gen:
                return
            providers = list(self._auto_providers)
        for p in providers:
            try:
                self.refresh(p)
            except KeyError:
                pass
        self._schedule(gen)

    def stop_auto(self) -> None:
        with self._lock:
            self._auto_gen = self._auto_gen + 1
            t, self._timer = self._timer, None
        if t is not None:
            t.cancel()

    def _notify(self, provider: str) -> None:
        for fn in list(self._listeners):
            try:
                fn(provider)
            except Exception:
                pass


class CustomApiService:
    """User-defined openai-compatible endpoints.

    Endpoints are persisted under the ``custom_apis`` key of the user
    config tier and registered into the live provider registry under the
    name ``custom:{name}`` so `OpenAICompatClient("custom:x")` resolves.
    """

    PREFIX = "custom:"

    def __init__(self, config: Optional[RuntimeConfig] = None):
        self._config = config
        self._names: List[str] = []
        self._lock = threading.Lock()
        if config is not None:
            # User tier only: live-pushed endpoints are transient and must
            # not be resurrected from (or copied into) the settings file.
            stored = config.get_user("custom_apis", {}) or {}
            for name, spec in stored.items():
                if isinstance(spec, dict) and spec.get("base_url"):
                    self._register(name, spec)

    # -- CRUD --------------------------------------------------------------
    def add_endpoint(self, name: str, base_url: str, *,
                     api_key_env: str = "", default_model: str = "",
                     supports_fim: bool = False) -> ProviderSettings:
        if not name or not base_url:
            raise ValueError("custom endpoint needs a name and base_url")
        spec = {"base_url": base_url, "api_key_env": api_key_env,
                "default_model": default_model,
                "supports_fim": bool(supports_fim)}
        with self._lock:
            settings = self._register(name, spec)
            if self._config is not None:
                # Whole-dict write keyed off the USER tier (a dotted
                # set_user path would nest a name like "my.lab"; reading
                # the merged view would persist live-pushed endpoints).
                apis = dict(self._config.get_user("custom_apis", {}) or {})
                apis[name] = spec
                self._config.set_user("custom_apis", apis)
        return settings

    def remove_endpoint(self, name: str) -> None:
        with self._lock:
            PROVIDERS.pop(self.PREFIX + name, None)
            if name in self._names:
                self._names.remove(name)
            if self._config is not None:
                apis = dict(self._config.get_user("custom_apis", {}) or {})
                if name in apis:
                    del apis[name]
                    self._config.set_user("custom_apis", apis)

    def list_endpoints(self) -> List[str]:
        return list(self._names)

    def settings_of(self, name: str) -> Optional[ProviderSettings]:
        return PROVIDERS.get(self.PREFIX + name)

    def _register(self, name: str, spec: Dict[str, Any]) -> ProviderSettings:
        settings = ProviderSettings(
            self.PREFIX + name, "openai-compat",
            base_url=str(spec.get("base_url", "")),
            api_key_env=str(spec.get("api_key_env", "")),
            supports_fim=bool(spec.get("supports_fim", False)),
            default_model=str(spec.get("default_model", "")))
        PROVIDERS[settings.name] = settings
        if name not in self._names:
            self._names.append(name)
        return settings
