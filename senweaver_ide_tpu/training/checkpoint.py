"""Train-state checkpointing with deterministic resume.

The TPU analogue of the reference's persistence plane (SURVEY.md §5
Checkpoint/resume): where the reference shards threads/traces into
IStorageService and flushes on timers, the trainer persists
params/optimizer/step with Orbax (async-capable, sharding-aware) plus a
JSON metadata sidecar carrying the data-order cursor — so a resumed run
continues from the exact batch it stopped at (deterministic data order,
SURVEY.md §7 step 5).

Falls back to a pure-numpy .npz format when Orbax is unavailable; both
formats restore onto an arbitrary device mesh (restored arrays are
re-sharded by the caller's shardings).
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from .trainer import TrainState

_STEP_DIR_RE = re.compile(r"^step_(\d+)$")


def _meta_path(root: str, step: int) -> str:
    return os.path.join(root, f"step_{step}", "meta.json")


class CheckpointManager:
    """Directory-per-step checkpoints: <root>/step_N/{state, meta.json}.

    keep_last bounds disk use the way MAX_TRACES bounds the trace store
    (traceCollectorService.ts:219)."""

    def __init__(self, root: str, *, keep_last: int = 3,
                 use_orbax: Optional[bool] = None):
        self.root = root
        self.keep_last = keep_last
        os.makedirs(root, exist_ok=True)
        if use_orbax is None:
            try:
                import orbax.checkpoint  # noqa: F401
                use_orbax = True
            except Exception:
                use_orbax = False
        self.use_orbax = use_orbax

    # -- public ------------------------------------------------------------
    def save(self, state: TrainState, *,
             data_cursor: int = 0,
             extra_meta: Optional[Dict[str, Any]] = None) -> str:
        step = int(jax.device_get(state.step))
        step_dir = os.path.join(self.root, f"step_{step}")
        os.makedirs(step_dir, exist_ok=True)
        if self.use_orbax:
            self._save_orbax(step_dir, state)
        else:
            self._save_npz(step_dir, state)
        meta = {"step": step, "data_cursor": int(data_cursor),
                "format": "orbax" if self.use_orbax else "npz"}
        if extra_meta:
            meta.update(extra_meta)
        tmp = _meta_path(self.root, step) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, _meta_path(self.root, step))
        self._gc()
        return step_dir

    def latest_step(self) -> Optional[int]:
        steps = []
        for name in os.listdir(self.root):
            m = _STEP_DIR_RE.match(name)
            # Only complete checkpoints (meta written last) count.
            if m and os.path.exists(_meta_path(self.root, int(m.group(1)))):
                steps.append(int(m.group(1)))
        return max(steps) if steps else None

    def restore(self, template: TrainState,
                step: Optional[int] = None
                ) -> Tuple[TrainState, Dict[str, Any]]:
        """Restore into the structure of ``template`` (shapes/dtypes/tree
        must match). Returns (state, meta)."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.root}")
        step_dir = os.path.join(self.root, f"step_{step}")
        with open(_meta_path(self.root, step)) as f:
            meta = json.load(f)
        if meta.get("format") == "orbax":
            state = self._restore_orbax(step_dir, template)
        else:
            state = self._restore_npz(step_dir, template)
        return state, meta

    # -- orbax backend -----------------------------------------------------
    def _save_orbax(self, step_dir: str, state: TrainState) -> None:
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(os.path.join(step_dir, "state"),
                   jax.device_get(state._asdict()), force=True)

    def _restore_orbax(self, step_dir: str,
                       template: TrainState) -> TrainState:
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        restored = ckptr.restore(os.path.join(step_dir, "state"),
                                 item=jax.device_get(template._asdict()))
        # The optimizer is code, not checkpoint state — re-attach the
        # template's so restored states step with the right transform.
        return TrainState(**restored, opt=template.opt)

    # -- npz fallback ------------------------------------------------------
    @staticmethod
    def _flatten(state: TrainState):
        leaves, treedef = jax.tree_util.tree_flatten(state)
        return leaves, treedef

    def _save_npz(self, step_dir: str, state: TrainState) -> None:
        leaves, _ = self._flatten(state)
        arrays = {f"leaf_{i}": np.asarray(jax.device_get(x))
                  for i, x in enumerate(leaves)}
        tmp = os.path.join(step_dir, "state.npz.tmp")
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, os.path.join(step_dir, "state.npz"))

    def _restore_npz(self, step_dir: str,
                     template: TrainState) -> TrainState:
        leaves, treedef = self._flatten(template)
        with np.load(os.path.join(step_dir, "state.npz")) as data:
            restored = [data[f"leaf_{i}"] for i in range(len(leaves))]
        return jax.tree_util.tree_unflatten(treedef, restored)

    # -- gc ----------------------------------------------------------------
    def _gc(self) -> None:
        import shutil
        if not self.keep_last:
            return
        complete: list = []
        torn: list = []
        for name in os.listdir(self.root):
            m = _STEP_DIR_RE.match(name)
            if not m:
                continue
            s = int(m.group(1))
            (complete if os.path.exists(_meta_path(self.root, s))
             else torn).append(s)
        # Torn checkpoints (state written, meta.json never landed — a
        # crash/preemption mid-save) are garbage, not history: reclaim
        # them FIRST and never count them toward keep_last, so a torn
        # dir can't evict a complete checkpoint from the retention
        # budget. A torn dir NEWER than every complete step could be a
        # save in progress (async/concurrent saver), so it is spared.
        newest_complete = max(complete) if complete else None
        for s in torn:
            if newest_complete is not None and s <= newest_complete:
                shutil.rmtree(os.path.join(self.root, f"step_{s}"),
                              ignore_errors=True)
        for s in sorted(complete)[:-self.keep_last]:
            shutil.rmtree(os.path.join(self.root, f"step_{s}"),
                          ignore_errors=True)
