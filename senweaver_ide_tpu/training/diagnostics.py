"""On-device GRPO training-health diagnostics (the jitted head).

The GRPO foundations paper (PAPERS.md, 2606.29238) shows group-relative
advantages degenerate in exactly the regime the disaggregated fleet
makes cheap — large groups, long horizons: gradients go sparse, the
advantage matrix collapses in rank, and per-token credit concentrates
on a few positions. This module computes those statistics PER ROUND,
entirely on device, from the same host batch ``rl_loop`` is about to
place on the mesh:

- **advantage rank spectrum** — singular values of the group-by-position
  advantage matrix ``M[g, s] = mean over group g of adv_b * mask[b, s]``;
  reported as effective rank ``exp(H(sigma/sum sigma))``, its fraction of
  the attainable rank, and the participation ratio
  ``(sum s^2)^2 / sum s^4``;
- **per-token credit entropy** — normalized entropy of the |per-token
  advantage| mass over the response mask (1 = credit spread evenly,
  0 = all credit on one token);
- **zero/degenerate-group fraction** — groups whose finite rewards all
  tie (no learning signal), counted over groups actually PRESENT in the
  batch (group ids are task indices and survive group drops
  non-contiguously);
- **NaN safety** — non-finite rewards are excluded from every statistic
  and surfaced as ``nonfinite_reward_fraction`` instead of silently
  poisoning the std (the pre-PR-9 ``obs.advantage_stats`` failure mode).

Host-sync contract (analysis/jit_lint.py): :func:`dispatch_round_health`
only DISPATCHES the jitted head (async, overlaps with batch placement);
:func:`finalize_round_health` performs the round's single batched
``jax.device_get`` of the whole stats dict. Nothing in the traced path
reads device values back.

Gradient sparsity and the policy-entropy / KL-to-anchor drift signals
ride in the train step's own metrics (training/grpo.py
``grad_sparsity``; ``entropy`` / ``kl``) — ``rl_loop`` merges them into
the same health dict after the update, so the telemetry consumer
(obs/training_health.py) sees one flat record per round.
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp

from .grpo import GRPOConfig


class DiagnosticsConfig(NamedTuple):
    """Static (hashable) knobs of the jitted head. Mirror of the
    advantage transform actually fed to the loss, so the spectrum the
    detectors see is the spectrum the optimizer sees."""

    normalize_std: bool = True
    min_group_std: float = 1e-4
    leave_one_out: bool = False
    # A group is "zero-advantage" when its centered rewards all fall
    # within this RELATIVE tolerance of zero (scaled by 1 + |group
    # mean|, so reward magnitude doesn't change what counts as a tie).
    zero_adv_rtol: float = 1e-8

    @classmethod
    def from_grpo(cls, config: GRPOConfig) -> "DiagnosticsConfig":
        return cls(normalize_std=config.normalize_std,
                   min_group_std=config.min_group_std,
                   leave_one_out=config.leave_one_out)


@functools.partial(jax.jit, static_argnames=("num_groups", "config"))
def _diagnostics_head(rewards: jax.Array, group_ids: jax.Array,
                      mask: jax.Array, num_groups: int,
                      config: DiagnosticsConfig
                      ) -> Dict[str, jax.Array]:
    """All-device round health: NaN-safe advantage stats + rank
    spectrum + credit entropy. Returns a dict of f32 scalars; the
    caller performs the one batched device_get (finalize_round_health).

    ``num_groups`` and ``config`` are static — one recompile per
    distinct (group count, config) pair, same trade the train step
    already makes with its own static args."""
    eps = jnp.float32(1e-30)
    rewards = rewards.astype(jnp.float32)
    finite = jnp.isfinite(rewards)
    fin = finite.astype(jnp.float32)
    r = jnp.where(finite, rewards, 0.0)
    n = jnp.maximum(jnp.float32(rewards.shape[0]), 1.0)
    # Summing the 0/1 indicator directly keeps the fraction exactly 0.0
    # on clean batches (1 - sum(fin)/n rounds to -3e-8 in f32, which a
    # `> 0` nonfinite detector would trip on).
    nonfinite_fraction = jnp.sum(1.0 - fin) / n

    ones = jnp.ones_like(r)
    counts_all = jax.ops.segment_sum(ones, group_ids,
                                     num_segments=num_groups)
    counts_fin = jax.ops.segment_sum(fin, group_ids,
                                     num_segments=num_groups)
    present = counts_all > 0.0
    n_present = jnp.maximum(jnp.sum(present.astype(jnp.float32)), 1.0)

    sums = jax.ops.segment_sum(r * fin, group_ids,
                               num_segments=num_groups)
    means = sums / jnp.maximum(counts_fin, 1.0)
    centered = (r - means[group_ids]) * fin

    # Zero-advantage groups: every FINITE member ties (relative tol).
    absmax = jax.ops.segment_max(jnp.abs(centered), group_ids,
                                 num_segments=num_groups)
    absmax = jnp.where(present, absmax, 0.0)   # empty segments are -inf
    tie_tol = config.zero_adv_rtol * (1.0 + jnp.abs(means))
    zero_group = present & (absmax <= tie_tol)
    zero_group_fraction = (jnp.sum(zero_group.astype(jnp.float32))
                           / n_present)

    # The advantages actually fed to the loss (same transform chain as
    # training/grpo.py group_relative_advantages, over finite members).
    if config.leave_one_out:
        factor = counts_fin / jnp.maximum(counts_fin - 1.0, 1.0)
        adv = centered * factor[group_ids]
    elif config.normalize_std:
        sq = jax.ops.segment_sum(centered * centered, group_ids,
                                 num_segments=num_groups)
        std = jnp.sqrt(sq / jnp.maximum(counts_fin, 1.0))
        adv = centered / jnp.maximum(std[group_ids],
                                     config.min_group_std)
    else:
        adv = centered
    n_fin = jnp.maximum(jnp.sum(fin), 1.0)
    adv_mean = jnp.sum(adv) / n_fin
    adv_std = jnp.sqrt(jnp.sum(fin * (adv - adv_mean) ** 2) / n_fin)

    # Group-by-position advantage matrix -> singular spectrum.
    m = mask.astype(jnp.float32)
    tok_adv = adv[:, None] * m                          # (B, S)
    gsum = jax.ops.segment_sum(tok_adv, group_ids,
                               num_segments=num_groups)  # (G, S)
    mat = gsum / jnp.maximum(counts_all, 1.0)[:, None]
    sv = jnp.linalg.svd(mat, compute_uv=False)
    ssum = jnp.sum(sv)
    p = sv / jnp.maximum(ssum, eps)
    spec_entropy = -jnp.sum(p * jnp.log(jnp.maximum(p, eps)))
    # An all-zero matrix (no advantage signal at all) is maximally
    # collapsed: pin it to the 1-direction floor rather than NaN.
    effective_rank = jnp.where(ssum > eps, jnp.exp(spec_entropy), 1.0)
    sv2 = jnp.sum(sv * sv)
    participation = jnp.where(sv2 > eps,
                              (sv2 * sv2) / jnp.maximum(
                                  jnp.sum(sv ** 4), eps),
                              1.0)
    # Attainable rank: present groups x positions any trajectory masks.
    s_active = jnp.maximum(jnp.sum(jnp.any(m > 0.0, axis=0)
                                   .astype(jnp.float32)), 1.0)
    rank_fraction = effective_rank / jnp.maximum(
        jnp.minimum(n_present, s_active), 1.0)

    # Credit entropy: where does |advantage| mass sit across the
    # batch's masked tokens? Normalized by log(n_masked) to [0, 1].
    w = jnp.abs(tok_adv)
    wsum = jnp.sum(w)
    pw = w / jnp.maximum(wsum, eps)
    credit_h = -jnp.sum(pw * jnp.log(jnp.maximum(pw, eps)))
    n_masked = jnp.sum(m)
    credit_entropy = jnp.where(
        (wsum > eps) & (n_masked > 1.0),
        credit_h / jnp.log(jnp.maximum(n_masked, 2.0)), 0.0)

    return {
        "nonfinite_reward_fraction": nonfinite_fraction,
        "zero_advantage_group_fraction": zero_group_fraction,
        "groups_present": n_present,
        "advantage_mean": adv_mean,
        "advantage_std": adv_std,
        "effective_rank": effective_rank,
        "rank_fraction": rank_fraction,
        "participation_ratio": participation,
        "top_singular_value": jnp.max(sv),
        "credit_entropy": credit_entropy,
    }


def dispatch_round_health(rewards, group_ids, mask, *,
                          num_groups: Optional[int] = None,
                          config: DiagnosticsConfig = DiagnosticsConfig()
                          ) -> Dict[str, jax.Array]:
    """Dispatch the jitted head on HOST batch arrays (call before
    ``place_batch_for_mesh``; the result computes asynchronously while
    placement and the forward pass proceed). Returns the device dict —
    hand it to :func:`finalize_round_health` for the round's single
    batched sync."""
    import numpy as np
    g = np.asarray(group_ids)
    if num_groups is None:
        num_groups = int(g.max()) + 1 if g.size else 1
    return _diagnostics_head(
        jnp.asarray(rewards, jnp.float32), jnp.asarray(g, jnp.int32),
        jnp.asarray(mask), num_groups=int(num_groups), config=config)


def finalize_round_health(device_stats: Dict[str, jax.Array]
                          ) -> Dict[str, float]:
    """The round's ONE batched device→host sync: fetch the whole stats
    dict in a single ``jax.device_get`` and return plain floats."""
    host = jax.device_get(device_stats)
    return {k: float(v) for k, v in host.items()}


def advantage_stats(rewards, group_ids) -> Dict[str, float]:
    """NaN-safe GRPO advantage diagnostics from host reward/group
    arrays — the single implementation behind ``obs.advantage_stats``
    (kept shape-compatible: same three historical keys, plus the
    non-finite fraction the old numpy path silently swallowed).

    Group ids may be arbitrary hashables-as-ints (non-contiguous after
    group drops); they are densified before hitting the jitted head.
    ``advantage_std`` is the spread of the plain centered advantages,
    matching the historical contract."""
    import numpy as np
    r = np.asarray(rewards, dtype=np.float64).reshape(-1)
    g = np.asarray(group_ids).reshape(-1)
    if r.size == 0 or g.size != r.size:
        return {"zero_advantage_group_fraction": 0.0,
                "advantage_std": 0.0, "groups": 0,
                "nonfinite_reward_fraction": 0.0}
    uniq, codes = np.unique(g, return_inverse=True)
    out = finalize_round_health(dispatch_round_health(
        r, codes, np.ones((r.size, 1), dtype=bool),
        num_groups=len(uniq),
        config=DiagnosticsConfig(normalize_std=False)))
    return {
        "zero_advantage_group_fraction":
            out["zero_advantage_group_fraction"],
        "advantage_std": out["advantage_std"],
        "groups": int(len(uniq)),
        "nonfinite_reward_fraction": out["nonfinite_reward_fraction"],
    }
