"""LoRA adapters: low-rank GRPO fine-tuning that fits one chip.

Full-precision GRPO on a 6.7B policy needs ~13.4 GB bf16 weights +
~27 GB fp32-equivalent Adam moments + a full-size gradient tree — far
past one 16 GB v5e. LoRA freezes the base and trains rank-r factors on
the attention (and optionally MLP) matmuls: the gradient tree and
optimizer state shrink to the adapters (tens of MB at r=16), and with
an int8-quantized base (models/quantize.py) the whole setup — weights,
adapters, moments, activations — fits a single chip (QLoRA recipe,
TPU-first: the dequant epilogue lives inside ``transformer._dense``,
so the merged forward is one code path for full/int8/LoRA serving).

Mechanics:
  - ``init_lora(config, key, rank, targets)`` → adapter pytree shaped
    like the layer stack: ``{"layers": {"wq_lora_a": (L, in, r),
    "wq_lora_b": (L, r, out), ...}}``; B starts at zero so the adapted
    model EQUALS the base at init (the LoRA invariant).
  - ``merge_lora(base_params, lora)`` → params whose layers dict also
    carries the adapter leaves; ``transformer._dense`` applies
    ``y += (h @ A) @ B`` wherever they are present. The merge is a dict
    union — no weight materialization, scan-compatible (leading L).
  - ``train_step(..., lora_base=base)`` (training/trainer.py) treats
    ``state.params`` as the adapter tree: gradients and optimizer state
    exist ONLY for the adapters; the base is a closed-over constant.
  - ``materialize_lora(base, lora, config)`` folds A·B into the dense
    weights for publish/export (re-quantizing if the base was int8).

The alpha/rank scale is baked into A at init (A ~ N(0, 1/in)·alpha/r,
B = 0): the adapted function class is identical and no extra scale leaf
has to ride the scanned layer dict.
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..models.config import ModelConfig
from ..models.quantize import _quantize_matrix, is_quantized

# (in_dim, out_dim) resolvers per supported target matrix.
_TARGET_DIMS = {
    "wq": lambda c: (c.hidden_size, c.q_dim),
    "wk": lambda c: (c.hidden_size, c.kv_dim),
    "wv": lambda c: (c.hidden_size, c.kv_dim),
    "wo": lambda c: (c.q_dim, c.hidden_size),
    "w_gate": lambda c: (c.hidden_size, c.intermediate_size),
    "w_up": lambda c: (c.hidden_size, c.intermediate_size),
    "w_down": lambda c: (c.intermediate_size, c.hidden_size),
}

DEFAULT_TARGETS: Tuple[str, ...] = ("wq", "wk", "wv", "wo")


def init_lora(config: ModelConfig, key: jax.Array, *, rank: int = 16,
              alpha: float = None, targets: Sequence[str] = DEFAULT_TARGETS,
              ) -> Dict:
    """Adapter pytree; zero function delta at init (B = 0)."""
    if config.num_experts > 0:
        bad = {"w_gate", "w_up", "w_down"} & set(targets)
        if bad:
            raise ValueError(f"MoE expert banks are not LoRA targets "
                             f"(got {sorted(bad)}); use attention targets")
    alpha = 2.0 * rank if alpha is None else alpha
    L = config.num_layers
    layers: Dict[str, jax.Array] = {}
    keys = jax.random.split(key, len(targets))
    for t, k in zip(targets, keys):
        if t not in _TARGET_DIMS:
            raise ValueError(f"unknown LoRA target {t!r}; "
                             f"available: {sorted(_TARGET_DIMS)}")
        d_in, d_out = _TARGET_DIMS[t](config)
        scale = (alpha / rank) / float(d_in) ** 0.5
        layers[t + "_lora_a"] = (
            jax.random.normal(k, (L, d_in, rank), config.dtype)
            * jnp.asarray(scale, config.dtype))
        layers[t + "_lora_b"] = jnp.zeros((L, rank, d_out), config.dtype)
    return {"layers": layers}


def merge_lora(base_params: Dict, lora: Dict) -> Dict:
    """Params view with adapter leaves alongside the base layer stack —
    what ``forward`` consumes. Pure dict union (no array math)."""
    out = dict(base_params)
    out["layers"] = {**base_params["layers"], **lora["layers"]}
    return out


def split_lora(params: Dict) -> Tuple[Dict, Dict]:
    """Inverse of merge_lora: (base_params, lora)."""
    base, adapters = {}, {}
    for name, leaf in params["layers"].items():
        (adapters if "_lora_" in name else base)[name] = leaf
    out = {k: v for k, v in params.items() if k != "layers"}
    out["layers"] = base
    return out, {"layers": adapters}


def materialize_lora(base_params: Dict, lora: Dict,
                     config: ModelConfig) -> Dict:
    """Fold A·B into the dense weights → a plain param tree (publish /
    export path). An int8 base is dequantized per matrix, folded, and
    re-quantized, so a QLoRA-served engine keeps its representation."""
    out = dict(base_params)
    layers = dict(base_params["layers"])
    for name in list(lora["layers"]):
        if not name.endswith("_lora_a"):
            continue
        target = name[: -len("_lora_a")]
        a = lora["layers"][name]
        b = lora["layers"][target + "_lora_b"]
        delta = jnp.einsum("lir,lro->lio", a.astype(jnp.float32),
                           b.astype(jnp.float32))
        w = layers[target]
        if w.dtype == jnp.int8:
            scale = layers[target + "_scale"]          # (L, out)
            wf = w.astype(jnp.float32) * scale[:, None, :]
            layers[target], layers[target + "_scale"] = _quantize_matrix(
                (wf + delta).astype(config.dtype))
        else:
            layers[target] = (w.astype(jnp.float32) + delta).astype(w.dtype)
    out["layers"] = layers
    return out


def lora_param_count(lora: Dict) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(lora))


# HF module names for each target (PEFT adapter layout).
_PEFT_MODULES = {
    "wq": "self_attn.q_proj", "wk": "self_attn.k_proj",
    "wv": "self_attn.v_proj", "wo": "self_attn.o_proj",
    "w_gate": "mlp.gate_proj", "w_up": "mlp.up_proj",
    "w_down": "mlp.down_proj",
}

# Hub repo ids for the local presets — what a PEFT runtime needs in
# adapter_config.json to resolve the base checkpoint.
_HF_REPO_IDS = {
    "qwen2.5-coder-0.5b": "Qwen/Qwen2.5-Coder-0.5B",
    "qwen2.5-coder-1.5b": "Qwen/Qwen2.5-Coder-1.5B",
    "qwen2.5-coder-7b": "Qwen/Qwen2.5-Coder-7B",
    "qwen3-1.7b": "Qwen/Qwen3-1.7B",
    "qwen3-8b": "Qwen/Qwen3-8B",
    "deepseek-coder-1.3b": "deepseek-ai/deepseek-coder-1.3b-base",
    "deepseek-coder-6.7b": "deepseek-ai/deepseek-coder-6.7b-base",
    "mistral-7b": "mistralai/Mistral-7B-v0.1",
    "mixtral-8x7b": "mistralai/Mixtral-8x7B-v0.1",
    "llama-3.2-1b": "meta-llama/Llama-3.2-1B",
    "llama-3.1-8b": "meta-llama/Llama-3.1-8B",
}


def export_peft_adapter(lora: Dict, config: ModelConfig,
                        out_dir: str, *,
                        base_model: str = None) -> str:
    """Write adapters in the HF-PEFT layout (adapter_model.safetensors +
    adapter_config.json) so a GRPO-trained adapter drops into any
    PEFT-ecosystem runtime over the matching base checkpoint.

    The alpha/rank scale is baked into our A at init, so the exported
    config pins ``lora_alpha == r`` (scaling 1.0) — the folded product
    A·B is identical either way. PEFT stores lora_A as (r, in) and
    lora_B as (out, r) (torch Linear layout); ours are (in, r)/(r, out).
    """
    import json
    import os

    import numpy as np
    from safetensors.numpy import save_file

    os.makedirs(out_dir, exist_ok=True)
    tensors: Dict[str, "np.ndarray"] = {}
    rank = None
    targets = []
    for name, leaf in lora["layers"].items():
        if not name.endswith("_lora_a"):
            continue
        target = name[: -len("_lora_a")]
        targets.append(_PEFT_MODULES[target].rsplit(".", 1)[-1])
        # one device→host transfer per stacked tensor, sliced host-side
        a = np.asarray(lora["layers"][name], dtype=np.float32)
        b = np.asarray(lora["layers"][target + "_lora_b"],
                       dtype=np.float32)
        rank = int(a.shape[-1])
        for i in range(a.shape[0]):
            prefix = (f"base_model.model.model.layers.{i}."
                      f"{_PEFT_MODULES[target]}")
            tensors[prefix + ".lora_A.weight"] = np.ascontiguousarray(
                a[i].T)                                    # (r, in)
            tensors[prefix + ".lora_B.weight"] = np.ascontiguousarray(
                b[i].T)                                    # (out, r)
    if not tensors:
        # An adapter tree with no *_lora_a leaves would otherwise export
        # an empty safetensors + a config with r=null — unusable in any
        # PEFT runtime and silent until load time (ADVICE r3).
        raise ValueError("export_peft_adapter: no LoRA adapter leaves "
                         "found in lora['layers'] (expected *_lora_a/"
                         "*_lora_b pairs)")
    path = os.path.join(out_dir, "adapter_model.safetensors")
    save_file(tensors, path)
    with open(os.path.join(out_dir, "adapter_config.json"), "w") as f:
        json.dump({"peft_type": "LORA", "r": rank, "lora_alpha": rank,
                   "lora_dropout": 0.0, "bias": "none",
                   "base_model_name_or_path": (
                       base_model or _HF_REPO_IDS.get(config.name,
                                                      config.name)),
                   "target_modules": sorted(set(targets)),
                   "task_type": "CAUSAL_LM"}, f, indent=1)
    return path


def load_peft_adapter(adapter_dir: str, config: ModelConfig) -> Dict:
    """Read a PEFT-layout adapter dir back into our stacked tree.

    Scaling: PEFT applies ``lora_alpha / r`` at runtime; we bake it into
    A, so A is multiplied by that factor on load (round-trips exports
    from :func:`export_peft_adapter`, whose config pins the factor to 1).
    """
    import json
    import os

    import numpy as np
    from safetensors.numpy import load_file

    with open(os.path.join(adapter_dir, "adapter_config.json")) as f:
        meta = json.load(f)
    r = float(meta["r"])
    alpha = float(meta.get("lora_alpha", r))
    # PEFT's rsLoRA option scales by alpha/sqrt(r) instead of alpha/r
    scaling = alpha / (r ** 0.5) if meta.get("use_rslora") else alpha / r
    raw = load_file(os.path.join(adapter_dir, "adapter_model.safetensors"))
    module_to_target = {v: k for k, v in _PEFT_MODULES.items()}

    per_target: Dict[str, Dict[int, Dict[str, "np.ndarray"]]] = {}
    skipped = []
    for key, tensor in raw.items():
        # base_model.model.model.layers.{i}.<module>.lora_{A,B}.weight;
        # keys outside that pattern (modules_to_save tensors, adapters
        # on modules this architecture doesn't have) are skipped — a
        # partial load is reported, a fully-unusable one is an error.
        parts = key.split(".")
        if "layers" not in parts or parts[-2] not in ("lora_A", "lora_B"):
            skipped.append(key)
            continue
        li = parts.index("layers")
        module = ".".join(parts[li + 2:-2])
        target = module_to_target.get(module)
        if target is None:
            skipped.append(key)
            continue
        i = int(parts[li + 1])
        per_target.setdefault(target, {}).setdefault(i, {})[parts[-2]] = \
            tensor
    if not per_target:
        raise ValueError(
            f"no loadable LoRA tensors in {adapter_dir!r} (skipped "
            f"{len(skipped)} keys, e.g. {skipped[:3]}); supported "
            f"modules: {sorted(module_to_target)}")

    layers: Dict[str, jax.Array] = {}
    for target, rows in per_target.items():
        L = config.num_layers
        if sorted(rows) != list(range(L)):
            raise ValueError(f"adapter covers layers {sorted(rows)} but "
                             f"config {config.name!r} has {L}")
        d_in, d_out = _TARGET_DIMS[target](config)
        got = rows[0]["lora_A"].T.shape
        if got != (d_in, int(r)):
            # fail HERE with the offending module, not deep inside a
            # jitted einsum (models/load.py _take precedent)
            raise ValueError(
                f"adapter {target} lora_A shape {got} does not match "
                f"config {config.name!r} expectation ({d_in}, {int(r)})")
        a = jnp.stack([jnp.asarray(rows[i]["lora_A"].T) for i in range(L)])
        b = jnp.stack([jnp.asarray(rows[i]["lora_B"].T) for i in range(L)])
        layers[target + "_lora_a"] = (a * scaling).astype(config.dtype)
        layers[target + "_lora_b"] = b.astype(config.dtype)
    return {"layers": layers}


__all__ = ["DEFAULT_TARGETS", "export_peft_adapter", "init_lora",
           "load_peft_adapter", "lora_param_count", "materialize_lora",
           "merge_lora", "split_lora", "is_quantized"]
