"""LoRA adapters: low-rank GRPO fine-tuning that fits one chip.

Full-precision GRPO on a 6.7B policy needs ~13.4 GB bf16 weights +
~27 GB fp32-equivalent Adam moments + a full-size gradient tree — far
past one 16 GB v5e. LoRA freezes the base and trains rank-r factors on
the attention (and optionally MLP) matmuls: the gradient tree and
optimizer state shrink to the adapters (tens of MB at r=16), and with
an int8-quantized base (models/quantize.py) the whole setup — weights,
adapters, moments, activations — fits a single chip (QLoRA recipe,
TPU-first: the dequant epilogue lives inside ``transformer._dense``,
so the merged forward is one code path for full/int8/LoRA serving).

Mechanics:
  - ``init_lora(config, key, rank, targets)`` → adapter pytree shaped
    like the layer stack: ``{"layers": {"wq_lora_a": (L, in, r),
    "wq_lora_b": (L, r, out), ...}}``; B starts at zero so the adapted
    model EQUALS the base at init (the LoRA invariant).
  - ``merge_lora(base_params, lora)`` → params whose layers dict also
    carries the adapter leaves; ``transformer._dense`` applies
    ``y += (h @ A) @ B`` wherever they are present. The merge is a dict
    union — no weight materialization, scan-compatible (leading L).
  - ``train_step(..., lora_base=base)`` (training/trainer.py) treats
    ``state.params`` as the adapter tree: gradients and optimizer state
    exist ONLY for the adapters; the base is a closed-over constant.
  - ``materialize_lora(base, lora, config)`` folds A·B into the dense
    weights for publish/export (re-quantizing if the base was int8).

The alpha/rank scale is baked into A at init (A ~ N(0, 1/in)·alpha/r,
B = 0): the adapted function class is identical and no extra scale leaf
has to ride the scanned layer dict.
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..models.config import ModelConfig
from ..models.quantize import _quantize_matrix, is_quantized

# (in_dim, out_dim) resolvers per supported target matrix.
_TARGET_DIMS = {
    "wq": lambda c: (c.hidden_size, c.q_dim),
    "wk": lambda c: (c.hidden_size, c.kv_dim),
    "wv": lambda c: (c.hidden_size, c.kv_dim),
    "wo": lambda c: (c.q_dim, c.hidden_size),
    "w_gate": lambda c: (c.hidden_size, c.intermediate_size),
    "w_up": lambda c: (c.hidden_size, c.intermediate_size),
    "w_down": lambda c: (c.intermediate_size, c.hidden_size),
}

DEFAULT_TARGETS: Tuple[str, ...] = ("wq", "wk", "wv", "wo")


def init_lora(config: ModelConfig, key: jax.Array, *, rank: int = 16,
              alpha: float = None, targets: Sequence[str] = DEFAULT_TARGETS,
              ) -> Dict:
    """Adapter pytree; zero function delta at init (B = 0)."""
    if config.num_experts > 0:
        bad = {"w_gate", "w_up", "w_down"} & set(targets)
        if bad:
            raise ValueError(f"MoE expert banks are not LoRA targets "
                             f"(got {sorted(bad)}); use attention targets")
    alpha = 2.0 * rank if alpha is None else alpha
    L = config.num_layers
    layers: Dict[str, jax.Array] = {}
    keys = jax.random.split(key, len(targets))
    for t, k in zip(targets, keys):
        if t not in _TARGET_DIMS:
            raise ValueError(f"unknown LoRA target {t!r}; "
                             f"available: {sorted(_TARGET_DIMS)}")
        d_in, d_out = _TARGET_DIMS[t](config)
        scale = (alpha / rank) / float(d_in) ** 0.5
        layers[t + "_lora_a"] = (
            jax.random.normal(k, (L, d_in, rank), config.dtype)
            * jnp.asarray(scale, config.dtype))
        layers[t + "_lora_b"] = jnp.zeros((L, rank, d_out), config.dtype)
    return {"layers": layers}


def merge_lora(base_params: Dict, lora: Dict) -> Dict:
    """Params view with adapter leaves alongside the base layer stack —
    what ``forward`` consumes. Pure dict union (no array math)."""
    out = dict(base_params)
    out["layers"] = {**base_params["layers"], **lora["layers"]}
    return out


def split_lora(params: Dict) -> Tuple[Dict, Dict]:
    """Inverse of merge_lora: (base_params, lora)."""
    base, adapters = {}, {}
    for name, leaf in params["layers"].items():
        (adapters if "_lora_" in name else base)[name] = leaf
    out = {k: v for k, v in params.items() if k != "layers"}
    out["layers"] = base
    return out, {"layers": adapters}


def materialize_lora(base_params: Dict, lora: Dict,
                     config: ModelConfig) -> Dict:
    """Fold A·B into the dense weights → a plain param tree (publish /
    export path). An int8 base is dequantized per matrix, folded, and
    re-quantized, so a QLoRA-served engine keeps its representation."""
    out = dict(base_params)
    layers = dict(base_params["layers"])
    for name in list(lora["layers"]):
        if not name.endswith("_lora_a"):
            continue
        target = name[: -len("_lora_a")]
        a = lora["layers"][name]
        b = lora["layers"][target + "_lora_b"]
        delta = jnp.einsum("lir,lro->lio", a.astype(jnp.float32),
                           b.astype(jnp.float32))
        w = layers[target]
        if w.dtype == jnp.int8:
            scale = layers[target + "_scale"]          # (L, out)
            wf = w.astype(jnp.float32) * scale[:, None, :]
            layers[target], layers[target + "_scale"] = _quantize_matrix(
                (wf + delta).astype(config.dtype))
        else:
            layers[target] = (w.astype(jnp.float32) + delta).astype(w.dtype)
    out["layers"] = layers
    return out


def lora_param_count(lora: Dict) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(lora))


__all__ = ["DEFAULT_TARGETS", "init_lora", "lora_param_count",
           "materialize_lora", "merge_lora", "split_lora", "is_quantized"]
