"""The full online-improvement cycle: prompt search AND weight updates.

This is the reference's auto-improvement premise assembled end to end —
``apoService.ts`` ``_tryAutoAnalyze`` (:454-472) watches the trace
corpus and, when its gates open, analyzes, requests a textual gradient,
and beam-searches a better prompt; the TPU build ADDS the north-star
upgrade alongside it: every round of collected episodes also takes a
GRPO weight step and publishes the new params to the serving engine. One
loop, both optimizers:

    round N:
      1. collect a GRPO group of episodes per task, with the CURRENT
         optimized rules injected into every session's system prompt
         (segments.get_optimized_rules — the applied-prompt state the
         reference renders into its system message)
      2. judge each episode with the outcome evaluator and record the
         feedback on its trace (the corpus signal both optimizers gate
         on: user-feedback reward dim + APO analysis thresholds)
      3. GRPO update on the episodes' real sampled tokens; publish the
         new weights to the engine (next round samples the new policy)
      4. APO side: maybe_auto_analyze() (time/size gates); when the
         corpus shows a low good-rate, run the prompt beam search —
         next round's sessions inherit the winning rules

The loop owns nothing heavy: caller supplies the session factory (which
must accept ``rules=[...]``), the shared collector, the engine, and the
train state — the same contract as ``runtime/jobs.py`` factories.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..apo.eval import outcome_feedback
from ..apo.service import APOService
from ..obs import get_registry, get_tracer
from ..resilience.faults import ResilienceConfig
from ..resilience.guard import HealthMitigator, UpdateGuard
from ..traces.collector import TraceCollector
from .grpo import GRPOConfig
from .lora import split_lora
from .rl_loop import GroupSizeScheduler, grpo_round

# Loop-id source (see OnlineImprovementLoop._loop_id): a process-unique
# tag + counter. The tag matters for WAL-persisted collectors — feedback
# keys f"{thread_id}:{message_idx}" survive restarts, and a bare counter
# restarting at 1 would overwrite a previous process's verdicts.
import uuid

_PROC_TAG = uuid.uuid4().hex[:6]
_LOOP_IDS = itertools.count(1)


class _SessionCounter:
    """Atomic, snapshotable session-id source.

    itertools.count gives the atomicity concurrent session creation
    needs but can't report its position — which checkpoint/resume does:
    a resumed loop's thread ids must keep advancing from the persisted
    cursor, not restart at 1 and collide with the killed process's WAL
    feedback keys."""

    def __init__(self, start: int = 1):
        self._lock = threading.Lock()
        self._next = int(start)                 # guarded-by: _lock

    def __next__(self) -> int:
        with self._lock:
            v = self._next
            self._next += 1
            return v

    def peek(self) -> int:
        """The id the NEXT __next__ will hand out (the resume cursor)."""
        with self._lock:
            return self._next


@dataclasses.dataclass
class OnlineRoundResult:
    round_idx: int
    reward_mean: float
    episodes: int
    rules: List[str]            # rules ACTIVE during this round
    analyzed: bool              # APO analysis ran this round
    beam_ran: bool              # prompt search ran this round
    train_metrics: Dict[str, float]
    # Resilience surface (defaults when the loop runs unguarded):
    failed_episodes: int = 0    # episodes quarantined this round
    update_skipped: Optional[str] = None  # guard veto reason, if any
    checkpointed: bool = False  # a checkpoint landed after this round
    # Training-health surface (empty for rounds with no batch):
    health: Dict[str, float] = dataclasses.field(default_factory=dict)
    health_triggers: List[str] = dataclasses.field(default_factory=list)
    health_events: List[str] = dataclasses.field(default_factory=list)
    group_size: int = 0         # group size the NEXT round will collect


class OnlineImprovementLoop:
    """Couples grpo_round with the APO auto-analysis cycle."""

    def __init__(self, state, model_config, mesh,
                 make_session: Callable[..., "RolloutSession"],
                 tasks: Sequence[str], *,
                 apo: APOService,
                 collector: TraceCollector,
                 engine=None,
                 group_size: int = 4,
                 pad_id: int = 0,
                 max_len: Optional[int] = None,
                 grpo_config: GRPOConfig = GRPOConfig(),
                 ppo_epochs: int = 1,
                 max_parallel: int = 8,
                 reward_override=None,
                 feedback_fn=outcome_feedback,
                 metrics_service=None,
                 anchor_every: int = 0,
                 analyze_every: Optional[int] = None,
                 resilience: Optional[ResilienceConfig] = None,
                 checkpoint_manager=None,
                 checkpoint_every: int = 1,
                 tenant_id: Optional[str] = None,
                 experience_sink=None):
        self.state = state
        self.model_config = model_config
        self.mesh = mesh
        self.make_session = make_session
        self.tasks = list(tasks)
        self.apo = apo
        self.collector = collector
        self.engine = engine
        self.group_size = group_size
        self.pad_id = pad_id
        self.max_len = max_len
        self.grpo_config = grpo_config
        self.ppo_epochs = ppo_epochs
        self.max_parallel = max_parallel
        self.reward_override = reward_override
        self.feedback_fn = feedback_fn
        self.metrics_service = metrics_service
        # Round-based analysis cadence: the reference's auto-analysis is
        # a RECURRING timer (apoService.ts:435-472, hourly); this loop
        # drives rounds, so the natural translation is "every N rounds".
        # None = every round (the service's own time/size gates still
        # apply either way — this only throttles how often they are
        # consulted).
        self.analyze_every = analyze_every
        # anchor_every > 0 (with grpo_config.kl_coef > 0): keep a
        # rolling snapshot of the policy as the k3-KL reference,
        # refreshed every anchor_every rounds — the drift stabilizer
        # proven by the contextual runs (ROUND3_NOTES.md §24).
        self.anchor_every = anchor_every
        self._anchor = (state.params
                        if anchor_every > 0 and grpo_config.kl_coef > 0
                        else None)
        # Resilience: the fault boundary config rides into every
        # grpo_round; ONE UpdateGuard spans the loop so the loss-spike
        # baseline accumulates across rounds instead of resetting.
        self.resilience = resilience
        self._update_guard = (UpdateGuard.from_config(resilience)
                              if resilience is not None else None)
        # Training-health mitigations: ONE mitigator spans the loop
        # (streak hysteresis is cross-round state, like the guard's
        # spike baseline). Even with health_mitigations=False it runs —
        # triggers are then counted as vetoes instead of applied. The
        # group-size scheduler only engages when its sub-gate is on.
        self._health_mitigator = (HealthMitigator.from_config(resilience)
                                  if resilience is not None else None)
        self._group_scheduler = (
            GroupSizeScheduler.from_config(resilience, group_size)
            if resilience is not None and resilience.mitigate_group_size
            else None)
        # Preemption safety: with a CheckpointManager, the loop persists
        # its full resume surface (train state + round index + session
        # cursor + optimized rules + KL anchor) every
        # ``checkpoint_every`` rounds; OnlineImprovementLoop.resume()
        # restores the exact round.
        self.checkpoint_manager = checkpoint_manager
        self.checkpoint_every = checkpoint_every
        # Per-tenant mode: the round trains ADAPTER deltas only (the
        # caller sets up lora_base training so state.params is the
        # adapter tree) and each round republishes through the no-drain
        # publish_adapter path instead of the rolling base publish —
        # one tenant's training loop never pauses the others' decodes.
        self.tenant_id = tenant_id
        # Streaming async mode: when set, every round ALSO streams its
        # collected episodes — stamped with the (epoch, version) that
        # sampled them — into an experience sink (an
        # ExperienceClient.submit or ExperienceQueue.offer_many duck),
        # making this loop a collector for a streaming learner
        # (serve/learner.py StreamingLearnerService) instead of the
        # only trainer. Offers are fire-and-forget per round; the
        # sink's idempotent episode ids make resubmits safe.
        self.experience_sink = experience_sink
        self._round = 0
        # Last weight version a versioned engine (ServingFleet) acked
        # for this loop's params; persisted so resume() can republish AT
        # that version instead of letting a fresh publisher restart at 1
        # (which would make the skew gauge and the round↔version metric
        # trail lie after a restart).
        self._published_version: Optional[int] = None
        # Atomic id source: sessions are created from the collection
        # pool's worker threads (a racy += would hand two episodes the
        # same thread_id and cross-attribute their traces). The loop
        # instance id keeps thread ids unique ACROSS loops sharing one
        # collector — two successive 'online' jobs must not collide on
        # f"{thread_id}:{message_idx}" feedback keys.
        self._loop_id = next(_LOOP_IDS)
        self._session_ids = _SessionCounter(1)
        # Factories that can't take thread_id force serial collection:
        # concurrent sessions sharing the collector's default thread id
        # would read each other's traces.
        import inspect
        try:
            sig = inspect.signature(make_session)
            self._factory_takes_thread_id = (
                "thread_id" in sig.parameters
                or any(p.kind is inspect.Parameter.VAR_KEYWORD
                       for p in sig.parameters.values()))
        except (TypeError, ValueError):
            self._factory_takes_thread_id = False
        if not self._factory_takes_thread_id and max_parallel > 1:
            raise ValueError(
                "session factory does not accept thread_id=; concurrent "
                "collection (max_parallel > 1) would cross-attribute "
                "episode traces — extend the factory or pass "
                "max_parallel=1")
        # feedback_fn may take (trace) — the reference's outcome shape —
        # or (trace, session) for judges that need the episode's sampled
        # token ids (EnginePolicyClient.call_log), e.g. real-policy
        # output-style evaluators.
        self._feedback_takes_session = False
        if feedback_fn is not None:
            try:
                sig = inspect.signature(feedback_fn)
                self._feedback_takes_session = len([
                    p for p in sig.parameters.values()
                    if p.kind in (p.POSITIONAL_ONLY,
                                  p.POSITIONAL_OR_KEYWORD)]) >= 2
            except (TypeError, ValueError):
                pass

    def current_rules(self) -> List[str]:
        return self.apo.get_optimized_rules()

    def _fresh_session(self, rules: List[str]):
        """Factory call with a UNIQUE thread id — episodes share one
        collector, so per-thread trace attribution needs distinct ids.
        (Factories without thread_id support were rejected at
        construction unless collection is serial.)"""
        if not self._factory_takes_thread_id:
            return self.make_session(rules=list(rules))
        tid = (f"online-{_PROC_TAG}-{self._loop_id}-r{self._round}"
               f"-s{next(self._session_ids)}")
        return self.make_session(rules=list(rules), thread_id=tid)

    def run_round(self) -> OnlineRoundResult:
        with get_tracer().span("online.round", round=self._round):
            return self._run_round_impl()

    def _stream_episodes(self, out) -> None:
        """Async-mode side channel: offer the round's episodes to the
        experience sink, stamped with the behavior version that sampled
        them. Sink failures never fail the round — the deterministic
        episode ids make the next round's resubmit a safe dedup."""
        from .experience import trajectories_to_episodes
        episodes = trajectories_to_episodes(
            out.trajectories, epoch=0,
            version=self._published_version or 0,
            source=f"online-{_PROC_TAG}-{self._loop_id}",
            round_idx=self._round)
        sink = self.experience_sink
        try:
            submit = getattr(sink, "submit", None)
            if submit is not None:             # ExperienceClient duck
                submit(episodes)
            else:                              # ExperienceQueue duck
                sink.offer_many(
                    episodes,
                    current_version=self._published_version or 0)
        except Exception:
            get_registry().counter(
                "senweaver_online_stream_offer_failures_total",
                "Rounds whose episode stream offer failed (episodes "
                "stay local; deterministic ids make the resubmit a "
                "dedup).").inc()

    def _run_round_impl(self) -> OnlineRoundResult:
        rules = self.current_rules()

        def reward(ti, g, session):
            # Judge the episode and RECORD the verdict on its trace —
            # the feedback signal the reward head weights highest and
            # the APO gates count. The trace reward (now including the
            # feedback dim) or the caller's override scores the episode.
            trace = self.collector.get_active_trace(session.thread_id)
            if self.feedback_fn is not None and trace is not None:
                fb = (self.feedback_fn(trace, session)
                      if self._feedback_takes_session
                      else self.feedback_fn(trace))
                if fb:
                    session.record_feedback(fb)
            if self.reward_override is not None:
                return self.reward_override(ti, g, session)
            return (trace.summary.final_reward or 0.0) \
                if trace is not None else 0.0

        out = grpo_round(
            self.state, self.model_config, self.mesh,
            lambda: self._fresh_session(rules), self.tasks,
            group_size=self.group_size, pad_id=self.pad_id,
            max_len=self.max_len, grpo_config=self.grpo_config,
            ppo_epochs=self.ppo_epochs, max_parallel=self.max_parallel,
            reward_override=reward,
            metrics_service=self.metrics_service, engine=self.engine,
            ref_params=self._anchor, resilience=self.resilience,
            update_guard=self._update_guard,
            health_mitigator=self._health_mitigator,
            round_idx=self._round,
            behavior_stamp=(0, self._published_version or 0))
        self.state = out.state
        if self.experience_sink is not None and out.trajectories:
            self._stream_episodes(out)
        # Group-size mitigation tick: resize for the NEXT round while
        # its trigger streak is active; changes become round events.
        health_events = list(out.health_events)
        if (self._group_scheduler is not None
                and self._health_mitigator is not None):
            self.group_size, gs_events = self._group_scheduler.update(
                self._health_mitigator.group_size_active())
            health_events.extend(gs_events)
            if gs_events and self.metrics_service is not None:
                self.metrics_service.capture("Group Size Rescheduled", {
                    "round": self._round, "group_size": self.group_size,
                    "events": ",".join(gs_events),
                })
        if (self._anchor is not None and self.anchor_every > 0
                and (self._round + 1) % self.anchor_every == 0):
            self._anchor = self.state.params
        if self.tenant_id is not None and self.engine is not None \
                and hasattr(self.engine, "publish_adapter"):
            # Tenant rounds publish ONLY the adapter leaves (state.params
            # is the adapter tree under lora_base training; a merged
            # tree is split the same way) at the tenant's next monotonic
            # adapter_version. In-flight requests keep their bound slot;
            # the tenant's next request uploads the new version.
            _, lora = split_lora(self.state.params)
            if not lora["layers"]:
                raise ValueError(
                    "tenant_id is set but state.params has no *_lora_* "
                    "leaves — per-tenant rounds train adapter deltas "
                    "(init_lora + lora_base training), not base weights")
            with get_tracer().span("online.publish_adapter",
                                   tenant=self.tenant_id):
                published = self.engine.publish_adapter(
                    self.tenant_id, lora)
            if isinstance(published, int):
                self._published_version = published
                if self.metrics_service is not None:
                    self.metrics_service.capture("Adapter Published", {
                        "round": self._round,
                        "tenant_id": self.tenant_id,
                        "adapter_version": published,
                    })
            if hasattr(self.engine, "record_snapshot"):
                self.engine.record_snapshot()
        elif self.engine is not None and hasattr(self.engine,
                                                 "update_params"):
            with get_tracer().span("online.publish_params"):
                published = self.engine.update_params(self.state.params)
            # A ServingFleet publish is VERSIONED (rolling drain→swap
            # across replicas via serve.WeightPublisher); a bare engine
            # returns None. Record the version + serving state so the
            # metrics trail ties each training round to the weight
            # version its next round samples from.
            if isinstance(published, int):
                self._published_version = published
                if self.metrics_service is not None:
                    self.metrics_service.capture("Weights Published", {
                        "round": self._round,
                        "weight_version": published,
                    })
            if hasattr(self.engine, "record_snapshot"):
                self.engine.record_snapshot()

        # APO side of the cycle (the reference's timer tick, driven at
        # round boundaries here): analysis when gates open; prompt beam
        # search when the corpus shows a low good-rate.
        due = (self.analyze_every is None
               or self._round % self.analyze_every == 0)
        with get_tracer().span("online.apo", due=due):
            report = self.apo.maybe_auto_analyze() if due else None
            beam_ran = False
            if report is not None and self.apo.should_auto_gradient() \
                    and self.apo.generate_fn is not None:
                self.apo.run_beam_search()
                beam_ran = True

        ep_rewards = [e.reward for e in out.episodes]
        result = OnlineRoundResult(
            round_idx=self._round,
            reward_mean=(sum(ep_rewards) / len(ep_rewards)
                         if ep_rewards else 0.0),
            episodes=len(out.episodes),
            rules=rules,
            analyzed=report is not None,
            beam_ran=beam_ran,
            train_metrics=dict(out.metrics),
            failed_episodes=len(out.failures),
            update_skipped=out.update_skipped,
            health=dict(out.health),
            health_triggers=list(out.health_triggers),
            health_events=health_events,
            group_size=self.group_size)
        self._round += 1
        if (self.checkpoint_manager is not None and self.checkpoint_every
                and self._round % self.checkpoint_every == 0):
            with get_tracer().span("online.checkpoint",
                                   round=self._round):
                self.checkpoint()
            result.checkpointed = True
        return result

    def run(self, rounds: int) -> List[OnlineRoundResult]:
        return [self.run_round() for _ in range(rounds)]

    # -- preemption-safe persistence ---------------------------------------
    def checkpoint(self) -> str:
        """Persist the loop's full resume surface and return the step dir.

        Beyond the train state, deterministic continuation needs the
        loop-level cursors: the round index (rewards/faults keyed on
        round coordinates), the session-id cursor (WAL feedback keys
        must not collide), the ACTIVE optimized rules (a resumed round
        must render the same system prompt), and the KL anchor params
        (saved as ``anchor.npz`` beside the state; if a preemption lands
        between meta.json and anchor.npz, resume() re-anchors at the
        restored params — a refresh, not a corruption)."""
        if self.checkpoint_manager is None:
            raise ValueError("loop was built without a checkpoint_manager")
        step_dir = self.checkpoint_manager.save(self.state, extra_meta={
            "online_round": self._round,
            "online_session_cursor": self._session_ids.peek(),
            "online_rules": self.current_rules(),
            "online_anchor": self._anchor is not None,
            "online_weight_version": self._published_version,
        })
        if self._anchor is not None:
            import jax
            import numpy as np
            leaves = jax.tree_util.tree_leaves(self._anchor)
            arrays = {f"leaf_{i}": np.asarray(jax.device_get(x))
                      for i, x in enumerate(leaves)}
            tmp = os.path.join(step_dir, "anchor.npz.tmp")
            with open(tmp, "wb") as f:
                np.savez(f, **arrays)
            os.replace(tmp, os.path.join(step_dir, "anchor.npz"))
        return step_dir

    @classmethod
    def resume(cls, checkpoint_manager, state_template, model_config,
               mesh, make_session: Callable[..., "RolloutSession"],
               tasks: Sequence[str], *, step: Optional[int] = None,
               **kwargs: Any) -> "OnlineImprovementLoop":
        """Reconstruct a loop at the exact round a checkpoint captured.

        ``state_template`` is a TrainState with matching structure
        (shapes/dtypes/optimizer) for CheckpointManager.restore;
        ``kwargs`` are the remaining constructor arguments (apo,
        collector, engine, resilience, ...) — pass the same values the
        killed process used, with the apo service backed by the SAME
        segment-store path or any path (the persisted rule-set is
        reinstalled either way). Restores: train state, round index,
        session-id cursor, optimized rules, and the KL anchor; then
        republishes the restored params to the engine so serving
        matches training from the first resumed episode."""
        state, meta = checkpoint_manager.restore(state_template, step)
        loop = cls(state, model_config, mesh, make_session, tasks,
                   checkpoint_manager=checkpoint_manager, **kwargs)
        loop._round = int(meta.get("online_round", 0))
        loop._session_ids = _SessionCounter(
            int(meta.get("online_session_cursor", 1)))
        rules = meta.get("online_rules")
        if rules is not None:
            loop.apo.segments.install_rules(list(rules))
        if loop._anchor is not None:
            anchor_path = os.path.join(checkpoint_manager.root,
                                       f"step_{meta['step']}",
                                       "anchor.npz")
            if meta.get("online_anchor") and os.path.exists(anchor_path):
                import jax
                import numpy as np
                leaves, treedef = jax.tree_util.tree_flatten(state.params)
                with np.load(anchor_path) as data:
                    restored = [data[f"leaf_{i}"]
                                for i in range(len(leaves))]
                loop._anchor = jax.tree_util.tree_unflatten(
                    treedef, restored)
            else:
                loop._anchor = state.params
        if loop.engine is not None and hasattr(loop.engine,
                                               "update_params"):
            saved_version = meta.get("online_weight_version")
            published = _republish(loop.engine, state.params,
                                   saved_version)
            if isinstance(published, int):
                loop._published_version = published
        return loop


def _republish(engine, params, saved_version: Optional[int]):
    """Republish restored params, stamping the checkpointed weight
    version onto versioned engines (ServingFleet).

    Without the stamp a restarted fleet would hand out version 1 for
    weights that are really round-N's, so the version-skew gauge and the
    round↔version metric trail would lie after every resume. Only pass
    the version when it actually advances the publisher — a fleet that
    survived the trainer restart already holds >= saved_version and a
    re-stamp would (correctly) be fenced as stale."""
    publisher = getattr(engine, "publisher", None)
    if (saved_version is not None and publisher is not None
            and int(saved_version) > publisher.version):
        return engine.update_params(params, version=int(saved_version))
    return engine.update_params(params)
