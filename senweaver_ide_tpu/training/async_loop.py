"""Sampler/trainer overlap: the pipelined GRPO driver.

SURVEY.md §7 names "sampler/trainer overlap" the main systems risk for
the tokens/sec/chip metric: ``grpo_round`` is strictly collect → train,
so the chip idles through every host-side phase of collection (tool
execution, agent-loop bookkeeping) and the host idles through the train
step. This driver runs them as a two-stage pipeline (the Podracer
"Sebulba" split, PAPERS.md): a collector thread drives rollout sessions
for round N+1 while the device trains on round N's batch.

Staleness is bounded by the queue depth (``prefetch``): a batch is at
most ``prefetch`` updates behind the params that train on it. Two
correction modes:

- ``importance_correction=True`` (default): the behavior params that
  collected each batch are held in a BOUNDED version-keyed LRU
  (:class:`~.experience.BehaviorParamsCache`) and the batch's
  ``old_logp`` is computed under them just before the update, so the
  clipped objective's importance ratio is exact. Residency is
  O(cache capacity) param trees no matter how far the collector runs
  ahead; when a batch's behavior version has aged out, the step
  degrades to the ratio-1 approximation under the current params —
  counted (``senweaver_grpo_behavior_ratio_one_fallbacks_total``),
  never crashed.
- ``importance_correction=False``: ``old_logp = stop_grad(current)``
  (ratio 1), the standard 1-step-stale approximation.

Weight publication: each update stages its params for ``publish_params``
(wire it to ``RolloutEngine.update_params``), and the collector applies
the latest staged set at its next ROUND BOUNDARY — never mid-round, so
the retained ``behavior_params`` snapshot is exactly what every episode
in the round sampled under (a mid-round swap would silently break the
importance correction for episodes finishing after it). Publications
coalesce (latest wins); the final update's params are always flushed
when ``run`` returns — the single-chip analogue of the disaggregated
actor/learner weight transfer (RLAX; reference semantic: the APO cycle's
"apply optimized prompt to the live agent", apoService.ts:1219-1264,
upgraded to weights).
"""

from __future__ import annotations

import functools
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from .data import make_batch, make_batch_logps, place_batch_for_mesh
from .experience import BehaviorParamsCache, BehaviorParamsEvicted
from .grpo import GRPOConfig, token_logprobs
from .rl_loop import EpisodeRecord, collect_group_trajectories
from .trainer import TrainState, train_step


@functools.partial(jax.jit, static_argnames=("config",))
def _behavior_logp(params, config, tokens: jax.Array) -> jax.Array:
    """Token logprobs of ``tokens`` under the (frozen) behavior policy;
    ratio positions are later selected by the objective's own mask."""
    from ..models.transformer import forward
    logits, _ = forward(params, config, tokens[:, :-1])
    return token_logprobs(logits, tokens[:, 1:])


def behavior_logp_batched(params, config, tokens: jax.Array,
                          accum_steps: int = 1) -> jax.Array:
    """Behavior logps with the SAME microbatch split as the update:
    a whole-batch forward materializes (B, S-1, V) logits — the exact
    allocation accum_steps was sized to avoid. Batch must be
    accum-divisible (place_batch_for_mesh guarantees it)."""
    b = tokens.shape[0]
    if accum_steps <= 1 or b % accum_steps != 0:
        return _behavior_logp(params, config, tokens)
    mb = b // accum_steps
    import jax.numpy as _jnp
    return _jnp.concatenate(
        [_behavior_logp(params, config, tokens[i * mb:(i + 1) * mb])
         for i in range(accum_steps)], axis=0)


@dataclass
class AsyncRoundResult:
    state: TrainState
    metrics: Dict[str, float]
    episodes: List[EpisodeRecord]
    staleness: int            # updates between collection and training
    collect_wait_s: float     # trainer time spent waiting for a batch


@dataclass
class _Collected:
    trajectories: list
    episodes: List[EpisodeRecord]
    # Version stamp only — the params themselves live in the trainer's
    # bounded BehaviorParamsCache, NOT on the queue item (an unbounded
    # reference per in-flight batch was the old host-memory leak when
    # the collector outran the trainer).
    behavior_version: int
    collect_s: float = field(default=0.0)


class AsyncGRPOTrainer:
    """Two-stage pipelined GRPO: collection overlaps the train step."""

    def __init__(self, state: TrainState, model_config, mesh,
                 make_session: Callable[[], "RolloutSession"],
                 tasks: Sequence[str], *,
                 group_size: int = 4,
                 pad_id: int = 0,
                 max_len: Optional[int] = None,
                 grpo_config: GRPOConfig = GRPOConfig(),
                 reward_override=None,
                 max_parallel: int = 8,
                 accum_steps: int = 1,
                 ppo_epochs: int = 1,
                 prefetch: int = 1,
                 importance_correction: bool = True,
                 behavior_cache_size: Optional[int] = None,
                 publish_params: Optional[Callable[[object], None]] = None,
                 metrics_service=None,
                 lora_base=None,
                 ref_params=None):
        self.state = state
        self.model_config = model_config
        self.mesh = mesh
        self.make_session = make_session
        self.tasks = list(tasks)
        self.group_size = group_size
        self.pad_id = pad_id
        self.max_len = max_len
        self.grpo_config = grpo_config
        self.reward_override = reward_override
        self.max_parallel = max_parallel
        self.accum_steps = accum_steps
        if ppo_epochs < 1:
            raise ValueError(f"ppo_epochs must be >= 1, got {ppo_epochs}")
        self.ppo_epochs = ppo_epochs
        self.importance_correction = importance_correction
        self.publish_params = publish_params
        self.metrics_service = metrics_service
        # LoRA: state.params are ONLY the adapters over this frozen base
        # (training/lora.py); behavior snapshots and publishes carry the
        # MATERIALIZED policy so logp recomputation and engines see full
        # weights, while the train step differentiates adapters only.
        self.lora_base = lora_base
        # Frozen/rolling reference for the k3-KL term (grpo_round's
        # ref_params analogue): a FULL policy tree; combined with
        # grpo_config.kl_coef > 0 it anchors long runs against drift
        # (ROUND3_NOTES.md §24). Swap via set_ref_params at round
        # boundaries for a rolling anchor.
        self.ref_params = ref_params

        self._queue: "queue.Queue[_Collected]" = queue.Queue(
            maxsize=max(1, prefetch))
        # Bounded behavior-params residency: every version the collector
        # may still train against is cached here by version; anything
        # older is evicted (typed, counted) and its batches degrade to
        # ratio-1. Default capacity covers the pipeline depth plus the
        # batch currently training and one staged publish.
        self.behavior_cache = BehaviorParamsCache(
            behavior_cache_size if behavior_cache_size is not None
            else max(2, prefetch) + 2)
        self.behavior_cache.put(0, self._merged_view(state.params))
        self._publish_lock = threading.Lock()
        # Staged (version, params) awaiting publication; the collector
        # applies it at round boundaries. _applied_behavior is the last
        # APPLIED pair — what the serving engine is actually running —
        # and is only touched by _flush_pending_publish (collector
        # thread, or run()'s finally after the collector joined).
        self._pending_publish: Optional[tuple] = None
        self._applied_behavior: tuple = (0,
                                         self._merged_view(state.params))
        self._version = 0
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None
        self._rounds_wanted = 0
        self._thread: Optional[threading.Thread] = None

    # -- collector side ---------------------------------------------------
    def _flush_pending_publish(self) -> None:
        """Apply the latest staged publication (if any) to the engine and
        remember it as the live behavior snapshot."""
        with self._publish_lock:
            pending = self._pending_publish
            self._pending_publish = None
        if pending is not None and self.publish_params is not None:
            pending = (pending[0], self._folded_view(pending[1]))
            self.publish_params(pending[1])
            self._applied_behavior = pending
            # The cache, not the queue item, is what _train_on reads
            # the behavior params back from (bounded residency).
            self.behavior_cache.put(pending[0], pending[1])

    def set_ref_params(self, ref_params) -> None:
        """Swap the KL anchor (rolling-anchor pattern); takes effect on
        the next train round. Pass a FULL policy tree (materialized for
        LoRA)."""
        self.ref_params = ref_params

    def _merged_view(self, params):
        """Zero-copy full-policy view (dict union): what behavior-logp
        recompute and no-publish collection consume — forward() applies
        adapter leaves directly, so no weight fold is needed."""
        if self.lora_base is None:
            return params
        from .lora import merge_lora
        return merge_lora(self.lora_base, params)

    def _folded_view(self, params):
        """Materialized full weights — ONLY for actual publication to an
        engine. Folding is O(full model); it runs at flush time so
        latest-wins coalescing never burns a discarded fold, and at most
        one folded copy is resident."""
        if self.lora_base is None:
            return params
        from .lora import materialize_lora
        return materialize_lora(self.lora_base, params, self.model_config)

    def _collect_loop(self) -> None:
        produced = 0
        try:
            while not self._stop.is_set() and produced < self._rounds_wanted:
                # Apply any params published since the last round BEFORE
                # sampling starts: publication is deferred to collection
                # round boundaries (see _train_on) so every episode in a
                # round was sampled under exactly the (version, params)
                # snapshot recorded here — a mid-round engine weight swap
                # would make the retained behavior_params wrong for the
                # episodes that finished after it.
                self._flush_pending_publish()
                if self.publish_params is not None:
                    # The engine serves exactly the last APPLIED pair —
                    # never a racy read of live trainer state (a train
                    # step may complete between the flush and here).
                    version, params = self._applied_behavior
                else:
                    # No publication channel: sessions read trainer state
                    # directly, so the live reference IS the behavior.
                    version = self._version
                    # reference for full FT; zero-copy merge for LoRA
                    params = self._merged_view(self.state.params)
                    self.behavior_cache.put(version, params)
                t0 = time.monotonic()
                trajectories, episodes = collect_group_trajectories(
                    self.make_session, self.tasks,
                    group_size=self.group_size,
                    reward_override=self.reward_override,
                    max_parallel=self.max_parallel)
                for ep in episodes:
                    # (epoch, version) behavior stamp — the in-process
                    # pipeline has no lease, so epoch stays 0.
                    ep.behavior_version = version
                item = _Collected(trajectories, episodes, version,
                                  collect_s=time.monotonic() - t0)
                while not self._stop.is_set():
                    try:
                        self._queue.put(item, timeout=0.2)
                        produced += 1
                        break
                    except queue.Full:
                        continue
        except BaseException as e:   # surfaced by run()
            self._error = e
            self._stop.set()

    # -- trainer side -----------------------------------------------------
    def run(self, num_rounds: int) -> List[AsyncRoundResult]:
        """Train ``num_rounds`` updates with pipelined collection."""
        self._rounds_wanted = num_rounds
        self._thread = threading.Thread(target=self._collect_loop,
                                        name="grpo-collector", daemon=True)
        self._thread.start()
        results: List[AsyncRoundResult] = []
        try:
            for _ in range(num_rounds):
                t_wait = time.monotonic()
                while True:
                    if self._error is not None:
                        raise RuntimeError(
                            "rollout collector failed") from self._error
                    try:
                        item = self._queue.get(timeout=0.2)
                        break
                    except queue.Empty:
                        continue
                wait_s = time.monotonic() - t_wait
                results.append(self._train_on(item, wait_s))
        finally:
            self._stop.set()
            self._thread.join(timeout=30)
            # Collector is down — flush the last pending publication so
            # the serving engine always ends on the final params even
            # though intermediate publishes coalesce (latest wins). If
            # the join timed out (a wedged session), the collector still
            # owns publication: flushing here would race its next round
            # boundary and reintroduce the mid-round swap.
            if not self._thread.is_alive():
                self._flush_pending_publish()
        return results

    def _train_on(self, item: _Collected,
                  wait_s: float) -> AsyncRoundResult:
        staleness = self._version - item.behavior_version
        if not item.trajectories:
            return AsyncRoundResult(self.state, {}, item.episodes,
                                    staleness, wait_s)
        tokens, mask, rewards, group_ids = make_batch(
            item.trajectories, pad_id=self.pad_id, max_len=self.max_len)
        recorded = (make_batch_logps(item.trajectories, tokens, mask)
                    if self.importance_correction else None)
        # Shared explicit mesh placement (same path as grpo_round —
        # GSPMD propagation alone broadcasts host batches to all
        # devices before resharding).
        tokens, mask, rewards, group_ids, old_logp = place_batch_for_mesh(
            self.mesh, tokens, mask, rewards, group_ids, recorded,
            pad_id=self.pad_id, accum_steps=self.accum_steps)
        if (old_logp is None
                and (self.ppo_epochs > 1
                     or (self.importance_correction and staleness > 0))):
            # Multi-epoch updates REQUIRE frozen behavior logps —
            # without them epochs 2+ recompute ratio==1 against the
            # already-updated params and clipping never engages — so
            # they are computed here regardless of the
            # importance_correction flag (which governs only the
            # 1-epoch staleness case). Microbatched like the update.
            try:
                behavior = self.behavior_cache.get(item.behavior_version)
            except BehaviorParamsEvicted:
                # Collector outran the trainer past the cache bound:
                # degrade to ratio-1 under the CURRENT params (counted),
                # instead of crashing or pinning unbounded param trees.
                self.behavior_cache.note_ratio_one_fallback()
                behavior = self._merged_view(self.state.params)
            old_logp = behavior_logp_batched(behavior,
                                             self.model_config, tokens,
                                             self.accum_steps)

        ref_logp = None
        ref = self.ref_params     # single read: set_ref_params may swap
        if ref is not None and self.grpo_config.kl_coef > 0.0:
            ref_logp = behavior_logp_batched(ref, self.model_config,
                                             tokens, self.accum_steps)
        for _ in range(self.ppo_epochs):
            self.state, metrics = train_step(
                self.state, self.model_config, self.mesh, tokens, mask,
                rewards, group_ids, old_logp=old_logp, ref_logp=ref_logp,
                grpo_config=self.grpo_config,
                accum_steps=self.accum_steps, lora_base=self.lora_base)
        self._version += 1
        if self.publish_params is not None:
            # Defer to the collector's next round boundary (latest wins):
            # swapping engine weights mid-collection would invalidate the
            # behavior_params snapshot for in-flight episodes. Version
            # and params are staged TOGETHER so the collector's applied
            # snapshot is always a coherent pair.
            with self._publish_lock:
                # adapters staged raw; the O(model) fold happens at
                # flush (once per APPLIED publish, not per train round)
                self._pending_publish = (self._version, self.state.params)

        out = {k: float(v) for k, v in metrics.items()}
        if self.metrics_service is not None:
            ep = [e.reward for e in item.episodes]
            self.metrics_service.capture("Async GRPO Round", {
                "episodes": len(item.episodes),
                "staleness": staleness,
                "collect_s": round(item.collect_s, 3),
                "trainer_wait_s": round(wait_s, 3),
                "reward_mean": sum(ep) / max(len(ep), 1),
                **{k: round(v, 6) for k, v in out.items()},
            })
        return AsyncRoundResult(self.state, out, item.episodes,
                                staleness, wait_s)
