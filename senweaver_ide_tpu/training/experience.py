"""Streaming experience pipeline: episodes flow, rounds don't.

The disaggregated learner (serve/learner.py) was strictly lockstep —
collect a full round, train, publish, repeat — so the learner idled
through every host-side collection phase and replicas idled through
every train step. This module holds the training-side half of the
continuous-flow replacement (RLAX 2512.06392 / Podracer's Sebulba
split, 2104.06272): replicas stream finished episodes as they land,
the learner aggregates PARTIAL groups and steps as soon as a
staleness-bounded batch is ready, and publishes overlap collection.

Three pieces, deliberately free of any serve/ import so the rollout
plane can depend on them without a cycle:

- :class:`StreamedEpisode` — one finished episode stamped with the
  ``(epoch, version)`` of the weights that SAMPLED it. The stamp is
  what makes asynchrony correct: the learner computes importance
  ratios against the stamped behavior version, not "whatever the
  params are now".
- :class:`ExperienceQueue` — bounded, idempotent (episode ids dedup
  across RPC replays AND learner restarts), staleness-bounded (an
  episode more than ``max_staleness`` versions behind the learner is
  dropped and counted, never trained). Group-aware: episodes bucket by
  ``group_key`` and a batch is released only when enough groups are
  COMPLETE — GRPO advantages need whole groups, not whole rounds.
- :class:`BehaviorParamsCache` — a small LRU of recently published
  param versions keyed by version. Bounds the host-memory failure
  mode where a collector outrunning the trainer pinned one full
  params pytree per in-flight batch; eviction is TYPED
  (:class:`BehaviorParamsEvicted`) so callers degrade to the ratio-1
  approximation (counted) instead of crashing or growing without
  bound.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .data import Trajectory, make_batch, make_batch_logps

# Buckets for the staleness histogram: versions-behind at train time.
STALENESS_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0)

# offer() outcomes — the collector's ack vocabulary. "duplicate" is a
# SUCCESS for the collector (the episode is already on the learner,
# via an RPC replay or a previous incarnation); only "full" asks it to
# back off and resubmit.
ACCEPTED = "accepted"
DUPLICATE = "duplicate"
STALE = "stale"
FULL = "full"


@dataclasses.dataclass
class StreamedEpisode:
    """One finished episode, wire-friendly (plain fields only — the rpc
    codec ships it as a tagged dict). ``group_key`` buckets alternative
    completions of the same prompt for group-relative advantages;
    ``(epoch, version)`` stamp the BEHAVIOR policy that sampled it."""

    episode_id: str
    group_key: str
    prompt_ids: List[int]
    completion_ids: List[int]
    reward: float
    epoch: int
    version: int
    # Per-completion-token behavior logps captured at SAMPLE time
    # (engine result_logps). When present on every episode in a batch,
    # old_logp is assembled exactly — token-exact importance ratios
    # with no second forward pass (training/data.py make_batch_logps).
    behavior_logp: Optional[List[float]] = None
    task_idx: int = 0

    def to_wire(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_wire(cls, d: Dict[str, Any]) -> "StreamedEpisode":
        return cls(**d)


def assemble_batch(episodes: Sequence[StreamedEpisode], *, pad_id: int,
                   max_len: Optional[int] = None
                   ) -> Tuple[List[Trajectory], np.ndarray, np.ndarray,
                              np.ndarray, np.ndarray,
                              Optional[np.ndarray]]:
    """Streamed episodes → the exact arrays ``grpo_round`` would build
    for the same episodes: group ids assigned by first appearance of
    each ``group_key`` (order-stable, so a streamed batch equals the
    lockstep reference given the same episode sequence), old_logp from
    recorded behavior logps when every episode carries them.

    Returns ``(trajectories, tokens, mask, rewards, group_ids,
    old_logp)``; ``old_logp`` is None when any episode lacks logps —
    the caller recomputes against the behavior params cache instead."""
    if not episodes:
        raise ValueError("empty episode batch")
    gid_by_key: Dict[str, int] = {}
    trajectories: List[Trajectory] = []
    for ep in episodes:
        gid = gid_by_key.setdefault(ep.group_key, len(gid_by_key))
        trajectories.append(Trajectory(
            prompt_ids=list(ep.prompt_ids),
            completion_ids=list(ep.completion_ids),
            reward=float(ep.reward), group_id=gid,
            behavior_logp=(list(ep.behavior_logp)
                           if ep.behavior_logp is not None else None)))
    tokens, mask, rewards, group_ids = make_batch(
        trajectories, pad_id=pad_id, max_len=max_len)
    old_logp = make_batch_logps(trajectories, tokens, mask)
    return trajectories, tokens, mask, rewards, group_ids, old_logp


def trajectories_to_episodes(trajectories: Sequence[Trajectory], *,
                             epoch: int, version: int, source: str,
                             round_idx: int = 0
                             ) -> List[StreamedEpisode]:
    """Lockstep-collected trajectories → streamed episodes (the online
    loop's collector-side adapter). Episode ids are deterministic in
    ``(source, round_idx, index)`` so a resubmit after a lost ack
    dedups instead of double-training; group keys preserve the
    trajectory's group id within the round."""
    return [StreamedEpisode(
        episode_id=f"{source}/r{round_idx}/i{i}",
        group_key=f"{source}/r{round_idx}/g{t.group_id}",
        prompt_ids=list(t.prompt_ids),
        completion_ids=list(t.completion_ids),
        reward=float(t.reward), epoch=int(epoch), version=int(version),
        behavior_logp=(list(t.behavior_logp)
                       if t.behavior_logp is not None else None),
        task_idx=int(t.group_id))
        for i, t in enumerate(trajectories)]


class ExperienceQueue:
    """Bounded, idempotent, staleness-bounded episode buffer.

    Episodes bucket by ``group_key``; :meth:`take_batch` releases only
    COMPLETE groups (``group_size`` episodes each), at least
    ``min_groups`` of them — partial groups wait, finished groups
    train. Staleness is enforced twice: at :meth:`offer` (don't buffer
    what's already too old) and again at :meth:`take_batch` (the
    learner may have published versions while episodes sat queued).
    Both drops land on ``senweaver_learner_stale_episodes_total``.

    Idempotency: every accepted episode id enters a bounded seen-set;
    a replayed offer (RPC retry, collector resubmit after a learner
    crash) acks ``duplicate`` without re-buffering. The seen-set is
    exportable (:meth:`seen_snapshot` / :meth:`restore_seen`) so a
    restarted learner refuses episodes its previous incarnation
    already trained — the no-double-train half of crash recovery; the
    collector's resubmit-until-acked loop is the no-loss half.
    """

    def __init__(self, *, group_size: int, capacity: int = 1024,
                 max_staleness: int = 4, min_groups: int = 1,
                 seen_capacity: int = 65536, registry=None):
        if group_size < 1:
            raise ValueError("group_size must be >= 1")
        self.group_size = int(group_size)
        self.capacity = int(capacity)
        self.max_staleness = int(max_staleness)
        self.min_groups = max(1, int(min_groups))
        self._seen_capacity = int(seen_capacity)
        # group_key -> episodes in arrival order. Dict preserves
        # insertion order, so batch assembly is deterministic.
        self._groups: Dict[str, List[StreamedEpisode]] = {}  # guarded-by: _lock
        self._depth = 0                                      # guarded-by: _lock
        # Cumulative intake accounting mirrored off the counters so
        # stats() can report fractions without reading the registry.
        self._accepted_count = 0                             # guarded-by: _lock
        self._stale_count = 0                                # guarded-by: _lock
        self._seen: "collections.OrderedDict[str, None]" = \
            collections.OrderedDict()                        # guarded-by: _lock
        self._lock = threading.Lock()
        if registry is None:
            from ..obs import get_registry
            registry = get_registry()
        self._depth_gauge = registry.gauge(
            "senweaver_learner_experience_queue_depth",
            "Episodes buffered on the streaming learner (all groups).")
        self._ready_gauge = registry.gauge(
            "senweaver_learner_experience_ready_groups",
            "Complete episode groups awaiting a train step.")
        self._stale_total = registry.counter(
            "senweaver_learner_stale_episodes_total",
            "Episodes dropped for exceeding the staleness bound "
            "(behavior version more than K published versions behind).")
        self._dup_total = registry.counter(
            "senweaver_learner_duplicate_episodes_total",
            "Episode offers deduplicated by id (RPC replays and "
            "post-crash resubmits; acked, never re-buffered).")
        self._full_total = registry.counter(
            "senweaver_learner_experience_rejected_full_total",
            "Episode offers refused because the queue was at capacity "
            "(collector backpressure; the collector resubmits).")
        self._accepted_total = registry.counter(
            "senweaver_learner_episodes_accepted_total",
            "Episodes accepted into the streaming experience queue.")
        self._staleness_hist = registry.histogram(
            "senweaver_learner_episode_staleness",
            "Versions-behind of each episode at train time.",
            buckets=STALENESS_BUCKETS)
        self._depth_gauge.set(0)
        self._ready_gauge.set(0)

    # -- intake --------------------------------------------------------------
    def offer(self, episode: StreamedEpisode, *,
              current_version: int) -> str:
        """Admit one episode; returns one of ``accepted`` /
        ``duplicate`` / ``stale`` / ``full`` (the collector's ack —
        everything except ``full`` means "stop resending this id")."""
        with self._lock:
            if episode.episode_id in self._seen:
                self._seen.move_to_end(episode.episode_id)
                self._dup_total.inc()
                return DUPLICATE
            if current_version - episode.version > self.max_staleness:
                # Stale episodes still enter the seen-set: a replayed
                # offer of a dropped episode must not flap to "full"
                # accounting, and the collector must stop resending it.
                self._note_seen(episode.episode_id)
                self._stale_total.inc()
                self._stale_count += 1
                return STALE
            if self._depth >= self.capacity:
                self._full_total.inc()
                return FULL
            self._note_seen(episode.episode_id)
            self._groups.setdefault(episode.group_key, []).append(episode)
            self._depth += 1
            self._accepted_total.inc()
            self._accepted_count += 1
            self._update_gauges()
            return ACCEPTED

    def offer_many(self, episodes: Sequence[StreamedEpisode], *,
                   current_version: int) -> Dict[str, Any]:
        """Batch offer; returns ``{"acks": {episode_id: outcome}}`` —
        the wire shape of the ``submit_episodes`` RPC."""
        return {"acks": {ep.episode_id:
                         self.offer(ep, current_version=current_version)
                         for ep in episodes}}

    def _note_seen(self, episode_id: str) -> None:
        # guarded-by: _lock
        self._seen[episode_id] = None
        while len(self._seen) > self._seen_capacity:
            self._seen.popitem(last=False)

    # -- release -------------------------------------------------------------
    def _evict_stale(self, current_version: int) -> None:
        # guarded-by: caller
        for key in list(self._groups):
            kept = [ep for ep in self._groups[key]
                    if current_version - ep.version <= self.max_staleness]
            dropped = len(self._groups[key]) - len(kept)
            if dropped:
                for _ in range(dropped):
                    self._stale_total.inc()
                self._stale_count += dropped
                self._depth -= dropped
            if kept:
                self._groups[key] = kept
            else:
                del self._groups[key]

    def ready_groups(self, *, current_version: Optional[int] = None) -> int:
        """Complete groups available right now (after staleness
        eviction when ``current_version`` is given)."""
        with self._lock:
            if current_version is not None:
                self._evict_stale(current_version)
            n = sum(len(eps) // self.group_size
                    for eps in self._groups.values())
            self._ready_gauge.set(n)
            return n

    def take_batch(self, *, current_version: int,
                   min_groups: Optional[int] = None
                   ) -> Optional[List[StreamedEpisode]]:
        """Pop a staleness-bounded batch of COMPLETE groups, or None
        when fewer than ``min_groups`` groups are ready. Each released
        group contributes exactly ``group_size`` episodes (oldest
        first); the remainder of an over-full group stays queued for
        the next step."""
        need = self.min_groups if min_groups is None else max(1,
                                                              int(min_groups))
        with self._lock:
            self._evict_stale(current_version)
            ready = [key for key, eps in self._groups.items()
                     if len(eps) >= self.group_size]
            if len(ready) < need:
                self._update_gauges()
                return None
            batch: List[StreamedEpisode] = []
            for key in ready:
                eps = self._groups[key]
                take, rest = eps[:self.group_size], eps[self.group_size:]
                if rest:
                    self._groups[key] = rest
                else:
                    del self._groups[key]
                self._depth -= len(take)
                batch.extend(take)
            for ep in batch:
                self._staleness_hist.observe(
                    float(max(0, current_version - ep.version)))
            self._update_gauges()
            return batch

    # -- introspection / durability ------------------------------------------
    @property
    def depth(self) -> int:
        with self._lock:
            return self._depth

    def _update_gauges(self) -> None:
        # guarded-by: _lock
        self._depth_gauge.set(self._depth)
        self._ready_gauge.set(sum(len(eps) // self.group_size
                                  for eps in self._groups.values()))

    def seen_snapshot(self, *, limit: int = 8192) -> List[str]:
        """Most-recent accepted episode ids (newest last) for the
        learner's durable state — a successor restores them so
        resubmitted episodes its predecessor already consumed ack
        ``duplicate`` instead of training twice."""
        with self._lock:
            ids = list(self._seen)
            return ids[-limit:]

    def restore_seen(self, ids: Sequence[str]) -> None:
        with self._lock:
            for i in ids:
                self._note_seen(str(i))

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"depth": self._depth,
                    "groups": len(self._groups),
                    "ready_groups": sum(len(e) // self.group_size
                                        for e in self._groups.values()),
                    "seen": len(self._seen),
                    "accepted": self._accepted_count,
                    "stale_dropped": self._stale_count}


class BehaviorParamsEvicted(KeyError):
    """The requested behavior version aged out of the bounded cache —
    the typed signal to degrade importance correction to the ratio-1
    approximation (counted), never to crash or to silently use wrong
    params."""


class BehaviorParamsCache:
    """Bounded LRU of ``version -> params`` pytrees.

    Replaces the unbounded per-in-flight-batch ``behavior_params``
    references the async trainer used to pin: when the collector
    outruns the trainer by more than ``capacity`` publishes, the
    oldest version is evicted (counted) and a later lookup raises
    :class:`BehaviorParamsEvicted` so the trainer falls back to
    ratio-1 old_logp under the CURRENT params (also counted) — memory
    stays O(capacity) params trees no matter how far ahead the
    collector runs."""

    def __init__(self, capacity: int = 4, *, registry=None):
        self.capacity = max(1, int(capacity))
        self._items: "collections.OrderedDict[int, Any]" = \
            collections.OrderedDict()           # guarded-by: _lock
        self._lock = threading.Lock()
        if registry is None:
            from ..obs import get_registry
            registry = get_registry()
        self._evictions_total = registry.counter(
            "senweaver_grpo_behavior_cache_evictions_total",
            "Behavior-params versions evicted from the bounded LRU "
            "(collector outran the trainer by more than the cache "
            "capacity).")
        self._fallbacks_total = registry.counter(
            "senweaver_grpo_behavior_ratio_one_fallbacks_total",
            "Train steps that degraded importance correction to the "
            "ratio-1 approximation because the behavior version was "
            "evicted.")

    def put(self, version: int, params: Any) -> None:
        with self._lock:
            self._items[int(version)] = params
            self._items.move_to_end(int(version))
            while len(self._items) > self.capacity:
                self._items.popitem(last=False)
                self._evictions_total.inc()

    def get(self, version: int) -> Any:
        with self._lock:
            try:
                params = self._items[int(version)]
            except KeyError:
                raise BehaviorParamsEvicted(
                    f"behavior params v{version} evicted "
                    f"(cache capacity {self.capacity}; resident: "
                    f"{sorted(self._items)})") from None
            self._items.move_to_end(int(version))
            return params

    def note_ratio_one_fallback(self) -> None:
        self._fallbacks_total.inc()

    def __contains__(self, version: int) -> bool:
        with self._lock:
            return int(version) in self._items

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def versions(self) -> List[int]:
        with self._lock:
            return sorted(self._items)


class StreamingTrainerAdapter:
    """The jax half of the streaming learner: streamed batch → one
    GRPO step, with the old_logp settlement the asynchrony demands.

    The serve-side :class:`~..serve.learner.StreamingLearnerService`
    owns leases, versions, and the publish saga; this adapter owns the
    TrainState and the math. ``old_logp`` settlement order: recorded
    per-token behavior logps when every episode carries them (token-
    exact, zero extra forwards — the normal path), else a recompute
    under each distinct stamped behavior version via the bounded
    :class:`BehaviorParamsCache`, degrading to ratio-1 under the
    CURRENT params for evicted versions (counted, never crashed).
    Merged-LoRA behavior views are out of scope here — use
    ``AsyncGRPOTrainer`` for the in-process LoRA path.

    ``note_published(version)`` must be called at every accepted
    publish so the cache can serve later recomputes for episodes that
    version will sample."""

    def __init__(self, state, model_config, mesh, *,
                 grpo_config=None, optimizer=None, pad_id: int = 0,
                 max_len: Optional[int] = None, accum_steps: int = 1,
                 behavior_cache_size: int = 4, registry=None):
        from .trainer import GRPOConfig
        self.state = state
        self.model_config = model_config
        self.mesh = mesh
        self.grpo_config = grpo_config or GRPOConfig()
        self.optimizer = optimizer
        self.pad_id = int(pad_id)
        self.max_len = max_len
        self.accum_steps = max(1, int(accum_steps))
        self.behavior_cache = BehaviorParamsCache(
            behavior_cache_size, registry=registry)
        # Version 0 (pre-first-publish weights) seeds the cache so the
        # earliest streamed episodes always have exact behavior params.
        self.behavior_cache.put(0, state.params)

    @property
    def params(self):
        return self.state.params

    def note_published(self, version: int) -> None:
        """Pin the params just published as behavior version
        ``version`` (the weights replicas will sample with next)."""
        self.behavior_cache.put(int(version), self.state.params)

    def _recomputed_old_logp(self, episodes: Sequence[StreamedEpisode],
                             tokens: np.ndarray) -> np.ndarray:
        """Per-row behavior logps under each row's STAMPED version —
        one forward per distinct version in the batch (small: the
        staleness bound caps how many versions can coexist)."""
        from .async_loop import behavior_logp_batched
        rows_by_version: Dict[int, List[int]] = {}
        for i, ep in enumerate(episodes):
            rows_by_version.setdefault(int(ep.version), []).append(i)
        out = np.zeros((tokens.shape[0], tokens.shape[1] - 1),
                       dtype=np.float32)
        for version, rows in sorted(rows_by_version.items()):
            try:
                params = self.behavior_cache.get(version)
            except BehaviorParamsEvicted:
                self.behavior_cache.note_ratio_one_fallback()
                params = self.state.params
            lp = np.asarray(behavior_logp_batched(
                params, self.model_config, tokens, self.accum_steps))
            out[rows] = lp[rows]
        return out

    def train_on_batch(self, episodes: Sequence[StreamedEpisode]
                       ) -> Dict[str, float]:
        """One grpo_step over a streamed batch; returns host-float
        metrics. Mutates ``self.state``."""
        from .data import place_batch_for_mesh
        from .trainer import train_step
        _, tokens, mask, rewards, group_ids, old_logp = assemble_batch(
            episodes, pad_id=self.pad_id, max_len=self.max_len)
        if old_logp is None:
            old_logp = self._recomputed_old_logp(episodes, tokens)
        tokens, mask, rewards, group_ids, old_logp = \
            place_batch_for_mesh(self.mesh, tokens, mask, rewards,
                                 group_ids, old_logp, pad_id=self.pad_id,
                                 accum_steps=self.accum_steps)
        self.state, metrics = train_step(
            self.state, self.model_config, self.mesh, tokens, mask,
            rewards, group_ids, old_logp=old_logp,
            grpo_config=self.grpo_config, optimizer=self.optimizer,
            accum_steps=self.accum_steps)
        return {k: float(v) for k, v in metrics.items()}
