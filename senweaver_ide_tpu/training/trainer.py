"""GRPO trainer: pjit-sharded train state + one-step update.

Mesh layout (parallel/mesh.py): gradients reduce over (dp, fsdp) — XLA lowers
the all-reduce/reduce-scatter onto ICI; params and Adam moments are sharded
per ``parallel/sharding.py`` (fsdp ZeRO-style + tp Megatron-style). The same
``train_step`` runs single-chip (trivial mesh) and on a v5e-64 layout
unchanged — only the Mesh differs.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.config import ModelConfig
from ..models.transformer import Params, forward, init_params
from ..parallel.mesh import make_mesh
from ..parallel.sharding import (param_shardings, param_specs,
                                 restrict_spec, shard_params)
from .grpo import (GRPOConfig, group_relative_advantages, grpo_objective,
                   token_logprobs)


class TrainState(NamedTuple):
    params: Params
    opt_state: Any
    step: jax.Array


def make_optimizer(learning_rate: float = 1e-5, *, weight_decay: float = 0.0,
                   max_grad_norm: float = 1.0,
                   warmup_steps: int = 0) -> optax.GradientTransformation:
    if warmup_steps > 0:
        schedule = optax.linear_schedule(0.0, learning_rate, warmup_steps)
    else:
        schedule = learning_rate
    return optax.chain(
        optax.clip_by_global_norm(max_grad_norm),
        optax.adamw(schedule, b1=0.9, b2=0.95, eps=1e-8,
                    weight_decay=weight_decay),
    )


def make_train_state(config: ModelConfig, key: jax.Array,
                     mesh: Optional[Mesh] = None, *,
                     learning_rate: float = 1e-5,
                     params: Optional[Params] = None,
                     optimizer: Optional[optax.GradientTransformation] = None,
                     ) -> TrainState:
    """Init (or adopt) params, shard them onto the mesh, init sharded opt state."""
    if params is None:
        params = init_params(config, key)
    if mesh is not None:
        params = shard_params(params, mesh)
    opt = optimizer or make_optimizer(learning_rate)
    opt_state = jax.jit(opt.init)(params) if mesh is None else \
        jax.jit(opt.init,
                out_shardings=_opt_state_shardings(opt, params, mesh))(params)
    return TrainState(params=params, opt_state=opt_state,
                      step=jnp.zeros((), jnp.int32))


def _opt_state_shardings(opt, params, mesh):
    """Shardings for the optimizer state: any leaf whose (shape, dtype)
    matches a param leaf (Adam moments are param-shaped) inherits that param's
    spec; everything else (counts, scalars) replicates."""
    shapes = jax.eval_shape(opt.init, params)
    pspecs = param_specs(params)
    shape_to_spec = {}
    for leaf, spec in zip(jax.tree_util.tree_leaves(params),
                          jax.tree_util.tree_leaves(
                              pspecs, is_leaf=lambda x: isinstance(x, P))):
        shape_to_spec.setdefault((leaf.shape, leaf.dtype), spec)

    def leaf_sharding(leaf):
        spec = shape_to_spec.get((leaf.shape, leaf.dtype), P())
        return NamedSharding(mesh, restrict_spec(spec, mesh))

    return jax.tree_util.tree_map(leaf_sharding, shapes)


@functools.partial(jax.jit,
                   static_argnames=("config", "grpo_config", "num_groups",
                                    "optimizer", "mesh"))
def _grpo_step(state: TrainState, config: ModelConfig,
               optimizer: optax.GradientTransformation,
               tokens: jax.Array, completion_mask: jax.Array,
               rewards: jax.Array, group_ids: jax.Array,
               old_logp: Optional[jax.Array],
               ref_logp: Optional[jax.Array],
               grpo_config: GRPOConfig,
               num_groups: int,
               mesh: Optional[Mesh] = None,
               ) -> Tuple[TrainState, Dict[str, jax.Array]]:
    adv = group_relative_advantages(
        rewards, group_ids, num_groups,
        normalize_std=grpo_config.normalize_std,
        min_std=grpo_config.min_group_std)

    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    tgt_mask = completion_mask[:, 1:]

    def loss_fn(params):
        logits, _, moe_aux = forward(params, config, inputs, with_aux=True,
                                     mesh=mesh)
        logp = token_logprobs(logits, targets)
        olp = old_logp if old_logp is not None else jax.lax.stop_gradient(logp)
        loss, metrics = grpo_objective(logp, olp, adv, tgt_mask, grpo_config,
                                       ref_logp=ref_logp)
        if config.num_experts > 0:
            loss = loss + grpo_config.moe_aux_coef * moe_aux
            metrics = dict(metrics)
            metrics["moe_aux"] = moe_aux
        return loss, metrics

    (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        state.params)
    updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    metrics = dict(metrics)
    metrics["loss"] = loss
    metrics["grad_norm"] = optax.global_norm(grads)
    metrics["adv_mean"] = jnp.mean(adv)
    return TrainState(params=params, opt_state=opt_state,
                      step=state.step + 1), metrics


# Default optimizer instance reused across steps (hashable for jit statics).
_DEFAULT_OPT = make_optimizer()


def train_step(state: TrainState, config: ModelConfig, mesh: Optional[Mesh],
               tokens: jax.Array, completion_mask: jax.Array,
               rewards: jax.Array, group_ids: jax.Array, *,
               old_logp: Optional[jax.Array] = None,
               ref_logp: Optional[jax.Array] = None,
               grpo_config: GRPOConfig = GRPOConfig(),
               optimizer: Optional[optax.GradientTransformation] = None,
               num_groups: Optional[int] = None,
               ) -> Tuple[TrainState, Dict[str, jax.Array]]:
    """One GRPO update. tokens: (B, S) prompt+completion; completion_mask True
    on completion positions; rewards: (B,) finalReward; group_ids: (B,) prompt
    group of each trajectory."""
    opt = optimizer or _DEFAULT_OPT
    n_groups = num_groups or int(tokens.shape[0])
    if mesh is not None:
        with mesh:
            return _grpo_step(state, config, opt, tokens, completion_mask,
                              rewards, group_ids, old_logp, ref_logp,
                              grpo_config, n_groups, mesh)
    return _grpo_step(state, config, opt, tokens, completion_mask, rewards,
                      group_ids, old_logp, ref_logp, grpo_config, n_groups)
