"""GRPO trainer: pjit-sharded train state + one-step update.

Mesh layout (parallel/mesh.py): gradients reduce over (dp, fsdp) — XLA lowers
the all-reduce/reduce-scatter onto ICI; params and Adam moments are sharded
per ``parallel/sharding.py`` (fsdp ZeRO-style + tp Megatron-style). The same
``train_step`` runs single-chip (trivial mesh) and on a v5e-64 layout
unchanged — only the Mesh differs.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.config import ModelConfig
from ..models.transformer import Params, forward, init_params
from ..obs.runtime_profile import ProfiledFunction
from ..parallel.mesh import make_mesh
from ..parallel.sharding import (param_shardings, param_specs,
                                 restrict_spec, shard_params)
from .grpo import (GRPOConfig, group_relative_advantages, grpo_objective,
                   token_logprobs)


@functools.partial(jax.tree_util.register_dataclass,
                   data_fields=("params", "opt_state", "step"),
                   meta_fields=("opt",))
@dataclasses.dataclass
class TrainState:
    params: Params
    opt_state: Any
    step: jax.Array
    # The transformation whose .init built opt_state — carried as STATIC
    # pytree metadata so every train_step applies updates with the same
    # optimizer. (r2 latent bug: train_step silently fell back to a
    # module-level lr-1e-5 default whenever the caller didn't re-pass
    # the optimizer, so make_train_state(learning_rate=X) built X-scaled
    # opt_state that was then stepped at 1e-5 — the GRPO loops trained
    # ~1000x slower than configured and no pytree error surfaced because
    # both chains have identical state structure.)
    opt: Optional[optax.GradientTransformation] = None

    def _asdict(self) -> Dict[str, Any]:
        """Array fields only (checkpoint serialization surface — the
        optimizer is code, not state; restore re-attaches it)."""
        return {"params": self.params, "opt_state": self.opt_state,
                "step": self.step}


@functools.lru_cache(maxsize=64)
def make_optimizer(learning_rate: float = 1e-5, *, weight_decay: float = 0.0,
                   max_grad_norm: float = 1.0,
                   warmup_steps: int = 0) -> optax.GradientTransformation:
    """Cached by config: equal arguments return the SAME transformation
    instance, so jit caches keyed on the (static) optimizer are shared
    across TrainStates instead of recompiling per state."""
    if warmup_steps > 0:
        schedule = optax.linear_schedule(0.0, learning_rate, warmup_steps)
    else:
        schedule = learning_rate
    return optax.chain(
        optax.clip_by_global_norm(max_grad_norm),
        optax.adamw(schedule, b1=0.9, b2=0.95, eps=1e-8,
                    weight_decay=weight_decay),
    )


def make_train_state(config: ModelConfig, key: jax.Array,
                     mesh: Optional[Mesh] = None, *,
                     learning_rate: float = 1e-5,
                     params: Optional[Params] = None,
                     optimizer: Optional[optax.GradientTransformation] = None,
                     ) -> TrainState:
    """Init (or adopt) params, shard them onto the mesh, init sharded opt state."""
    if params is None:
        params = init_params(config, key)
    if mesh is not None:
        params = shard_params(params, mesh)
    opt = optimizer or make_optimizer(learning_rate)
    opt_state = jax.jit(opt.init)(params) if mesh is None else \
        jax.jit(opt.init,
                out_shardings=_opt_state_shardings(opt, params, mesh))(params)
    return TrainState(params=params, opt_state=opt_state,
                      step=jnp.zeros((), jnp.int32), opt=opt)


def make_lora_train_state(config: ModelConfig, base_params: Params,
                          key: jax.Array, mesh: Optional[Mesh] = None, *,
                          rank: int = 16, alpha: Optional[float] = None,
                          targets: Optional[Tuple[str, ...]] = None,
                          learning_rate: float = 1e-4,
                          optimizer: Optional[
                              optax.GradientTransformation] = None,
                          ) -> TrainState:
    """TrainState whose params are ONLY the LoRA adapters for
    ``base_params`` (training/lora.py): pass the frozen base to
    ``train_step(..., lora_base=base_params)``. Adapters are replicated
    on the mesh (they are tiny; the base keeps its own shardings)."""
    from .lora import DEFAULT_TARGETS, init_lora
    wq = base_params["layers"]["wq"]
    expect = (config.num_layers, config.hidden_size, config.q_dim)
    if tuple(wq.shape) != expect:
        # adapter shapes come from config; a mismatched base would only
        # explode later, deep inside the jitted step
        raise ValueError(f"base_params do not match config "
                         f"{config.name!r}: wq {tuple(wq.shape)} != "
                         f"{expect}")
    lora = init_lora(config, key, rank=rank, alpha=alpha,
                     targets=targets or DEFAULT_TARGETS)
    if mesh is not None:
        repl = NamedSharding(mesh, P())
        lora = jax.device_put(lora, repl)
    opt = optimizer or make_optimizer(learning_rate)
    opt_state = jax.jit(opt.init)(lora)
    return TrainState(params=lora, opt_state=opt_state,
                      step=jnp.zeros((), jnp.int32), opt=opt)


def _opt_state_shardings(opt, params, mesh):
    """Shardings for the optimizer state: any leaf whose (shape, dtype)
    matches a param leaf (Adam moments are param-shaped) inherits that param's
    spec; everything else (counts, scalars) replicates."""
    shapes = jax.eval_shape(opt.init, params)
    pspecs = param_specs(params)
    shape_to_spec = {}
    for leaf, spec in zip(jax.tree_util.tree_leaves(params),
                          jax.tree_util.tree_leaves(
                              pspecs, is_leaf=lambda x: isinstance(x, P))):
        shape_to_spec.setdefault((leaf.shape, leaf.dtype), spec)

    def leaf_sharding(leaf):
        spec = shape_to_spec.get((leaf.shape, leaf.dtype), P())
        return NamedSharding(mesh, restrict_spec(spec, mesh))

    return jax.tree_util.tree_map(leaf_sharding, shapes)


@functools.partial(jax.jit,
                   static_argnames=("config", "grpo_config", "num_groups",
                                    "optimizer", "mesh", "accum_steps"))
def _grpo_step(state: TrainState, config: ModelConfig,
                     optimizer: optax.GradientTransformation,
                     tokens: jax.Array, completion_mask: jax.Array,
                     rewards: jax.Array, group_ids: jax.Array,
                     old_logp: Optional[jax.Array],
                     ref_logp: Optional[jax.Array],
                     branch_mask: Optional[jax.Array],
                     grpo_config: GRPOConfig,
                     num_groups: int,
                     accum_steps: int,
                     mesh: Optional[Mesh] = None,
                     lora_base: Optional[Params] = None,
                     ) -> Tuple[TrainState, Dict[str, jax.Array]]:
    """The GRPO step — always the accumulated form; ``accum_steps=1``
    is a length-1 scan and IS the monolithic step (single implementation,
    no second code path to keep in sync). Larger ``accum_steps`` splits
    the batch into sequentially-scanned microbatches holding one
    microbatch's activations at a time — how a 7B policy trains on long
    trajectories that don't fit as one batch (SURVEY.md §7 hard part
    'long-trajectory memory', alongside remat and ring attention).

    Equivalence to the monolithic step: advantages are group-relative
    over the FULL batch (computed before the split — group members may
    land in different microbatches), and each microbatch's gradient is
    weighted by its share of completion tokens, so the accumulated
    gradient equals the full-batch token-normalized objective's. The MoE
    aux loss uses the same weights (token-share weighting of a
    batch-mean term — exact when microbatches have equal token counts).
    """
    b = tokens.shape[0]
    if b % accum_steps != 0:
        raise ValueError(f"batch {b} not divisible by accum_steps "
                         f"{accum_steps}")
    adv = group_relative_advantages(
        rewards, group_ids, num_groups,
        normalize_std=grpo_config.normalize_std,
        min_std=grpo_config.min_group_std,
        leave_one_out=grpo_config.leave_one_out)

    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    tgt_mask = completion_mask[:, 1:]
    total_denom = jnp.maximum(jnp.sum(tgt_mask), 1.0)

    def micro(x):
        return x.reshape(accum_steps, b // accum_steps, *x.shape[1:])

    # lax.scan xs can't carry None leaves: absent ref_logp scans zeros
    # and the static has_ref closure keeps the KL term genuinely off.
    has_ref = ref_logp is not None
    has_old = old_logp is not None
    has_branch = branch_mask is not None
    zeros_f32 = jnp.zeros_like(micro(targets), dtype=jnp.float32)
    scan_xs = (micro(inputs), micro(targets), micro(tgt_mask), micro(adv),
               micro(ref_logp) if has_ref else zeros_f32,
               micro(old_logp) if has_old else zeros_f32,
               # branch mask is (B, S) like completion_mask; the shift
               # to target layout mirrors tgt_mask above.
               micro(branch_mask[:, 1:].astype(jnp.float32))
               if has_branch else zeros_f32)

    def loss_fn(params, m_in, m_tgt, m_mask, m_adv, m_ref, m_old, m_branch):
        if lora_base is not None:
            # LoRA: `params` is the adapter tree; the frozen base rides
            # as a closed-over constant — gradients and optimizer state
            # exist only for the adapters (training/lora.py).
            from .lora import merge_lora
            model_params = merge_lora(lora_base, params)
        else:
            model_params = params
        logits, _, moe_aux = forward(model_params, config, m_in,
                                     with_aux=True, mesh=mesh)
        logp = token_logprobs(logits, m_tgt)
        olp = m_old if has_old else jax.lax.stop_gradient(logp)
        loss, metrics = grpo_objective(
            logp, olp, m_adv, m_mask, grpo_config,
            ref_logp=m_ref if has_ref else None,
            branch_mask=m_branch if has_branch else None)
        if config.num_experts > 0:
            loss = loss + grpo_config.moe_aux_coef * moe_aux
        return loss, (metrics, moe_aux)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    zero_grads = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), state.params)

    # Same metrics schema as the monolithic step: every per-token-
    # normalized metric weight-sums across microbatches exactly like the
    # loss does.
    acc_keys = ("pg_loss", "kl", "entropy", "ratio_mean", "clip_frac",
                "grad_sparsity")
    if has_branch:
        acc_keys = acc_keys + ("branch_token_frac",)

    def body(carry, m):
        grads_acc, loss_acc, metr_acc = carry
        m_in, m_tgt, m_mask, m_adv, m_ref, m_old, m_branch = m
        (loss, (metrics, moe_aux)), grads = grad_fn(
            state.params, m_in, m_tgt, m_mask, m_adv, m_ref, m_old,
            m_branch)
        w = jnp.maximum(jnp.sum(m_mask), 0.0) / total_denom
        grads_acc = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(jnp.float32) * w, grads_acc, grads)
        metr_acc = {k: metr_acc[k] + metrics[k] * w for k in acc_keys}
        metr_acc["moe_aux"] = metr_acc.get("moe_aux", 0.0) + moe_aux * w
        return (grads_acc, loss_acc + loss * w, metr_acc), None

    zero_metrics = {k: jnp.zeros(()) for k in acc_keys}
    zero_metrics["moe_aux"] = jnp.zeros(())
    (grads, loss, metr), _ = jax.lax.scan(
        body, (zero_grads, jnp.zeros(()), zero_metrics), scan_xs)
    grads = jax.tree_util.tree_map(
        lambda g, p: g.astype(p.dtype), grads, state.params)

    updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    metrics = dict(metr)
    if config.num_experts == 0:
        del metrics["moe_aux"]
    metrics["loss"] = loss
    metrics["grad_norm"] = optax.global_norm(grads)
    metrics["adv_mean"] = jnp.mean(adv)
    # Carry the optimizer that ACTUALLY produced this opt_state — if the
    # caller passed one explicitly into a state built without, the next
    # step must keep using it, not fall back to the module default.
    return TrainState(params=params, opt_state=opt_state,
                      step=state.step + 1, opt=optimizer), metrics


# Default optimizer instance reused across steps (hashable for jit statics).
_DEFAULT_OPT = make_optimizer()

# Runtime observatory wiring (obs/runtime_profile.py): compile/retrace
# ledger for the GRPO update. ``block=False`` keeps the async-dispatch
# contract below (the span comment in train_step) — the step histogram
# records dispatch; device time stays with rl_loop's train_s, which
# obs/telemetry.py combines with this ledger's cost_analysis FLOPs for
# the measured MFU. State/config/optimizer trees are shape-stable and
# skipped from the signature scan (retraces they cause still count via
# the jit cache).
_grpo_step = ProfiledFunction(
    _grpo_step, "trainer.grpo_step", skip_args=(0, 1, 2),
    skip_kwargs=("mesh", "lora_base"), block=False)


def train_step(state: TrainState, config: ModelConfig, mesh: Optional[Mesh],
               tokens: jax.Array, completion_mask: jax.Array,
               rewards: jax.Array, group_ids: jax.Array, *,
               old_logp: Optional[jax.Array] = None,
               ref_logp: Optional[jax.Array] = None,
               branch_mask: Optional[jax.Array] = None,
               grpo_config: GRPOConfig = GRPOConfig(),
               optimizer: Optional[optax.GradientTransformation] = None,
               num_groups: Optional[int] = None,
               accum_steps: int = 1,
               lora_base: Optional[Params] = None,
               ) -> Tuple[TrainState, Dict[str, jax.Array]]:
    """One GRPO update. tokens: (B, S) prompt+completion; completion_mask True
    on completion positions; rewards: (B,) finalReward; group_ids: (B,) prompt
    group of each trajectory. ``accum_steps > 1`` splits the batch into
    sequentially-scanned microbatches (one microbatch of activations
    resident at a time) with token-share-weighted gradient accumulation —
    equivalent update, fraction of the memory.

    Optimizer resolution: an explicit ``optimizer`` wins, else the
    transformation the state was BUILT with (``state.opt``), else the
    module default — never a silent mismatch with the opt_state."""
    from ..models.quantize import is_quantized
    if is_quantized(state.params):
        # einsum would silently promote unscaled int8 → garbage grads
        raise TypeError(
            "train_step received int8-quantized params "
            "(models/quantize.py) — quantization is a SERVING transform; "
            "train on the full-precision state and publish quantized")
    # An int8 lora_base is ALLOWED: adapters differentiate through the
    # dequant epilogue wrt activations only (QLoRA; training/lora.py).
    opt = optimizer or state.opt or _DEFAULT_OPT
    n_groups = num_groups or int(tokens.shape[0])
    args = (state, config, opt, tokens, completion_mask, rewards, group_ids,
            old_logp, ref_logp, branch_mask, grpo_config, n_groups,
            accum_steps)
    # Span measures DISPATCH of the jitted step (results are async);
    # callers wanting completion time force with float()/block_until_ready
    # inside their own enclosing span (rl_loop does).
    from ..obs import get_tracer
    with get_tracer().span("trainer.grpo_step",
                           batch=int(tokens.shape[0]),
                           accum_steps=accum_steps):
        if mesh is not None:
            with mesh:
                return _grpo_step(*args, mesh=mesh, lora_base=lora_base)
        return _grpo_step(*args, lora_base=lora_base)


def train_step_guarded(state: TrainState, config: ModelConfig,
                       mesh: Optional[Mesh],
                       tokens: jax.Array, completion_mask: jax.Array,
                       rewards: jax.Array, group_ids: jax.Array, *,
                       guard, **kwargs
                       ) -> Tuple[TrainState, Dict[str, float],
                                  Optional[str]]:
    """``train_step`` behind a resilience.UpdateGuard.

    Runs the update, syncs the metrics to host floats (forcing device
    completion), and asks ``guard`` whether to ADOPT the new state.
    Returns ``(state, float_metrics, skip_reason)`` — on a veto the
    returned state is the INPUT state (params and optimizer moments
    untouched by the non-finite/spiking update) and ``skip_reason`` is
    the guard's verdict; otherwise ``skip_reason`` is None. A ``guard``
    of None degrades to plain train_step with float metrics."""
    new_state, metrics = train_step(state, config, mesh, tokens,
                                    completion_mask, rewards, group_ids,
                                    **kwargs)
    float_metrics = {k: float(v) for k, v in metrics.items()}
    if guard is None:
        return new_state, float_metrics, None
    reason = guard.check(float_metrics)
    if reason is not None:
        return state, float_metrics, reason
    return new_state, float_metrics, None
