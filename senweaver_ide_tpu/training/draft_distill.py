"""Online DRAFT distillation from fleet speculation outcomes.

FastGRPO's failure mode (PAPERS.md): during RL the target policy keeps
moving, so a frozen speculation draft's acceptance rate — and with it
the entire speculative speedup — decays with every weight publish. The
serving engine already harvests the perfect supervision signal for
free: every verification round records the context it speculated from
and the tokens the TARGET actually chose (accepted proposals plus the
correction token that ended the round). Those pairs are exactly the
sequences the draft must imitate to raise its acceptance rate, and they
cost zero extra forward passes — they fall out of the fused
draft+verify step.

:class:`DraftDistiller` closes the loop:

    engine.drain_spec_outcomes() → ring buffer → CE steps on the draft
        → publisher.publish_draft(...)   (fleet, (epoch, version) fence)
        → engine.update_draft_params(...) (single engine)

Correctness never depends on any of this — greedy speculative decoding
is exact for an arbitrarily bad draft — so the distiller can run lazily
between serving bursts and publish without draining in-flight work.
Only the acceptance EMA (throughput) moves.

The jitted update is shared with the offline path
(``rollout.speculative._distill_step``): one CE step over (B, S) token
batches with a train-position mask. Batches are padded to a CONSTANT
batch size and a power-of-two width so the step compiles once per
width bucket, never per batch (JIT110 discipline).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer import ModelConfig, Params
from ..rollout.speculative import _distill_step


class DraftDistiller:
    """Continually distill a speculation draft toward the serving target
    using the (context, target-chosen tokens) pairs the engine's fused
    verification step records.

    Not thread-safe: run it from one loop (the trainer's) and hand the
    result to the fleet through the fenced
    :meth:`WeightPublisher.publish_draft` path.
    """

    def __init__(self, draft_params: Params, draft_config: ModelConfig, *,
                 learning_rate: float = 1e-3, buffer_size: int = 1024,
                 batch_size: int = 8, max_len: int = 256, pad_id: int = 0,
                 seed: int = 0, registry=None):
        import optax
        self.params = draft_params
        self.config = draft_config
        self.optimizer = optax.adam(learning_rate)
        self.opt_state = jax.jit(self.optimizer.init)(draft_params)
        # Ring buffer of (tokens, n_trained_tail): the final
        # ``n_trained_tail`` positions carry the CE mask — they are the
        # tokens the TARGET chose during verification; everything
        # before is conditioning context.
        self.buffer: List[Tuple[List[int], int]] = []
        self.buffer_size = int(buffer_size)
        self.batch_size = int(batch_size)
        self.max_len = int(max_len)
        self.pad_id = int(pad_id)
        self.steps = 0
        self.harvested = 0
        self.version = 0        # last version handed to publish/install
        self._rng = np.random.default_rng(seed)
        if registry is None:
            from ..obs import get_registry
            registry = get_registry()
        self._steps_total = registry.counter(
            "senweaver_spec_distill_steps_total",
            "Draft distillation CE steps taken.")
        self._harvested_total = registry.counter(
            "senweaver_spec_distill_outcomes_total",
            "Verification outcomes harvested into the distill buffer.")
        self._loss_gauge = registry.gauge(
            "senweaver_spec_distill_loss",
            "Cross-entropy of the draft on target-chosen tokens "
            "(last step).")

    # -- data intake ---------------------------------------------------------
    def observe(self, context: Sequence[int],
                targets: Sequence[int]) -> None:
        """Record one verification outcome: ``targets`` are the tokens
        the target chose immediately after ``context``."""
        if not targets:
            return
        seq = (list(context) + list(targets))[-self.max_len:]
        n_out = min(len(targets), len(seq))
        self.buffer.append((seq, n_out))
        if len(self.buffer) > self.buffer_size:
            del self.buffer[:len(self.buffer) - self.buffer_size]

    def harvest(self, engine) -> int:
        """Drain one engine's buffered speculation outcomes into the
        buffer; returns how many were taken. Safe to call every round —
        draining is O(outcomes) and clears the engine's ring."""
        outcomes: List[Dict] = engine.drain_spec_outcomes()
        for o in outcomes:
            self.observe(o["context"], o["targets"])
        self.harvested += len(outcomes)
        if outcomes:
            self._harvested_total.inc(len(outcomes))
        return len(outcomes)

    # -- optimisation --------------------------------------------------------
    def step(self) -> float:
        """One CE update over a uniform sample of the buffer. Returns
        the loss (0.0 when the buffer is empty)."""
        if not self.buffer:
            return 0.0
        idx = self._rng.choice(len(self.buffer),
                               size=min(self.batch_size, len(self.buffer)),
                               replace=False)
        picked = [self.buffer[i] for i in idx]
        # Constant batch rows + power-of-two width: both axes shape-
        # stable so the jitted step compiles once per width bucket.
        width = 16
        need = min(self.max_len, max(len(seq) for seq, _ in picked))
        while width < need:
            width *= 2
        toks = np.full((self.batch_size, width), self.pad_id, np.int32)
        mask = np.zeros((self.batch_size, width), bool)
        for i, (seq, n_out) in enumerate(picked):
            seq = seq[-width:]
            n = min(n_out, len(seq))
            toks[i, :len(seq)] = seq
            mask[i, len(seq) - n:len(seq)] = True
        self.params, self.opt_state, loss = _distill_step(
            self.params, self.opt_state, self.config, self.optimizer,
            jnp.asarray(toks), jnp.asarray(mask))
        self.steps += 1
        self._steps_total.inc()
        out = float(loss)
        self._loss_gauge.set(out)
        return out

    def run(self, steps: int) -> float:
        """``steps`` CE updates; returns the final loss."""
        loss = 0.0
        for _ in range(max(0, int(steps))):
            loss = self.step()
        return loss

    # -- publication ---------------------------------------------------------
    def publish(self, publisher, *, epoch: Optional[int] = None,
                version: Optional[int] = None) -> int:
        """Republish the improved draft fleet-wide through the fenced
        :meth:`WeightPublisher.publish_draft` path (no drain — drafts
        cannot affect correctness). Returns the accepted version."""
        self.version = publisher.publish_draft(self.params, epoch=epoch,
                                               version=version)
        return self.version

    def install(self, engine, *, version: Optional[int] = None) -> int:
        """Single-engine path: swap the draft directly via
        ``engine.update_draft_params`` (tests, one-box serving)."""
        self.version = self.version + 1 if version is None else int(version)
        engine.update_draft_params(self.params, version=self.version)
        return self.version

    def round(self, engines: Sequence, *, steps: int = 4,
              publisher=None) -> float:
        """One full loop turn: harvest every engine, take ``steps``
        updates, then publish (fleet) or install (each engine
        directly). Returns the final loss."""
        for e in engines:
            self.harvest(e)
        loss = self.run(steps)
        if not self.buffer:
            return loss
        if publisher is not None:
            self.publish(publisher)
        else:
            v = self.version + 1
            for e in engines:
                self.install(e, version=v)
        return loss
