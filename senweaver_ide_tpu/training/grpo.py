"""GRPO: group-relative policy optimization for the local policy LLM.

The reference's "optimization step" is a black-box prompt edit shipped to a
backend (``apoService.ts`` textual gradient / beam search). The TPU build
upgrades it to weight updates (BASELINE north star): finalReward from the jit
reward head → group-relative advantages over response groups per prompt (no
critic) → PPO-style clipped token-level objective, gradients all-reduced over
ICI by XLA (mesh dp/fsdp axes).

Design notes from the GRPO literature (PAPERS.md, "Policy Gradient
Foundations of GRPO"): group mean-centering is the unbiased part; dividing by
the group std reweights sparse-reward groups and can collapse ranks when a
group's rewards tie — so std normalization is optional
(``normalize_std=False`` keeps plain centered advantages), and a minimum-std
floor guards the division.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class GRPOConfig(NamedTuple):
    clip_eps: float = 0.2
    kl_coef: float = 0.04        # KL penalty vs the reference (frozen) policy
    entropy_coef: float = 0.0
    normalize_std: bool = True
    min_group_std: float = 1e-4
    moe_aux_coef: float = 0.01   # MoE load-balance weight (num_experts > 0)


def group_relative_advantages(
    rewards: jax.Array,          # (B,) finalReward per trajectory
    group_ids: jax.Array,        # (B,) int32 — trajectories with the same id
                                 # were sampled from the same prompt
    num_groups: int,
    *,
    normalize_std: bool = True,
    min_std: float = 1e-4,
) -> jax.Array:
    """Center (and optionally scale) rewards within each prompt group."""
    ones = jnp.ones_like(rewards)
    counts = jax.ops.segment_sum(ones, group_ids, num_segments=num_groups)
    counts = jnp.maximum(counts, 1.0)
    sums = jax.ops.segment_sum(rewards, group_ids, num_segments=num_groups)
    means = sums / counts
    centered = rewards - means[group_ids]
    if not normalize_std:
        return centered
    sq = jax.ops.segment_sum(centered * centered, group_ids,
                             num_segments=num_groups)
    std = jnp.sqrt(sq / counts)
    return centered / jnp.maximum(std[group_ids], min_std)


def token_logprobs(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """(B, S, V) fp32 logits + (B, S) targets → (B, S) log p(target)."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return tgt - logz


def grpo_objective(
    logp: jax.Array,             # (B, S) current-policy completion logprobs
    old_logp: jax.Array,         # (B, S) behavior-policy logprobs (sampled)
    advantages: jax.Array,       # (B,)
    mask: jax.Array,             # (B, S) True on completion tokens
    config: GRPOConfig = GRPOConfig(),
    ref_logp: Optional[jax.Array] = None,  # (B, S) frozen reference policy
) -> tuple:
    """Clipped surrogate + KL penalty. Returns (loss, metrics dict)."""
    mask = mask.astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    adv = advantages[:, None]

    ratio = jnp.exp(logp - old_logp)
    unclipped = ratio * adv
    clipped = jnp.clip(ratio, 1.0 - config.clip_eps,
                       1.0 + config.clip_eps) * adv
    pg_loss = -jnp.sum(jnp.minimum(unclipped, clipped) * mask) / denom

    kl = jnp.zeros(())
    if ref_logp is not None and config.kl_coef > 0.0:
        # k3 estimator (Schulman): unbiased, positive.
        log_ratio = ref_logp - logp
        kl_per_tok = jnp.exp(log_ratio) - log_ratio - 1.0
        kl = jnp.sum(kl_per_tok * mask) / denom

    # Entropy bonus via the sampled-surprisal estimator E[-log p(x)] = H:
    # the objective only sees target logps (full logits never reach it),
    # so the bonus is a -logp penalty on sampled tokens — anti-collapse
    # pressure that keeps exploration alive when a group's rewards go
    # uniform (zero advantage) and nothing else pushes back. Exact only
    # in expectation (the score-function term of ∇H is dropped), which
    # is the standard confidence-penalty regularizer trade.
    entropy = -jnp.sum(logp * mask) / denom

    loss = (pg_loss + config.kl_coef * kl
            - config.entropy_coef * entropy)
    metrics = {
        "pg_loss": pg_loss,
        "kl": kl,
        "entropy": entropy,
        "ratio_mean": jnp.sum(ratio * mask) / denom,
        "clip_frac": jnp.sum((jnp.abs(ratio - 1.0) > config.clip_eps) * mask)
        / denom,
    }
    return loss, metrics
