"""GRPO: group-relative policy optimization for the local policy LLM.

The reference's "optimization step" is a black-box prompt edit shipped to a
backend (``apoService.ts`` textual gradient / beam search). The TPU build
upgrades it to weight updates (BASELINE north star): finalReward from the jit
reward head → group-relative advantages over response groups per prompt (no
critic) → PPO-style clipped token-level objective, gradients all-reduced over
ICI by XLA (mesh dp/fsdp axes).

Design notes from the GRPO literature (PAPERS.md, "Policy Gradient
Foundations of GRPO"): group mean-centering is the unbiased part; dividing by
the group std reweights sparse-reward groups and can collapse ranks when a
group's rewards tie — so std normalization is optional
(``normalize_std=False`` keeps plain centered advantages), and a minimum-std
floor guards the division.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class GRPOConfig(NamedTuple):
    clip_eps: float = 0.2
    kl_coef: float = 0.04        # KL penalty vs the reference (frozen) policy
    entropy_coef: float = 0.0
    normalize_std: bool = True
    min_group_std: float = 1e-4
    moe_aux_coef: float = 0.01   # MoE load-balance weight (num_experts > 0)
    # Health-guarded mitigations (training/diagnostics.py detectors,
    # resilience/guard.py HealthMitigator triggers) — default OFF so
    # every existing caller keeps the exact historical objective:
    # RLOO leave-one-out baseline (unnormalized by construction —
    # dividing by the same group's std would reintroduce the rank
    # coupling the mitigation exists to remove).
    leave_one_out: bool = False
    # Per-token credit: weight each completion token's advantage by a
    # gamma-decay toward the reward (later tokens closer to the episode
    # outcome carry more credit), normalized to mean 1 per sequence so
    # the loss scale is unchanged. gamma=1.0 is exactly uniform credit.
    token_level_advantages: bool = False
    token_adv_gamma: float = 0.98
    # Tree-rollout credit sharpening (2606.29238: branch points are
    # where per-token credit is sharpest): boost the credit weight of
    # tokens AT recorded branch positions by (1 + boost), renormalized
    # to mean 1 so the loss scale is unchanged. 0.0 = off (exact
    # historical objective); only engages when the batch carries a
    # branch mask (tree-planner trajectories).
    branch_credit_boost: float = 0.0


def group_relative_advantages(
    rewards: jax.Array,          # (B,) finalReward per trajectory
    group_ids: jax.Array,        # (B,) int32 — trajectories with the same id
                                 # were sampled from the same prompt
    num_groups: int,
    *,
    normalize_std: bool = True,
    min_std: float = 1e-4,
    leave_one_out: bool = False,
) -> jax.Array:
    """Center (and optionally scale) rewards within each prompt group.

    ``leave_one_out=True`` is the RLOO baseline: each trajectory is
    compared against the mean of the OTHER group members,
    ``adv_i = r_i - mean(group \\ i) = (n/(n-1)) * (r_i - mean)``.
    RLOO advantages are returned UNNORMALIZED (``normalize_std`` is
    ignored): the point of the mitigation is to decouple a trajectory's
    scale from its own group's spread."""
    ones = jnp.ones_like(rewards)
    counts = jax.ops.segment_sum(ones, group_ids, num_segments=num_groups)
    counts = jnp.maximum(counts, 1.0)
    sums = jax.ops.segment_sum(rewards, group_ids, num_segments=num_groups)
    means = sums / counts
    centered = rewards - means[group_ids]
    if leave_one_out:
        # n=1 groups mean-center to zero either way; clamp keeps the
        # scale factor finite there.
        factor = counts / jnp.maximum(counts - 1.0, 1.0)
        return centered * factor[group_ids]
    if not normalize_std:
        return centered
    sq = jax.ops.segment_sum(centered * centered, group_ids,
                             num_segments=num_groups)
    std = jnp.sqrt(sq / counts)
    return centered / jnp.maximum(std[group_ids], min_std)


def token_credit_weights(mask: jax.Array, gamma: float) -> jax.Array:
    """(B, S) per-token credit weights: ``gamma``-decay from the LAST
    masked token backward (tokens nearer the reward carry more credit),
    normalized to mean 1 over each row's masked tokens so multiplying a
    sequence-level advantage by the weights preserves the loss scale.
    Rows with no masked tokens return zeros; ``gamma=1`` returns the
    mask itself (uniform credit)."""
    m = mask.astype(jnp.float32)
    n_tok = jnp.sum(m, axis=-1, keepdims=True)            # (B, 1)
    # 0-based position among the row's MASKED tokens.
    pos = jnp.cumsum(m, axis=-1) - 1.0
    w = jnp.power(jnp.float32(gamma), jnp.maximum(n_tok - 1.0 - pos,
                                                  0.0)) * m
    norm = jnp.sum(w, axis=-1, keepdims=True)
    return w * n_tok / jnp.maximum(norm, 1e-30)


def branch_credit_weights(mask: jax.Array, branch_mask: jax.Array, *,
                          gamma: float, boost: float) -> jax.Array:
    """(B, S) credit weights for tree-planner trajectories: the
    gamma-decay base of :func:`token_credit_weights`, with tokens at
    recorded BRANCH positions scaled by ``1 + boost`` — the split
    points are where sibling leaves actually diverged, so they carry
    the sharpest group-relative credit signal. Renormalized to mean 1
    over each row's masked tokens, so the loss scale (and ``boost=0``
    behavior) is exactly the unboosted weighting."""
    base = token_credit_weights(mask, gamma)
    m = mask.astype(jnp.float32)
    b = branch_mask.astype(jnp.float32) * m
    w = base * (1.0 + jnp.float32(boost) * b)
    n_tok = jnp.sum(m, axis=-1, keepdims=True)
    norm = jnp.sum(w, axis=-1, keepdims=True)
    return w * n_tok / jnp.maximum(norm, 1e-30)


def token_logprobs(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """(B, S, V) fp32 logits + (B, S) targets → (B, S) log p(target)."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return tgt - logz


def grpo_objective(
    logp: jax.Array,             # (B, S) current-policy completion logprobs
    old_logp: jax.Array,         # (B, S) behavior-policy logprobs (sampled)
    advantages: jax.Array,       # (B,) per-trajectory, or (B, S) per-token
    mask: jax.Array,             # (B, S) True on completion tokens
    config: GRPOConfig = GRPOConfig(),
    ref_logp: Optional[jax.Array] = None,  # (B, S) frozen reference policy
    branch_mask: Optional[jax.Array] = None,  # (B, S) 1 at branch points
) -> tuple:
    """Clipped surrogate + KL penalty. Returns (loss, metrics dict).

    ``advantages`` may be per-trajectory (B,) — the historical shape —
    or already per-token (B, S). With ``config.token_level_advantages``
    a (B,) advantage is spread over the response mask with
    :func:`token_credit_weights` (gamma-decay toward the reward) instead
    of broadcast uniformly; a ``branch_mask`` (tree-planner
    trajectories) with ``config.branch_credit_boost > 0`` additionally
    sharpens credit at the recorded split points via
    :func:`branch_credit_weights`."""
    mask = mask.astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    if advantages.ndim == 2:
        adv = advantages
    else:
        adv = advantages[:, None]
        if branch_mask is not None and config.branch_credit_boost > 0.0:
            adv = adv * branch_credit_weights(
                mask, branch_mask,
                gamma=(config.token_adv_gamma
                       if config.token_level_advantages else 1.0),
                boost=config.branch_credit_boost)
        elif config.token_level_advantages:
            adv = adv * token_credit_weights(mask, config.token_adv_gamma)

    ratio = jnp.exp(logp - old_logp)
    unclipped = ratio * adv
    clipped = jnp.clip(ratio, 1.0 - config.clip_eps,
                       1.0 + config.clip_eps) * adv
    pg_loss = -jnp.sum(jnp.minimum(unclipped, clipped) * mask) / denom

    kl = jnp.zeros(())
    if ref_logp is not None and config.kl_coef > 0.0:
        # k3 estimator (Schulman): unbiased, positive.
        log_ratio = ref_logp - logp
        kl_per_tok = jnp.exp(log_ratio) - log_ratio - 1.0
        kl = jnp.sum(kl_per_tok * mask) / denom

    # Entropy bonus via the sampled-surprisal estimator E[-log p(x)] = H:
    # the objective only sees target logps (full logits never reach it),
    # so the bonus is a -logp penalty on sampled tokens — anti-collapse
    # pressure that keeps exploration alive when a group's rewards go
    # uniform (zero advantage) and nothing else pushes back. Exact only
    # in expectation (the score-function term of ∇H is dropped), which
    # is the standard confidence-penalty regularizer trade.
    entropy = -jnp.sum(logp * mask) / denom

    loss = (pg_loss + config.kl_coef * kl
            - config.entropy_coef * entropy)

    # Gradient-sparsity diagnostic (2606.29238's sparse-gradient failure
    # mode): the surrogate's per-token gradient wrt logp is
    # ratio*adv where the clip isn't binding against the advantage's
    # direction, and exactly 0 where it is — so a per-example RMS norm
    # of that closed form is the cheap stand-in for a per-example
    # parameter-gradient norm. The fraction of examples whose norm is
    # ~0 (zero-advantage groups, fully-clipped rows) is the share of
    # the batch contributing NO learning signal this step.
    clip_active = jnp.where(adv >= 0.0,
                            ratio <= 1.0 + config.clip_eps,
                            ratio >= 1.0 - config.clip_eps)
    g_tok = ratio * adv * clip_active.astype(jnp.float32) * mask
    tok_counts = jnp.sum(mask, axis=-1)
    ex_norm = jnp.sqrt(jnp.sum(g_tok * g_tok, axis=-1)
                       / jnp.maximum(tok_counts, 1.0))
    has_tok = (tok_counts > 0.0).astype(jnp.float32)
    near_zero = (ex_norm < 1e-6).astype(jnp.float32) * has_tok
    grad_sparsity = jnp.sum(near_zero) / jnp.maximum(jnp.sum(has_tok), 1.0)

    metrics = {
        "pg_loss": pg_loss,
        "kl": kl,
        "entropy": entropy,
        "ratio_mean": jnp.sum(ratio * mask) / denom,
        "clip_frac": jnp.sum((jnp.abs(ratio - 1.0) > config.clip_eps) * mask)
        / denom,
        "grad_sparsity": grad_sparsity,
    }
    if branch_mask is not None:
        bm = branch_mask.astype(jnp.float32) * mask
        metrics["branch_token_frac"] = jnp.sum(bm) / denom
    return loss, metrics
