from .grpo import (GRPOConfig, group_relative_advantages, grpo_objective,
                   token_credit_weights, token_logprobs)
from .diagnostics import (DiagnosticsConfig, advantage_stats,
                          dispatch_round_health, finalize_round_health)
from .trainer import (TrainState, make_lora_train_state, make_optimizer,
                      make_train_state, train_step, train_step_guarded)
from .lora import (export_peft_adapter, init_lora, load_peft_adapter,
                   lora_param_count, materialize_lora, merge_lora,
                   split_lora)
from .checkpoint import CheckpointManager
from .data import (Trajectory, TrajectoryDataset, make_batch,
                   make_batch_logps)
from .async_loop import AsyncGRPOTrainer, AsyncRoundResult
from .experience import (BehaviorParamsCache, BehaviorParamsEvicted,
                         ExperienceQueue, StreamedEpisode,
                         StreamingTrainerAdapter, assemble_batch,
                         trajectories_to_episodes)
from .rl_loop import (CollectResult, EpisodeRecord, GroupSizeScheduler,
                      RoundResult, collect_group_trajectories, grpo_round)
from .online import OnlineImprovementLoop, OnlineRoundResult
from .draft_distill import DraftDistiller
