from .grpo import (GRPOConfig, group_relative_advantages, grpo_objective,
                   token_logprobs)
from .trainer import (TrainState, make_optimizer, make_train_state, train_step)
from .checkpoint import CheckpointManager
