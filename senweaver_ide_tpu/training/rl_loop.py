"""The closed GRPO loop: tasks → grouped rollouts → rewards → update.

This is the system SURVEY.md §7's architecture diagram describes end to
end: the rollout engine samples G trajectories per task (the GRPO group),
each driven through a fully-wired RolloutSession (tools, subagents,
traces), the 9-dim reward head scores each episode's trace, group-relative
advantages are computed per task, and the policy takes a clipped-objective
step — replacing the reference's backend-LLM prompt optimization with
local weight updates (apoService.ts:992-1215's optimizer moves in-tree).

Credit assignment: every LLM call inside an episode becomes one
trajectory carrying the episode's finalReward (the per-call token streams
come from EnginePolicyClient.record_calls — no re-tokenization drift);
group ids are per task so advantages compare alternative episodes of the
SAME task.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

import jax.numpy as jnp

from ..obs import StepTelemetry, get_registry, get_tracer
from ..rollout.session import RolloutSession
from .data import (Trajectory, make_batch, make_batch_logps,
                   place_batch_for_mesh)
from .grpo import GRPOConfig
from .trainer import TrainState, train_step


@dataclasses.dataclass
class EpisodeRecord:
    task_idx: int
    reward: float
    n_calls: int
    steps: int


@dataclasses.dataclass
class RoundResult:
    state: TrainState
    metrics: Dict[str, float]
    episodes: List[EpisodeRecord]
    trajectories: List[Trajectory]


def _run_episode(make_session, task_idx: int, task: str, g: int,
                 reward_override) -> tuple[List[Trajectory], EpisodeRecord]:
    session = make_session()
    try:
        client = session.client
        log_start = len(getattr(client, "call_log", []))
        out = session.run_turn(task)
        if reward_override is not None:
            reward = reward_override(task_idx, g, session)
        else:
            reward = (out.trace.summary.final_reward
                      if out.trace is not None else 0.0)
        calls = list(getattr(client, "call_log", []))[log_start:]
        trajectories = [
            Trajectory(prompt_ids=rec[0], completion_ids=rec[1],
                       reward=float(reward), group_id=task_idx,
                       behavior_logp=(list(rec[2]) if len(rec) > 2
                                      else None))
            for rec in calls]
        episode = EpisodeRecord(task_idx=task_idx, reward=float(reward),
                                n_calls=len(calls), steps=out.loop.steps)
        return trajectories, episode
    finally:
        session.close()


def collect_group_trajectories(
        make_session: Callable[[], RolloutSession],
        tasks: Sequence[str], *, group_size: int,
        reward_override: Optional[Callable[[int, int, RolloutSession],
                                           float]] = None,
        max_parallel: int = 8,
) -> tuple[List[Trajectory], List[EpisodeRecord]]:
    """Run group_size episodes per task; one Trajectory per LLM call.

    Episodes run CONCURRENTLY (up to ``max_parallel`` host threads — the
    reference's 8-way subagent posture, subagentToolService.ts:33): each
    thread drives its own session/agent loop while all their LLM calls
    interleave on the shared engine's slot pool (EnginePolicyClient.chat
    steps the engine until its own request finishes), so collection
    actually exploits continuous batching instead of keeping one slot busy.

    make_session must return a FRESH session per call — own workspace,
    collector, and client instance (``EnginePolicyClient(record_calls=True)``
    or compatible; the engine itself is shared and lock-serialized, but
    ``call_log`` slicing requires a client per episode).
    reward_override(task_idx, g, session) can replace the trace reward
    (evaluator-in-the-loop). Results are returned in deterministic
    (task_idx, g) order regardless of completion order."""
    import concurrent.futures as _fut

    # Span context must cross the pool explicitly (contextvars don't):
    # each episode span re-attaches the caller's context so the whole
    # group nests under the round's "collect" span in the flamegraph.
    tracer = get_tracer()
    parent_ctx = tracer.capture()

    def _episode_job(ti: int, task: str, g: int):
        with tracer.attach(parent_ctx):
            with tracer.span("episode", task_idx=ti, g=g):
                return _run_episode(make_session, ti, task, g,
                                    reward_override)

    jobs = [(ti, task, g) for ti, task in enumerate(tasks)
            for g in range(group_size)]
    results: Dict[tuple, tuple] = {}
    if max_parallel <= 1 or len(jobs) <= 1:
        for ti, task, g in jobs:
            results[(ti, g)] = _episode_job(ti, task, g)
    else:
        with _fut.ThreadPoolExecutor(max_workers=max_parallel) as pool:
            futs = {pool.submit(_episode_job, ti, task, g): (ti, g)
                    for ti, task, g in jobs}
            for f in _fut.as_completed(futs):
                results[futs[f]] = f.result()

    trajectories: List[Trajectory] = []
    episodes: List[EpisodeRecord] = []
    for key in sorted(results):
        trajs, episode = results[key]
        trajectories.extend(trajs)
        episodes.append(episode)
    return trajectories, episodes


def grpo_round(state: TrainState, model_config, mesh,
               make_session: Callable[[], RolloutSession],
               tasks: Sequence[str], *, group_size: int = 4,
               pad_id: int = 0, max_len: Optional[int] = None,
               grpo_config: GRPOConfig = GRPOConfig(),
               reward_override=None,
               max_parallel: int = 8,
               accum_steps: int = 1,
               ppo_epochs: int = 1,
               metrics_service=None,
               perf_monitor=None,
               engine=None,
               lora_base=None,
               ref_params=None,
               profile_dir: Optional[str] = None) -> RoundResult:
    """One on-policy round: collect → batch → GRPO update(s).

    ``metrics_service`` (services.MetricsService) observes the trainer
    itself (SURVEY.md §7 step 8): per-phase wall time, episode rewards,
    and the update's loss/grad metrics — the trainer-side counterpart of
    the agent loop's 'Agent Loop Done' capture
    (chatThreadService.ts:1742). ``perf_monitor``
    (services.PerformanceMonitor) threshold-checks each phase;
    ``profile_dir`` wraps the whole round in a ``jax.profiler.trace``
    capture (TensorBoard-loadable device timelines)."""
    import time as _time

    if ppo_epochs < 1:
        raise ValueError(f"ppo_epochs must be >= 1, got {ppo_epochs}")

    from ..services.perf_monitor import profile_capture
    with profile_capture(profile_dir), \
            get_tracer().span("grpo_round", tasks=len(tasks),
                              group_size=group_size):
        return _grpo_round_impl(
            state, model_config, mesh, make_session, tasks,
            accum_steps=accum_steps, ppo_epochs=ppo_epochs,
            group_size=group_size, pad_id=pad_id, max_len=max_len,
            grpo_config=grpo_config, reward_override=reward_override,
            max_parallel=max_parallel, metrics_service=metrics_service,
            perf_monitor=perf_monitor, engine=engine, lora_base=lora_base,
            ref_params=ref_params)


def _grpo_round_impl(state, model_config, mesh, make_session, tasks, *,
                     group_size, pad_id, max_len, grpo_config,
                     reward_override, max_parallel, accum_steps=1,
                     ppo_epochs=1, metrics_service=None,
                     perf_monitor=None, engine=None,
                     lora_base=None, ref_params=None) -> RoundResult:
    import time as _time
    tracer = get_tracer()
    t0 = _time.monotonic()
    with tracer.span("collect", tasks=len(tasks), group_size=group_size):
        trajectories, episodes = collect_group_trajectories(
            make_session, tasks, group_size=group_size,
            reward_override=reward_override, max_parallel=max_parallel)
    collect_s = _time.monotonic() - t0
    if perf_monitor is not None:
        perf_monitor.record_ms("rollout_collect", collect_s * 1000.0,
                               episodes=len(episodes))
    if not trajectories:
        if metrics_service is not None:
            metrics_service.capture("GRPO Round Empty",
                                    {"tasks": len(tasks),
                                     "collect_s": round(collect_s, 3)})
        return RoundResult(state=state, metrics={}, episodes=episodes,
                           trajectories=[])
    t_b = _time.monotonic()
    with tracer.span("batch_build", trajectories=len(trajectories)):
        tokens, mask, rewards, group_ids = make_batch(
            trajectories, pad_id=pad_id, max_len=max_len)
        if perf_monitor is not None:
            perf_monitor.record_ms("batch_build",
                                   (_time.monotonic() - t_b) * 1000.0,
                                   batch=len(trajectories))
        # Recorded behavior logps align on the UNPADDED batch (padding
        # appends rows/columns, leaving existing positions fixed).
        old_logp = make_batch_logps(trajectories, tokens, mask)
        tokens, mask, rewards, group_ids, old_logp = place_batch_for_mesh(
            mesh, tokens, mask, rewards, group_ids, old_logp,
            pad_id=pad_id, accum_steps=accum_steps)
    batch_build_s = _time.monotonic() - t_b
    # Multi-epoch (PPO-style) updates need the BEHAVIOR policy's logps
    # frozen across epochs — the clipped ratio is what bounds the drift.
    # Recorded sample-time logps are already exactly that; without them,
    # one extra forward under the pre-update params captures them
    # (timed separately so 'train_step' stays a pure update metric).
    if ppo_epochs > 1 and old_logp is None:
        from .async_loop import behavior_logp_batched
        t_b = _time.monotonic()
        with tracer.span("behavior_logp"):
            logp_params = state.params
            if lora_base is not None:
                from .lora import merge_lora
                logp_params = merge_lora(lora_base, state.params)
            old_logp = behavior_logp_batched(logp_params, model_config,
                                             tokens, accum_steps)
        if perf_monitor is not None:
            perf_monitor.record_ms("behavior_logp",
                                   (_time.monotonic() - t_b) * 1000.0)
    old = old_logp
    # Anchored training: a frozen REFERENCE policy (e.g. a rolling
    # snapshot of the serving params a few rounds back) supplies
    # ref_logp for the k3 KL term — the stabilizer against the observed
    # conditioning collapse under long unanchored runs
    # (ROUND3_NOTES.md §23). ref_params must be a FULL policy tree
    # (callers using LoRA pass the materialized/merged view).
    ref = None
    if ref_params is not None and grpo_config.kl_coef > 0.0:
        from .async_loop import behavior_logp_batched
        t_r = _time.monotonic()
        with tracer.span("ref_logp"):
            ref = behavior_logp_batched(ref_params, model_config, tokens,
                                        accum_steps)
        if perf_monitor is not None:
            perf_monitor.record_ms("ref_logp",
                                   (_time.monotonic() - t_r) * 1000.0)
    t1 = _time.monotonic()
    with tracer.span("train_step", epochs=ppo_epochs,
                     batch_tokens=int(tokens.size)):
        for _ in range(ppo_epochs):
            state, metrics = train_step(
                state, model_config, mesh, tokens, mask, rewards,
                group_ids, old_logp=old, ref_logp=ref,
                grpo_config=grpo_config, accum_steps=accum_steps,
                lora_base=lora_base)
        # float() forces device completion, so the span/timer close on
        # the finished update, not on async dispatch.
        out_metrics = {k: float(v) for k, v in metrics.items()}
    train_s = _time.monotonic() - t1
    if perf_monitor is not None:
        perf_monitor.record_ms("train_step", train_s * 1000.0,
                               epochs=ppo_epochs)
    # Round telemetry (tokens/sec, step-time breakdown, analytic MFU):
    # always-on — a handful of registry writes per round keeps the
    # dashboard's obs tile and /metrics live without span tracing.
    from ..models.transformer import count_params
    telemetry = StepTelemetry(
        get_registry(), param_count=count_params(state.params))
    telemetry_out = telemetry.record_round(
        collect_s=collect_s, batch_build_s=batch_build_s, train_s=train_s,
        batch_tokens=int(tokens.size),
        completion_tokens=sum(len(t.completion_ids)
                              for t in trajectories),
        episodes=len(episodes), trajectories=len(trajectories),
        ppo_epochs=ppo_epochs)
    if metrics_service is not None:
        ep_rewards = [e.reward for e in episodes]
        # Engine serving counters (reuse efficiency) belong in the round
        # record when the caller shares its engine for observability.
        engine_stats = ({f"engine_{k}": v for k, v in engine.stats().items()}
                        if engine is not None and hasattr(engine, "stats")
                        else {})
        metrics_service.capture("GRPO Round Done", {
            "tasks": len(tasks), "group_size": group_size,
            **engine_stats,
            "episodes": len(episodes),
            "trajectories": len(trajectories),
            "batch_tokens": int(tokens.size),
            "reward_mean": sum(ep_rewards) / len(ep_rewards),
            "reward_min": min(ep_rewards), "reward_max": max(ep_rewards),
            "collect_s": round(collect_s, 3),
            "train_s": round(train_s, 3),
            **{k: round(float(v), 3) for k, v in telemetry_out.items()},
            **{k: round(v, 6) for k, v in out_metrics.items()},
        })
    return RoundResult(
        state=state, metrics=out_metrics,
        episodes=episodes, trajectories=trajectories)
