"""The closed GRPO loop: tasks → grouped rollouts → rewards → update.

This is the system SURVEY.md §7's architecture diagram describes end to
end: the rollout engine samples G trajectories per task (the GRPO group),
each driven through a fully-wired RolloutSession (tools, subagents,
traces), the 9-dim reward head scores each episode's trace, group-relative
advantages are computed per task, and the policy takes a clipped-objective
step — replacing the reference's backend-LLM prompt optimization with
local weight updates (apoService.ts:992-1215's optimizer moves in-tree).

Credit assignment: every LLM call inside an episode becomes one
trajectory carrying the episode's finalReward (the per-call token streams
come from EnginePolicyClient.record_calls — no re-tokenization drift);
group ids are per task so advantages compare alternative episodes of the
SAME task.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from ..obs import StepTelemetry, get_registry, get_tracer
from ..resilience.faults import (FailedEpisode, REASON_ERROR,
                                 REASON_TIMEOUT, ResilienceConfig,
                                 episode_retry_delay_s)
from ..rollout.session import RolloutSession
from .data import (Trajectory, make_batch, make_batch_logps,
                   make_branch_mask, place_batch_for_mesh)
from .grpo import GRPOConfig
from .trainer import TrainState, train_step


@dataclasses.dataclass
class EpisodeRecord:
    task_idx: int
    reward: float
    n_calls: int
    steps: int
    # (epoch, version) of the weights that SAMPLED this episode — the
    # behavior-policy stamp the streaming experience pipeline keys its
    # staleness bound and importance correction on. Lockstep rounds
    # stamp the round's published pair; 0/0 means "unstamped"
    # (in-process session with no versioned publisher).
    behavior_epoch: int = 0
    behavior_version: int = 0


@dataclasses.dataclass
class RoundResult:
    state: TrainState
    metrics: Dict[str, float]
    episodes: List[EpisodeRecord]
    trajectories: List[Trajectory]
    # Resilience surface (empty/None without a ResilienceConfig):
    failures: List[FailedEpisode] = dataclasses.field(default_factory=list)
    dropped_groups: List[int] = dataclasses.field(default_factory=list)
    update_skipped: Optional[str] = None
    # Training-health surface (empty for skipped/empty rounds): the
    # round's flat health dict (training/diagnostics + step metrics),
    # the detector triggers that fired, and any mitigation/veto events.
    health: Dict[str, float] = dataclasses.field(default_factory=dict)
    health_triggers: List[str] = dataclasses.field(default_factory=list)
    health_events: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class CollectResult:
    """Collection outcome + fault-boundary bookkeeping.

    Iterates as the historical ``(trajectories, episodes)`` pair so
    existing ``trajs, eps = collect_group_trajectories(...)`` call sites
    keep working; resilience-aware callers read the named fields."""

    trajectories: List[Trajectory]
    episodes: List[EpisodeRecord]
    failures: List[FailedEpisode] = dataclasses.field(default_factory=list)
    dropped_groups: List[int] = dataclasses.field(default_factory=list)
    retries: int = 0
    # Tree-planner shape summary (rollout.group_tree branch_stats) when
    # collection went through the shared-KV planner; empty for the
    # session path. Folded into round health as tree_* keys.
    branch_stats: Dict[str, float] = dataclasses.field(default_factory=dict)

    def __iter__(self):
        return iter((self.trajectories, self.episodes))


class GroupSizeScheduler:
    """Health-triggered group-size hook (the third PR-9 mitigation).

    A high zero-advantage-group fraction usually means the group is too
    SMALL to separate rewards — more samples per prompt restore a
    spread. While the ``group_size`` mitigation is active
    (resilience.HealthMitigator streak logic), :meth:`update` doubles
    the group size toward ``max_size``; once the mitigation clears it
    halves back toward the caller's baseline. The current size
    publishes as the ``senweaver_grpo_group_size`` gauge and every
    change is returned as a round event — the loop (training/online.py)
    feeds the returned size into its NEXT round's collection."""

    def __init__(self, group_size: int, *, min_size: int = 2,
                 max_size: int = 16, registry=None):
        if registry is None:
            registry = get_registry()
        self.base = max(1, int(group_size))
        self.min_size = max(1, int(min_size))
        self.max_size = max(self.min_size, int(max_size))
        self.current = min(max(self.base, self.min_size), self.max_size)
        self._gauge = registry.gauge(
            "senweaver_grpo_group_size",
            "Current GRPO group size (health scheduler may raise it).")
        self._gauge.set(float(self.current))

    @classmethod
    def from_config(cls, config: ResilienceConfig, group_size: int,
                    registry=None) -> "GroupSizeScheduler":
        return cls(group_size, min_size=config.group_size_min,
                   max_size=config.group_size_max, registry=registry)

    def update(self, mitigation_active: bool) -> Tuple[int, List[str]]:
        """One post-round tick; returns (next_group_size, events)."""
        events: List[str] = []
        if mitigation_active and self.current < self.max_size:
            self.current = min(self.current * 2, self.max_size)
            events.append(f"group_size_increased:{self.current}")
        elif not mitigation_active and self.current > self.base:
            self.current = max(self.base, self.current // 2)
            events.append(f"group_size_decreased:{self.current}")
        self._gauge.set(float(self.current))
        return self.current, events


class EpisodeTimeout(RuntimeError):
    """An episode attempt exceeded ResilienceConfig.episode_timeout_s."""


def _call_with_timeout(fn, timeout_s: Optional[float]):
    """Run ``fn()`` bounded by ``timeout_s`` wall seconds. Python can't
    kill a thread, so a timed-out attempt is ABANDONED on a daemon
    thread: its session still closes via _run_episode's finally when
    (if) the attempt eventually returns, but the boundary stops
    waiting."""
    if not timeout_s:
        return fn()
    box: Dict[str, object] = {}

    def target():
        try:
            box["ok"] = fn()
        except BaseException as e:          # re-raised on the caller
            box["err"] = e

    t = threading.Thread(target=target, daemon=True,
                         name="episode-attempt")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise EpisodeTimeout(f"episode exceeded {timeout_s}s")
    if "err" in box:
        raise box["err"]                    # type: ignore[misc]
    return box["ok"]


def _run_episode(make_session, task_idx: int, task: str, g: int,
                 reward_override, round_idx: int = 0
                 ) -> tuple[List[Trajectory], EpisodeRecord]:
    session = make_session()
    try:
        # Episode-aware sessions (the chaos harness's ChaosSession, or
        # any session wanting per-episode attribution) learn their exact
        # coordinates before the turn runs.
        bind = getattr(session, "bind_episode", None)
        if bind is not None:
            bind(round_idx, task_idx, g)
        client = session.client
        log_start = len(getattr(client, "call_log", []))
        out = session.run_turn(task)
        if reward_override is not None:
            reward = reward_override(task_idx, g, session)
        else:
            reward = (out.trace.summary.final_reward
                      if out.trace is not None else 0.0)
        calls = list(getattr(client, "call_log", []))[log_start:]
        trajectories = [
            Trajectory(prompt_ids=rec[0], completion_ids=rec[1],
                       reward=float(reward), group_id=task_idx,
                       behavior_logp=(list(rec[2]) if len(rec) > 2
                                      else None))
            for rec in calls]
        episode = EpisodeRecord(task_idx=task_idx, reward=float(reward),
                                n_calls=len(calls), steps=out.loop.steps)
        return trajectories, episode
    finally:
        session.close()


def collect_tree_trajectories(
        planner, prompts: Sequence[Sequence[int]], *, group_size: int,
        max_new_tokens: int = 128, eos_id: Optional[int] = None,
        adapter_id: Optional[str] = None,
        reward_fn: Optional[Callable[..., float]] = None,
) -> CollectResult:
    """Token-list collection through the shared-KV tree planner.

    The session path below runs G INDEPENDENT episodes per task — G
    prefills of the same prompt. This path routes token-list tasks
    through :class:`rollout.group_tree.GroupRollout` instead: one
    shared prefill per group (engine.submit_group block-table forks)
    plus BranchPolicy-driven tree splits, so a group of G leaves costs
    one prefill and only the divergent suffixes' decode. Each finished
    leaf becomes one Trajectory whose ``branch_points`` (0-based
    completion indices) carry the tree lineage into the batch
    (data.make_branch_mask → grpo_objective branch-credit sharpening),
    and the planner's ``branch_stats`` ride on the CollectResult for
    the round-health fold.

    ``reward_fn(task_idx, leaf_idx, record)`` scores a leaf record (the
    planner ``collect()`` dict: spliced ``tokens``/``logps`` plus
    lineage); without one every leaf gets reward 0.0 and the caller
    stamps rewards on the returned trajectories afterwards."""
    tracer = get_tracer()
    trajectories: List[Trajectory] = []
    episodes: List[EpisodeRecord] = []
    with tracer.span("tree_collect", tasks=len(prompts),
                     group_size=group_size):
        gids = [planner.submit_group(
                    list(p), group_size, max_new_tokens=max_new_tokens,
                    eos_id=eos_id, adapter_id=adapter_id)
                for p in prompts]
        planner.run()
        for ti, (prompt, gid) in enumerate(zip(prompts, gids)):
            for li, rec in enumerate(planner.collect(gid)):
                reward = (float(reward_fn(ti, li, rec))
                          if reward_fn is not None else 0.0)
                toks = list(rec["tokens"])
                # Planner branch positions are group-relative emitted
                # counts ("pos tokens out"); completion index = pos-1.
                pts = sorted({int(p) - 1 for p in rec["branch_points"]
                              if 1 <= int(p) <= len(toks)})
                trajectories.append(Trajectory(
                    prompt_ids=list(prompt), completion_ids=toks,
                    reward=reward, group_id=ti,
                    behavior_logp=list(rec["logps"]),
                    branch_points=pts or None))
                episodes.append(EpisodeRecord(
                    task_idx=ti, reward=reward, n_calls=1, steps=1))
    stats = {k: float(v) for k, v in planner.branch_stats().items()}
    return CollectResult(trajectories=trajectories, episodes=episodes,
                         branch_stats=stats)


def collect_group_trajectories(
        make_session: Callable[[], RolloutSession],
        tasks: Sequence[str], *, group_size: int,
        reward_override: Optional[Callable[[int, int, RolloutSession],
                                           float]] = None,
        max_parallel: int = 8,
        resilience: Optional[ResilienceConfig] = None,
        round_idx: int = 0,
        retry_sleep: Callable[[float], None] = time.sleep,
        planner=None,
) -> CollectResult:
    """Run group_size episodes per task; one Trajectory per LLM call.

    Episodes run CONCURRENTLY (up to ``max_parallel`` host threads — the
    reference's 8-way subagent posture, subagentToolService.ts:33): each
    thread drives its own session/agent loop while all their LLM calls
    interleave on the shared engine's slot pool (EnginePolicyClient.chat
    steps the engine until its own request finishes), so collection
    actually exploits continuous batching instead of keeping one slot busy.

    make_session must return a FRESH session per call — own workspace,
    collector, and client instance (``EnginePolicyClient(record_calls=True)``
    or compatible; the engine itself is shared and lock-serialized, but
    ``call_log`` slicing requires a client per episode).
    reward_override(task_idx, g, session) can replace the trace reward
    (evaluator-in-the-loop). Results are returned in deterministic
    (task_idx, g) order regardless of completion order.

    With a ``resilience`` config, each episode runs inside a FAULT
    BOUNDARY: per-attempt timeout (``episode_timeout_s``), bounded retry
    with backoff (``episode_retries``), and quarantine — a persistently
    failing episode becomes a :class:`FailedEpisode` record instead of
    an exception. Task groups keeping fewer than ``min_group_survivors``
    episodes are dropped whole (their advantages are degenerate), and a
    round losing every group returns empty — the caller's empty-batch
    path skips the update. Without a config the historical raise-on-
    first-error semantics hold (but in-flight work is drained first).

    With a ``planner`` (rollout.group_tree.GroupRollout) and TOKEN-LIST
    tasks, collection routes through :func:`collect_tree_trajectories`
    instead — one shared prefill per group via KV fork, tree branching
    per the planner's BranchPolicy; ``reward_override`` is then called
    as ``reward_override(task_idx, leaf_idx, leaf_record)``."""
    if planner is not None:
        if any(isinstance(t, str) for t in tasks):
            raise ValueError(
                "planner routing needs token-list tasks (the tree "
                "planner drives the engine directly; string tasks run "
                "through sessions — drop the planner argument)")
        return collect_tree_trajectories(
            planner, tasks, group_size=group_size,
            reward_fn=reward_override)
    import concurrent.futures as _fut

    # Span context must cross the pool explicitly (contextvars don't):
    # each episode span re-attaches the caller's context so the whole
    # group nests under the round's "collect" span in the flamegraph.
    tracer = get_tracer()
    parent_ctx = tracer.capture()
    registry = get_registry()
    failures: List[FailedEpisode] = []
    retries_total = [0]

    def _episode_job(ti: int, task: str, g: int):
        with tracer.attach(parent_ctx):
            with tracer.span("episode", task_idx=ti, g=g):
                return _run_episode(make_session, ti, task, g,
                                    reward_override, round_idx)

    def _guarded_job(ti: int, task: str, g: int):
        """The fault boundary: returns (result, None) or (None,
        FailedEpisode) — never raises."""
        assert resilience is not None
        t0 = time.monotonic()
        last_err: Optional[BaseException] = None
        attempts = 0
        while attempts <= resilience.episode_retries:
            attempts += 1
            try:
                out = _call_with_timeout(
                    lambda: _episode_job(ti, task, g),
                    resilience.episode_timeout_s)
                return out, None
            except Exception as e:
                last_err = e
            if attempts <= resilience.episode_retries:
                retries_total[0] += 1
                registry.counter(
                    "senweaver_grpo_episode_retries_total",
                    "Episode attempts retried by the fault boundary"
                ).inc()
                retry_sleep(episode_retry_delay_s(
                    attempts, base_s=resilience.retry_base_delay_s,
                    max_s=resilience.retry_max_delay_s))
        reason = (REASON_TIMEOUT if isinstance(last_err, EpisodeTimeout)
                  else REASON_ERROR)
        registry.counter(
            "senweaver_grpo_episodes_failed_total",
            "Episodes quarantined after exhausting retries",
            labelnames=("reason",)).inc(reason=reason)
        return None, FailedEpisode(
            task_idx=ti, g=g, round_idx=round_idx, reason=reason,
            error=repr(last_err), attempts=attempts,
            elapsed_s=time.monotonic() - t0)

    run_job = _episode_job if resilience is None else _guarded_job
    jobs = [(ti, task, g) for ti, task in enumerate(tasks)
            for g in range(group_size)]
    results: Dict[tuple, tuple] = {}
    if max_parallel <= 1 or len(jobs) <= 1:
        for ti, task, g in jobs:
            results[(ti, g)] = run_job(ti, task, g)
    else:
        with _fut.ThreadPoolExecutor(max_workers=max_parallel) as pool:
            futs = {pool.submit(run_job, ti, task, g): (ti, g)
                    for ti, task, g in jobs}
            try:
                for f in _fut.as_completed(futs):
                    results[futs[f]] = f.result()
            except BaseException:
                # Historical (no-resilience) crash path, fixed: cancel
                # episodes that haven't started and DRAIN the in-flight
                # ones before re-raising — their threads must not keep
                # stepping a shared engine the caller is about to tear
                # down, and _run_episode's finally closes each session
                # only when its thread finishes.
                for other in futs:
                    other.cancel()
                _fut.wait(list(futs))
                raise

    if resilience is not None:
        for (ti, g), (out, failure) in sorted(results.items()):
            if failure is not None:
                failures.append(failure)
        # Group-survivor threshold: group-relative advantages over 0-1
        # survivors are degenerate (vacuous or mean-centered to zero),
        # so a gutted group's trajectories only add noise to the batch.
        eff_min = min(resilience.min_group_survivors, group_size)
        dropped_groups: List[int] = []
        for ti in range(len(tasks)):
            survivors = [k for k, (out, fl) in results.items()
                         if k[0] == ti and fl is None]
            if len(survivors) < eff_min:
                dropped_groups.append(ti)
                for k in survivors:
                    del results[k]
        if dropped_groups:
            registry.counter(
                "senweaver_grpo_task_groups_dropped_total",
                "Task groups dropped below min_group_survivors"
            ).inc(len(dropped_groups))
        results = {k: v[0] for k, v in results.items()
                   if v[1] is None and k[0] not in dropped_groups}
    else:
        dropped_groups = []

    trajectories: List[Trajectory] = []
    episodes: List[EpisodeRecord] = []
    for key in sorted(results):
        trajs, episode = results[key]
        trajectories.extend(trajs)
        episodes.append(episode)
    return CollectResult(trajectories=trajectories, episodes=episodes,
                         failures=failures,
                         dropped_groups=dropped_groups,
                         retries=retries_total[0])


def grpo_round(state: TrainState, model_config, mesh,
               make_session: Callable[[], RolloutSession],
               tasks: Sequence[str], *, group_size: int = 4,
               pad_id: int = 0, max_len: Optional[int] = None,
               grpo_config: GRPOConfig = GRPOConfig(),
               reward_override=None,
               max_parallel: int = 8,
               accum_steps: int = 1,
               ppo_epochs: int = 1,
               metrics_service=None,
               perf_monitor=None,
               engine=None,
               lora_base=None,
               ref_params=None,
               resilience: Optional[ResilienceConfig] = None,
               update_guard=None,
               health_mitigator=None,
               round_idx: int = 0,
               behavior_stamp: Optional[Tuple[int, int]] = None,
               planner=None,
               profile_dir: Optional[str] = None) -> RoundResult:
    """One on-policy round: collect → batch → GRPO update(s).

    ``metrics_service`` (services.MetricsService) observes the trainer
    itself (SURVEY.md §7 step 8): per-phase wall time, episode rewards,
    and the update's loss/grad metrics — the trainer-side counterpart of
    the agent loop's 'Agent Loop Done' capture
    (chatThreadService.ts:1742). ``perf_monitor``
    (services.PerformanceMonitor) threshold-checks each phase;
    ``profile_dir`` wraps the whole round in a ``jax.profiler.trace``
    capture (TensorBoard-loadable device timelines).

    ``resilience`` arms the episode fault boundary in collection (see
    collect_group_trajectories) and — unless an explicit
    ``update_guard`` is passed — a fresh UpdateGuard vetoing NaN/Inf
    updates for this round. Loops spanning many rounds should build ONE
    resilience.UpdateGuard (UpdateGuard.from_config) and pass it in, so
    the loss-spike baseline accumulates across rounds. ``round_idx``
    tags FailedEpisode records and the chaos harness's injection
    coordinates.

    ``health_mitigator`` (resilience.HealthMitigator, one per run like
    the guard) lets persistent training-health triggers reshape the
    round's EFFECTIVE GRPOConfig (leave-one-out / token-level credit)
    under streak hysteresis; without one the diagnostics still run and
    publish, they just never change the objective."""
    import time as _time

    if ppo_epochs < 1:
        raise ValueError(f"ppo_epochs must be >= 1, got {ppo_epochs}")
    if update_guard is None and resilience is not None:
        from ..resilience.guard import UpdateGuard
        update_guard = UpdateGuard.from_config(resilience)

    from ..services.perf_monitor import profile_capture
    with profile_capture(profile_dir), \
            get_tracer().span("grpo_round", tasks=len(tasks),
                              group_size=group_size):
        return _grpo_round_impl(
            state, model_config, mesh, make_session, tasks,
            accum_steps=accum_steps, ppo_epochs=ppo_epochs,
            group_size=group_size, pad_id=pad_id, max_len=max_len,
            grpo_config=grpo_config, reward_override=reward_override,
            max_parallel=max_parallel, metrics_service=metrics_service,
            perf_monitor=perf_monitor, engine=engine, lora_base=lora_base,
            ref_params=ref_params, resilience=resilience,
            update_guard=update_guard, health_mitigator=health_mitigator,
            round_idx=round_idx, behavior_stamp=behavior_stamp,
            planner=planner)


def _grpo_round_impl(state, model_config, mesh, make_session, tasks, *,
                     group_size, pad_id, max_len, grpo_config,
                     reward_override, max_parallel, accum_steps=1,
                     ppo_epochs=1, metrics_service=None,
                     perf_monitor=None, engine=None,
                     lora_base=None, ref_params=None, resilience=None,
                     update_guard=None, health_mitigator=None,
                     round_idx=0, behavior_stamp=None,
                     planner=None) -> RoundResult:
    import time as _time
    tracer = get_tracer()
    t0 = _time.monotonic()
    with tracer.span("collect", tasks=len(tasks), group_size=group_size):
        collected = collect_group_trajectories(
            make_session, tasks, group_size=group_size,
            reward_override=reward_override, max_parallel=max_parallel,
            resilience=resilience, round_idx=round_idx, planner=planner)
    trajectories, episodes = collected.trajectories, collected.episodes
    if behavior_stamp is not None:
        # Lockstep sampling: every episode in the round was collected
        # under ONE (epoch, version) pair — the publisher never swaps
        # weights mid-round — so the caller's stamp applies uniformly.
        b_epoch, b_version = int(behavior_stamp[0]), int(behavior_stamp[1])
        for ep in episodes:
            ep.behavior_epoch = b_epoch
            ep.behavior_version = b_version
    failures = collected.failures
    dropped_groups = collected.dropped_groups
    collect_s = _time.monotonic() - t0
    if perf_monitor is not None:
        perf_monitor.record_ms("rollout_collect", collect_s * 1000.0,
                               episodes=len(episodes))
    if not trajectories:
        # Bottom rung of the degradation ladder: nothing survived
        # collection — keep the state, skip the update, leave a trail.
        if resilience is not None and (failures or dropped_groups):
            get_registry().counter(
                "senweaver_grpo_rounds_skipped_total",
                "Rounds skipped after losing every task group").inc()
        if metrics_service is not None:
            metrics_service.capture("GRPO Round Empty",
                                    {"tasks": len(tasks),
                                     "failed_episodes": len(failures),
                                     "groups_dropped": len(dropped_groups),
                                     "collect_s": round(collect_s, 3)})
        return RoundResult(state=state, metrics={}, episodes=episodes,
                           trajectories=[], failures=failures,
                           dropped_groups=dropped_groups)
    t_b = _time.monotonic()
    with tracer.span("batch_build", trajectories=len(trajectories)):
        tokens, mask, rewards, group_ids = make_batch(
            trajectories, pad_id=pad_id, max_len=max_len)
        if perf_monitor is not None:
            perf_monitor.record_ms("batch_build",
                                   (_time.monotonic() - t_b) * 1000.0,
                                   batch=len(trajectories))
        # Recorded behavior logps align on the UNPADDED batch (padding
        # appends rows/columns, leaving existing positions fixed).
        old_logp = make_batch_logps(trajectories, tokens, mask)
        branch_np = make_branch_mask(trajectories, tokens, mask)
        # Training-health diagnostics: DISPATCH the jitted head on the
        # HOST arrays before placement (it computes asynchronously while
        # the batch is placed); the single device_get happens below,
        # outside the build span. Group ids are task indices and may be
        # non-contiguous after group drops — densify for segment ops.
        import numpy as _np
        from .diagnostics import (DiagnosticsConfig, dispatch_round_health,
                                  finalize_round_health)
        diag_cfg = DiagnosticsConfig.from_grpo(
            health_mitigator.effective(grpo_config)
            if health_mitigator is not None else grpo_config)
        _uniq, _codes = _np.unique(_np.asarray(group_ids),
                                   return_inverse=True)
        health_dev = dispatch_round_health(
            rewards, _codes, mask, num_groups=max(len(_uniq), 1),
            config=diag_cfg)
        tokens, mask, rewards, group_ids, old_logp = place_batch_for_mesh(
            mesh, tokens, mask, rewards, group_ids, old_logp,
            pad_id=pad_id, accum_steps=accum_steps)
        branch_mask = None
        if branch_np is not None:
            # Tree-planner batches: pad the host branch mask to the
            # placed grid (appended rows/columns are outside the
            # completion mask, never read) and co-place it with tokens.
            import jax as _jax
            branch_np = _np.pad(
                branch_np,
                ((0, int(tokens.shape[0]) - branch_np.shape[0]),
                 (0, int(tokens.shape[1]) - branch_np.shape[1])))
            branch_mask = _jax.device_put(branch_np, tokens.sharding)
    batch_build_s = _time.monotonic() - t_b
    # The round's ONE health sync, then the pre-step detector pass; a
    # persistent trigger streak may reshape this round's objective
    # (leave-one-out / token-level credit) — every transition or veto
    # becomes a round event and a labeled counter.
    from ..obs.training_health import evaluate_health, get_health_monitor
    health = finalize_round_health(health_dev)
    health["groups"] = float(len(_uniq))
    # Tree-planner lineage reaches the diagnostics surface here: the
    # planner's shape summary rides the round health dict (tree_* keys)
    # next to the advantage/credit detectors it informs.
    for k, v in collected.branch_stats.items():
        health[f"tree_{k}"] = float(v)
    monitor = get_health_monitor()
    pre_triggers = evaluate_health(health, monitor.config)
    health_events: List[str] = []
    if health_mitigator is not None:
        grpo_config, health_events = health_mitigator.apply(
            grpo_config, pre_triggers)
    # Multi-epoch (PPO-style) updates need the BEHAVIOR policy's logps
    # frozen across epochs — the clipped ratio is what bounds the drift.
    # Recorded sample-time logps are already exactly that; without them,
    # one extra forward under the pre-update params captures them
    # (timed separately so 'train_step' stays a pure update metric).
    if ppo_epochs > 1 and old_logp is None:
        from .async_loop import behavior_logp_batched
        t_b = _time.monotonic()
        with tracer.span("behavior_logp"):
            logp_params = state.params
            if lora_base is not None:
                from .lora import merge_lora
                logp_params = merge_lora(lora_base, state.params)
            old_logp = behavior_logp_batched(logp_params, model_config,
                                             tokens, accum_steps)
        if perf_monitor is not None:
            perf_monitor.record_ms("behavior_logp",
                                   (_time.monotonic() - t_b) * 1000.0)
    old = old_logp
    # Anchored training: a frozen REFERENCE policy (e.g. a rolling
    # snapshot of the serving params a few rounds back) supplies
    # ref_logp for the k3 KL term — the stabilizer against the observed
    # conditioning collapse under long unanchored runs
    # (ROUND3_NOTES.md §23). ref_params must be a FULL policy tree
    # (callers using LoRA pass the materialized/merged view).
    ref = None
    if ref_params is not None and grpo_config.kl_coef > 0.0:
        from .async_loop import behavior_logp_batched
        t_r = _time.monotonic()
        with tracer.span("ref_logp"):
            ref = behavior_logp_batched(ref_params, model_config, tokens,
                                        accum_steps)
        if perf_monitor is not None:
            perf_monitor.record_ms("ref_logp",
                                   (_time.monotonic() - t_r) * 1000.0)
    t1 = _time.monotonic()
    update_skipped: Optional[str] = None
    with tracer.span("train_step", epochs=ppo_epochs,
                     batch_tokens=int(tokens.size)):
        for _ in range(ppo_epochs):
            prev_state = state
            state, metrics = train_step(
                state, model_config, mesh, tokens, mask, rewards,
                group_ids, old_logp=old, ref_logp=ref,
                branch_mask=branch_mask,
                grpo_config=grpo_config, accum_steps=accum_steps,
                lora_base=lora_base)
            if update_guard is not None:
                # Guarded adoption: sync the metrics to host floats and
                # let the guard veto the step BEFORE the new state is
                # kept — a NaN gradient never reaches the optimizer
                # moments, and further epochs on a vetoed batch are
                # pointless.
                out_metrics = {k: float(v) for k, v in metrics.items()}
                update_skipped = update_guard.check(out_metrics)
                if update_skipped is not None:
                    state = prev_state
                    break
        if update_guard is None:
            # float() forces device completion, so the span/timer close
            # on the finished update, not on async dispatch.
            out_metrics = {k: float(v) for k, v in metrics.items()}
    train_s = _time.monotonic() - t1
    if perf_monitor is not None:
        perf_monitor.record_ms("train_step", train_s * 1000.0,
                               epochs=ppo_epochs)
    # Fold the step's own health signals into the round's dict (finite
    # values only — a vetoed NaN step is already represented by the
    # guard veto event and the nonfinite trigger), then run the FULL
    # detector pass. Post-step-only triggers can't gate this round's
    # objective — they seed the mitigator's next-round streaks.
    import math as _math
    for src, dst in (("grad_sparsity", "grad_sparsity"),
                     ("entropy", "policy_entropy"),
                     ("kl", "kl_to_anchor")):
        v = out_metrics.get(src)
        if v is not None and _math.isfinite(v):
            health[dst] = float(v)
    health_triggers = evaluate_health(health, monitor.config)
    if health_mitigator is not None:
        health_mitigator.note_post_step(
            [t for t in health_triggers if t not in pre_triggers])
    if update_skipped is not None:
        health_events.append(f"update_skipped:{update_skipped}")
    adv_stats = {
        "zero_advantage_group_fraction":
            health.get("zero_advantage_group_fraction", 0.0),
        "advantage_std": health.get("advantage_std", 0.0),
        "groups": int(health.get("groups", 0.0)),
    }
    # Round telemetry (tokens/sec, step-time breakdown, analytic MFU):
    # always-on — a handful of registry writes per round keeps the
    # dashboard's obs tile and /metrics live without span tracing.
    from ..models.transformer import count_params
    telemetry = StepTelemetry(
        get_registry(), param_count=count_params(state.params))
    telemetry_out = telemetry.record_round(
        collect_s=collect_s, batch_build_s=batch_build_s, train_s=train_s,
        batch_tokens=int(tokens.size),
        completion_tokens=sum(len(t.completion_ids)
                              for t in trajectories),
        episodes=len(episodes), trajectories=len(trajectories),
        ppo_epochs=ppo_epochs, advantage_stats=adv_stats,
        health=health, health_triggers=health_triggers,
        health_events=health_events, round_index=round_idx)
    if metrics_service is not None:
        ep_rewards = [e.reward for e in episodes]
        # Engine serving counters (reuse efficiency) belong in the round
        # record when the caller shares its engine for observability.
        engine_stats = ({f"engine_{k}": v for k, v in engine.stats().items()}
                        if engine is not None and hasattr(engine, "stats")
                        else {})
        metrics_service.capture("GRPO Round Done", {
            "tasks": len(tasks), "group_size": group_size,
            **engine_stats,
            "episodes": len(episodes),
            "trajectories": len(trajectories),
            "failed_episodes": len(failures),
            "episode_retries": collected.retries,
            "groups_dropped": len(dropped_groups),
            "update_skipped": update_skipped or "",
            "batch_tokens": int(tokens.size),
            "reward_mean": sum(ep_rewards) / len(ep_rewards),
            "reward_min": min(ep_rewards), "reward_max": max(ep_rewards),
            "collect_s": round(collect_s, 3),
            "train_s": round(train_s, 3),
            "health_triggers": ",".join(health_triggers),
            "health_events": ",".join(health_events),
            **{k: round(float(v), 3) for k, v in telemetry_out.items()
               if isinstance(v, (int, float))},
            **{k: round(v, 6) for k, v in out_metrics.items()},
        })
    return RoundResult(
        state=state, metrics=out_metrics,
        episodes=episodes, trajectories=trajectories,
        failures=failures, dropped_groups=dropped_groups,
        update_skipped=update_skipped, health=health,
        health_triggers=health_triggers, health_events=health_events)
