"""The closed GRPO loop: tasks → grouped rollouts → rewards → update.

This is the system SURVEY.md §7's architecture diagram describes end to
end: the rollout engine samples G trajectories per task (the GRPO group),
each driven through a fully-wired RolloutSession (tools, subagents,
traces), the 9-dim reward head scores each episode's trace, group-relative
advantages are computed per task, and the policy takes a clipped-objective
step — replacing the reference's backend-LLM prompt optimization with
local weight updates (apoService.ts:992-1215's optimizer moves in-tree).

Credit assignment: every LLM call inside an episode becomes one
trajectory carrying the episode's finalReward (the per-call token streams
come from EnginePolicyClient.record_calls — no re-tokenization drift);
group ids are per task so advantages compare alternative episodes of the
SAME task.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

import jax.numpy as jnp

from ..rollout.session import RolloutSession
from .data import Trajectory, make_batch
from .grpo import GRPOConfig
from .trainer import TrainState, train_step


@dataclasses.dataclass
class EpisodeRecord:
    task_idx: int
    reward: float
    n_calls: int
    steps: int


@dataclasses.dataclass
class RoundResult:
    state: TrainState
    metrics: Dict[str, float]
    episodes: List[EpisodeRecord]
    trajectories: List[Trajectory]


def collect_group_trajectories(
        make_session: Callable[[], RolloutSession],
        tasks: Sequence[str], *, group_size: int,
        reward_override: Optional[Callable[[int, int, RolloutSession],
                                           float]] = None
) -> tuple[List[Trajectory], List[EpisodeRecord]]:
    """Run group_size episodes per task; one Trajectory per LLM call.

    make_session must return a FRESH session whose client is an
    EnginePolicyClient(record_calls=True) (or compatible) — episodes must
    not share mutable workspace state. reward_override(task_idx, g,
    session) can replace the trace reward (evaluator-in-the-loop)."""
    trajectories: List[Trajectory] = []
    episodes: List[EpisodeRecord] = []
    for task_idx, task in enumerate(tasks):
        for g in range(group_size):
            session = make_session()
            client = session.client
            log_start = len(getattr(client, "call_log", []))
            out = session.run_turn(task)
            if reward_override is not None:
                reward = reward_override(task_idx, g, session)
            else:
                reward = (out.trace.summary.final_reward
                          if out.trace is not None else 0.0)
            calls = list(getattr(client, "call_log", []))[log_start:]
            for prompt_ids, out_ids in calls:
                trajectories.append(Trajectory(
                    prompt_ids=prompt_ids, completion_ids=out_ids,
                    reward=float(reward), group_id=task_idx))
            episodes.append(EpisodeRecord(task_idx=task_idx,
                                          reward=float(reward),
                                          n_calls=len(calls),
                                          steps=out.loop.steps))
            session.close()
    return trajectories, episodes


def grpo_round(state: TrainState, model_config, mesh,
               make_session: Callable[[], RolloutSession],
               tasks: Sequence[str], *, group_size: int = 4,
               pad_id: int = 0, max_len: Optional[int] = None,
               grpo_config: GRPOConfig = GRPOConfig(),
               reward_override=None,
               metrics_service=None) -> RoundResult:
    """One on-policy round: collect → batch → single GRPO step.

    ``metrics_service`` (services.MetricsService) observes the trainer
    itself (SURVEY.md §7 step 8): per-phase wall time, episode rewards,
    and the update's loss/grad metrics — the trainer-side counterpart of
    the agent loop's 'Agent Loop Done' capture
    (chatThreadService.ts:1742)."""
    import time as _time
    t0 = _time.monotonic()
    trajectories, episodes = collect_group_trajectories(
        make_session, tasks, group_size=group_size,
        reward_override=reward_override)
    collect_s = _time.monotonic() - t0
    if not trajectories:
        if metrics_service is not None:
            metrics_service.capture("GRPO Round Empty",
                                    {"tasks": len(tasks),
                                     "collect_s": round(collect_s, 3)})
        return RoundResult(state=state, metrics={}, episodes=episodes,
                           trajectories=[])
    tokens, mask, rewards, group_ids = make_batch(
        trajectories, pad_id=pad_id, max_len=max_len)
    t1 = _time.monotonic()
    state, metrics = train_step(
        state, model_config, mesh, jnp.asarray(tokens), jnp.asarray(mask),
        jnp.asarray(rewards), jnp.asarray(group_ids),
        grpo_config=grpo_config)
    out_metrics = {k: float(v) for k, v in metrics.items()}
    if metrics_service is not None:
        ep_rewards = [e.reward for e in episodes]
        metrics_service.capture("GRPO Round Done", {
            "tasks": len(tasks), "group_size": group_size,
            "episodes": len(episodes),
            "trajectories": len(trajectories),
            "batch_tokens": int(tokens.size),
            "reward_mean": sum(ep_rewards) / len(ep_rewards),
            "reward_min": min(ep_rewards), "reward_max": max(ep_rewards),
            "collect_s": round(collect_s, 3),
            "train_s": round(_time.monotonic() - t1, 3),
            **{k: round(v, 6) for k, v in out_metrics.items()},
        })
    return RoundResult(
        state=state, metrics=out_metrics,
        episodes=episodes, trajectories=trajectories)
