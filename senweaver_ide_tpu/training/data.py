"""Trajectory dataset: rollouts → padded GRPO training batches.

The bridge between the rollout plane (sessions producing traces + token
logs) and the jit training step: trajectories are (prompt_ids,
completion_ids, reward, group_id); batches pad to a power-of-two bucket
(bounded recompilation, same policy as the rollout engine) with a
completion-token mask so the objective only scores generated tokens.

Deterministic order for resume (SURVEY.md §7 step 5): the dataset shuffles
with a seeded permutation per epoch and exposes a cursor that the
checkpoint meta records (training/checkpoint.py data_cursor), so a
restored run continues on the exact next batch.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class Trajectory:
    prompt_ids: List[int]
    completion_ids: List[int]
    reward: float
    group_id: int
    # Behavior log-prob per completion token, captured at SAMPLE time by
    # the engine (result_logps). When every trajectory in a batch has
    # them, make_batch_logps aligns them into the old_logp array and the
    # GRPO step trains with exact importance ratios (no second forward,
    # no retained behavior params).
    behavior_logp: Optional[List[float]] = None
    # Tree-rollout lineage (rollout/group_tree.py): 0-based positions
    # WITHIN completion_ids where this trajectory's path through the
    # rollout tree branched. make_branch_mask aligns them with a
    # make_batch output so grpo_objective can sharpen credit at split
    # points (GRPOConfig.branch_credit_boost). None/empty = unbranched.
    branch_points: Optional[List[int]] = None


def _bucket(n: int, minimum: int = 32) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


def make_batch(trajectories: Sequence[Trajectory], *, pad_id: int,
               max_len: Optional[int] = None
               ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Returns (tokens (B, S), completion_mask (B, S) bool, rewards (B,),
    group_ids (B,)). S = power-of-two bucket of the longest trajectory
    (clipped to max_len; overlong trajectories keep their completion tail
    — the prompt head is dropped, since the objective needs completion
    tokens in context, not the full prompt)."""
    if not trajectories:
        raise ValueError("empty batch")
    lens = [len(t.prompt_ids) + len(t.completion_ids) for t in trajectories]
    s = _bucket(max(lens))
    if max_len is not None:
        s = min(s, max_len)
    b = len(trajectories)
    tokens = np.full((b, s), pad_id, np.int32)
    mask = np.zeros((b, s), bool)
    rewards = np.zeros((b,), np.float32)
    group_ids = np.zeros((b,), np.int32)
    for i, t in enumerate(trajectories):
        seq = list(t.prompt_ids) + list(t.completion_ids)
        comp_start = len(t.prompt_ids)
        if len(seq) > s:
            drop = len(seq) - s
            seq = seq[drop:]
            comp_start = max(0, comp_start - drop)
        tokens[i, :len(seq)] = seq
        mask[i, comp_start:len(seq)] = True
        rewards[i] = t.reward
        group_ids[i] = t.group_id
    return tokens, mask, rewards, group_ids


def make_batch_logps(trajectories: Sequence[Trajectory],
                     tokens: np.ndarray,
                     mask: np.ndarray) -> Optional[np.ndarray]:
    """Align recorded behavior logps with a make_batch output.

    Returns old_logp shaped (B, S-1) — the trainer's target layout
    (position j-1 predicts token j) — or None unless EVERY trajectory
    carries a full logp list (a partial batch would silently mix exact
    ratios with the ratio-1 approximation). Positions outside the
    completion mask hold 0.0 (never read by the masked objective)."""
    if any(t.behavior_logp is None
           or len(t.behavior_logp) != len(t.completion_ids)
           for t in trajectories):
        return None
    b, s = tokens.shape
    old = np.zeros((b, s - 1), np.float32)
    for i, t in enumerate(trajectories):
        # completion tokens sit at the masked positions of row i, in
        # order; target index of seq position j is j-1. Position 0 can
        # never be a target (nothing precedes it) — the trainer's
        # shifted mask excludes it too.
        pos = np.nonzero(mask[i])[0]
        lps = np.asarray(t.behavior_logp[-len(pos):] if len(pos) else [],
                         np.float32)
        keep = pos >= 1
        old[i, pos[keep] - 1] = lps[keep]
    return old


def make_branch_mask(trajectories: Sequence[Trajectory],
                     tokens: np.ndarray,
                     mask: np.ndarray) -> Optional[np.ndarray]:
    """Align recorded tree branch points with a make_batch output.

    Returns a (B, S) float32 mask with 1.0 at the completion tokens
    where the trajectory's rollout-tree path branched, or None when no
    trajectory carries branch points (the common unbranched batch adds
    no operand to the train step). Points cropped away by an overlong
    row's front-drop are silently outside the kept tail."""
    if not any(t.branch_points for t in trajectories):
        return None
    b, s = tokens.shape
    out = np.zeros((b, s), np.float32)
    for i, t in enumerate(trajectories):
        if not t.branch_points:
            continue
        pos = np.nonzero(mask[i])[0]
        n = len(pos)
        dropped = len(t.completion_ids) - n
        for p in t.branch_points:
            q = int(p) - dropped
            if 0 <= q < n:
                out[i, pos[q]] = 1.0
    return out


def pad_batch_for_mesh(
    tokens: np.ndarray, mask: np.ndarray, rewards: np.ndarray,
    group_ids: np.ndarray, *, batch_multiple: int = 1,
    seq_multiple: int = 1, pad_id: int = 0,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Pad a make_batch output so it shards evenly on a mesh: batch axis to
    a multiple of (dp·fsdp), and the TRAINING sequence length (S−1, after
    the trainer's next-token shift) to a multiple of sp. Padded rows get an
    all-False mask, zero reward, and a fresh singleton group id each — they
    contribute nothing to the masked objective or group advantages."""
    b, s = tokens.shape
    target_s = ((s - 1 + seq_multiple - 1) // seq_multiple) * seq_multiple + 1
    if target_s > s:
        pad = target_s - s
        tokens = np.pad(tokens, ((0, 0), (0, pad)), constant_values=pad_id)
        mask = np.pad(mask, ((0, 0), (0, pad)))
    target_b = ((b + batch_multiple - 1) // batch_multiple) * batch_multiple
    if target_b > b:
        extra = target_b - b
        tokens = np.pad(tokens, ((0, extra), (0, 0)), constant_values=pad_id)
        mask = np.pad(mask, ((0, extra), (0, 0)))
        rewards = np.pad(rewards, (0, extra))
        next_gid = int(group_ids.max()) + 1 if b else 0
        group_ids = np.concatenate(
            [group_ids, np.arange(next_gid, next_gid + extra,
                                  dtype=group_ids.dtype)])
    return tokens, mask, rewards, group_ids


class TrajectoryDataset:
    """Seeded-permutation epochs + a resumable cursor."""

    def __init__(self, trajectories: Sequence[Trajectory], *,
                 batch_size: int, seed: int = 0):
        self._items = list(trajectories)
        self.batch_size = batch_size
        self.seed = seed
        self.cursor = 0              # global batch index across epochs

    def __len__(self) -> int:
        return len(self._items)

    @property
    def batches_per_epoch(self) -> int:
        # Ceil division: the final short batch is kept (dropping it would
        # silently skew GRPO groups by a permutation-dependent remainder).
        return max(1, -(-len(self._items) // self.batch_size))

    def _epoch_perm(self, epoch: int) -> np.ndarray:
        rng = np.random.default_rng((self.seed, epoch))
        return rng.permutation(len(self._items))

    def batch_at(self, cursor: int) -> List[Trajectory]:
        epoch = cursor // self.batches_per_epoch
        step = cursor % self.batches_per_epoch
        perm = self._epoch_perm(epoch)
        idx = perm[step * self.batch_size:(step + 1) * self.batch_size]
        return [self._items[i] for i in idx]

    def __iter__(self) -> Iterator[List[Trajectory]]:
        while True:
            yield self.batch_at(self.cursor)
            self.cursor += 1


def place_batch_for_mesh(mesh, tokens, mask, rewards, group_ids,
                         old_logp=None, *, pad_id: int = 0,
                         accum_steps: int = 1):
    """Pad a make_batch output for the mesh and device_put every array
    with its batch/sequence sharding.

    Explicit placement matters: feeding host numpy through jit relies on
    GSPMD propagation, which broadcasts the batch to every device before
    resharding (VERDICT r1 weak #5). The sequence axis keeps S = k·sp+1
    (the TRAINING length S−1 shards over sp after the next-token shift
    inside the step), so grids place batch-axis-only here.
    Returns jnp/global arrays ready for train_step."""
    import jax as _jax
    import numpy as _np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.sharding import restrict_spec

    if mesh is None:
        import jax.numpy as _jnp
        if accum_steps > 1 and tokens.shape[0] % accum_steps != 0:
            # Same contract as the mesh path: the returned batch must
            # satisfy the microbatch scan's divisibility check.
            tokens, mask, rewards, group_ids = pad_batch_for_mesh(
                tokens, mask, rewards, group_ids,
                batch_multiple=accum_steps, pad_id=pad_id)
            if old_logp is not None and old_logp.shape[0] < tokens.shape[0]:
                old_logp = _np.pad(
                    old_logp, ((0, tokens.shape[0] - old_logp.shape[0]),
                               (0, 0)))
        out = tuple(map(_jnp.asarray, (tokens, mask, rewards, group_ids)))
        return out + ((_jnp.asarray(old_logp)
                       if old_logp is not None else None),)
    import math as _math
    axes = dict(zip(mesh.axis_names, _np.asarray(mesh.devices).shape))
    data_axes = axes.get("dp", 1) * axes.get("fsdp", 1)
    # The padded batch must ALSO stay divisible by accum_steps (the
    # microbatch scan rejects indivisible batches) → lcm of the two.
    batch_multiple = _math.lcm(data_axes, max(accum_steps, 1))
    tokens, mask, rewards, group_ids = pad_batch_for_mesh(
        tokens, mask, rewards, group_ids,
        batch_multiple=batch_multiple,
        seq_multiple=axes.get("sp", 1), pad_id=pad_id)
    if old_logp is not None and old_logp.shape != (tokens.shape[0],
                                                   tokens.shape[1] - 1):
        # Row AND column growth (sequence padding fires whenever sp>1:
        # bucketed S-1 is never sp-divisible) — padded positions are
        # outside the mask and never read.
        old_logp = _np.pad(old_logp,
                           ((0, tokens.shape[0] - old_logp.shape[0]),
                            (0, tokens.shape[1] - 1 - old_logp.shape[1])))
    row_sh = NamedSharding(mesh, restrict_spec(P(("dp", "fsdp")), mesh))
    grid_sh = NamedSharding(mesh, restrict_spec(P(("dp", "fsdp"), None),
                                                mesh))
    return (_jax.device_put(tokens, grid_sh),
            _jax.device_put(mask, grid_sh),
            _jax.device_put(rewards, row_sh),
            _jax.device_put(group_ids, row_sh),
            (_jax.device_put(old_logp, grid_sh)
             if old_logp is not None else None))
