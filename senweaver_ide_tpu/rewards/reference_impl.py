"""Pure-Python golden-model of the TS reward computation.

A direct, conditional-for-conditional transcription of the *semantics* of
``_computeRewardSignals`` (``common/traceCollectorService.ts:668-788``), used
only as the oracle in golden tests against the branchless jit head
(:mod:`senweaver_ide_tpu.rewards.head`). Keep this boring and readable; never
optimize it.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..traces.schema import SpanType, Trace

_WEIGHTS = {
    "user_feedback": 0.25,
    "task_completion": 0.18,
    "tool_success_rate": 0.12,
    "tool_call_reliability": 0.08,
    "tool_call_efficiency": 0.05,
    "tool_duration_efficiency": 0.05,
    "response_efficiency": 0.08,
    "token_efficiency": 0.08,
    "conversation_efficiency": 0.11,
}


def compute_reward_signals(trace: Trace) -> Tuple[List[Dict], Optional[float]]:
    """Returns (dims, final_reward) exactly as the TS would."""
    dims: List[Dict] = []
    s = trace.summary
    is_agent = trace.chat_mode == "agent"

    # Dim 1: user feedback (:677-679)
    fb = 1.0 if s.user_feedback == "good" else (-1.0 if s.user_feedback == "bad" else 0.0)
    dims.append({"name": "user_feedback", "value": fb})

    # Dim 2: task completion (:682-692)
    completion = 0.5
    if trace.end_time is not None and not s.has_errors:
        completion = 0.8
    if s.has_errors:
        completion = -0.5
    if s.user_feedback == "good":
        completion = 1.0
    dims.append({"name": "task_completion", "value": completion})

    # Dims 3-5b, gated on tool calls (:696-729)
    if s.total_tool_calls > 0:
        rate = s.tool_calls_succeeded / s.total_tool_calls
        dims.append({"name": "tool_success_rate", "value": rate * 2 - 1})

        severe, moderate, minor = (5, 3, 2) if is_agent else (3, 2, 1)
        if s.tool_calls_failed >= severe:
            penalty = -1.0
        elif s.tool_calls_failed >= moderate:
            penalty = -0.5
        elif s.tool_calls_failed >= minor:
            penalty = -0.2
        else:
            penalty = 1.0
        dims.append({"name": "tool_call_reliability", "value": penalty})

        excellent, goodt, fair = (8, 15, 25) if is_agent else (3, 6, 10)
        if s.total_tool_calls > fair:
            count_score = -0.8
        elif s.total_tool_calls > goodt:
            count_score = -0.3
        elif s.total_tool_calls > excellent:
            count_score = 0.3
        else:
            count_score = 1.0
        dims.append({"name": "tool_call_efficiency", "value": count_score})

        if s.total_tool_duration_ms > 0:
            avg = s.total_tool_duration_ms / s.total_tool_calls
            if avg > 10000:
                dur = -0.5
            elif avg > 3000:
                dur = 0.0
            elif avg > 1000:
                dur = 0.5
            else:
                dur = 1.0
            dims.append({"name": "tool_duration_efficiency", "value": dur})

    # Dim 6: response efficiency (:732-737)
    if s.total_llm_calls > 0:
        t = 3 if is_agent else 1
        eff = max(-1.0, 1.0 - max(0, s.total_llm_calls - t) * 0.4)
        dims.append({"name": "response_efficiency", "value": eff})

    # Dim 7: token efficiency (:739-749)
    if s.total_tokens > 0:
        excellent, goodt, fair = (5000, 15000, 30000) if is_agent else (2000, 5000, 10000)
        if s.total_tokens > fair:
            tok = -0.5
        elif s.total_tokens > goodt:
            tok = 0.0
        elif s.total_tokens > excellent:
            tok = 0.5
        else:
            tok = 1.0
        dims.append({"name": "token_efficiency", "value": tok})

    # Dim 8: conversation efficiency (:752-763)
    user_msgs = sum(1 for sp in trace.spans if sp.type is SpanType.USER_MESSAGE)
    asst_msgs = sum(1 for sp in trace.spans if sp.type is SpanType.ASSISTANT_MESSAGE)
    turns = min(user_msgs, asst_msgs)
    if turns > 0:
        t = 3 if is_agent else 2
        if turns > t * 3:
            ts = -0.8
        elif turns > t * 2:
            ts = -0.3
        elif turns > t:
            ts = 0.3
        else:
            ts = 1.0
        dims.append({"name": "conversation_efficiency", "value": ts})

    # finalReward: weight-renormalized sum over present dims (:766-787)
    weighted = 0.0
    total_w = 0.0
    for d in dims:
        w = _WEIGHTS.get(d["name"], 0.05)
        weighted += d["value"] * w
        total_w += w
    final = weighted / total_w if total_w > 0 else None
    return dims, final
