"""Jit-compiled 9-dimension chatMode-adaptive reward head.

Bit-level semantic port of ``_computeRewardSignals``
(``common/traceCollectorService.ts:668-788``). The TS implementation builds a
*variable-length* list of (name, value) dims — dims appear only when their
denominators are nonzero — and renormalizes weights over the *present* dims
(:777-784). The TPU design keeps a fixed-width ``(9,)`` dim vector plus a
``(9,)`` presence mask, so the computation is branchless, jittable, and
vmappable over a trace batch, while ``finalReward`` is numerically identical
to the TS weighted renormalized sum.

Threshold tables (traceCollectorService.ts:701-762, BASELINE.md):

==========================  =================  =================
quantity                    agent mode         normal mode
==========================  =================  =================
tool-fail severe/mod/minor  5 / 3 / 2          3 / 2 / 1
tool-count exc/good/fair    8 / 15 / 25        3 / 6 / 10
token exc/good/fair         5k / 15k / 30k     2k / 5k / 10k
LLM-call threshold T        3                  1
turn threshold T            3                  2
==========================  =================  =================
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..obs.runtime_profile import ProfiledFunction
from ..traces import features as F
from ..traces.schema import Trace
from ..traces.features import trace_features

# Dim indices in the fixed-width reward vector.
D_USER_FEEDBACK = 0
D_TASK_COMPLETION = 1
D_TOOL_SUCCESS_RATE = 2
D_TOOL_CALL_RELIABILITY = 3
D_TOOL_CALL_EFFICIENCY = 4
D_TOOL_DURATION_EFFICIENCY = 5
D_RESPONSE_EFFICIENCY = 6
D_TOKEN_EFFICIENCY = 7
D_CONVERSATION_EFFICIENCY = 8
N_DIMS = 9

DIM_NAMES = (
    "user_feedback",
    "task_completion",
    "tool_success_rate",
    "tool_call_reliability",
    "tool_call_efficiency",
    "tool_duration_efficiency",
    "response_efficiency",
    "token_efficiency",
    "conversation_efficiency",
)

# finalReward weights (traceCollectorService.ts:766-776).
WEIGHTS = jnp.array([0.25, 0.18, 0.12, 0.08, 0.05, 0.05, 0.08, 0.08, 0.11],
                    dtype=jnp.float32)

# Threshold tables, row 0 = normal, row 1 = agent.
_FAIL_T = jnp.array([[3.0, 2.0, 1.0], [5.0, 3.0, 2.0]])      # severe/moderate/minor
_COUNT_T = jnp.array([[3.0, 6.0, 10.0], [8.0, 15.0, 25.0]])  # excellent/good/fair
_TOKEN_T = jnp.array([[2000.0, 5000.0, 10000.0],
                      [5000.0, 15000.0, 30000.0]])           # excellent/good/fair
_LLM_T = jnp.array([1.0, 3.0])
_TURN_T = jnp.array([2.0, 3.0])


class RewardOutput(NamedTuple):
    """Fixed-width reward head output for one trace (or a batch when vmapped)."""

    dims: jax.Array      # (9,) dim values; 0 where absent
    mask: jax.Array      # (9,) 1.0 where the dim is present
    final_reward: jax.Array  # () weight-renormalized sum over present dims


def reward_head(feat: jax.Array) -> RewardOutput:
    """Compute the 9-dim reward vector from one ``(N_FEATURES,)`` feature row.

    Pure, branchless; ``jax.vmap(reward_head)`` scores a whole trace store.
    """
    feat = feat.astype(jnp.float32)
    agent = feat[F.F_IS_AGENT].astype(jnp.int32)  # 0 normal / 1 agent
    fb = feat[F.F_FEEDBACK]
    ended = feat[F.F_ENDED] > 0.5
    has_err = feat[F.F_HAS_ERRORS] > 0.5
    tool_calls = feat[F.F_TOOL_CALLS]
    tool_ok = feat[F.F_TOOL_OK]
    tool_fail = feat[F.F_TOOL_FAIL]
    tool_dur = feat[F.F_TOOL_DURATION_MS]
    llm_calls = feat[F.F_LLM_CALLS]
    tokens = feat[F.F_TOKENS]
    turns = jnp.minimum(feat[F.F_USER_MSGS], feat[F.F_ASSISTANT_MSGS])
    good = fb > 0.5

    # Dim 1: user feedback (ref :677-679). Always present.
    d_feedback = fb

    # Dim 2: task completion (ref :682-692). Always present. The TS applies
    # the branches in source order, so `good` overrides everything.
    d_completion = jnp.float32(0.5)
    d_completion = jnp.where(ended & ~has_err, 0.8, d_completion)
    d_completion = jnp.where(has_err, -0.5, d_completion)
    d_completion = jnp.where(good, 1.0, d_completion)

    # Dim 3: tool success rate → [-1, 1] (ref :697-698).
    safe_calls = jnp.maximum(tool_calls, 1.0)
    d_success = (tool_ok / safe_calls) * 2.0 - 1.0

    # Dim 4: tool-call reliability, adaptive fail thresholds (ref :701-708).
    ft = _FAIL_T[agent]
    d_reliability = jnp.where(
        tool_fail >= ft[0], -1.0,
        jnp.where(tool_fail >= ft[1], -0.5,
                  jnp.where(tool_fail >= ft[2], -0.2, 1.0)))

    # Dim 5: tool-call count efficiency (ref :710-718).
    ct = _COUNT_T[agent]
    d_count = jnp.where(
        tool_calls > ct[2], -0.8,
        jnp.where(tool_calls > ct[1], -0.3,
                  jnp.where(tool_calls > ct[0], 0.3, 1.0)))

    # Dim 5b: tool duration efficiency, avg-duration bands (ref :721-729).
    avg_dur = tool_dur / safe_calls
    d_duration = jnp.where(
        avg_dur > 10000.0, -0.5,
        jnp.where(avg_dur > 3000.0, 0.0,
                  jnp.where(avg_dur > 1000.0, 0.5, 1.0)))

    # Dim 6: response efficiency (ref :733-737).
    llm_t = _LLM_T[agent]
    d_response = jnp.maximum(
        -1.0, 1.0 - jnp.maximum(0.0, llm_calls - llm_t) * 0.4)

    # Dim 7: token efficiency (ref :740-749).
    tt = _TOKEN_T[agent]
    d_token = jnp.where(
        tokens > tt[2], -0.5,
        jnp.where(tokens > tt[1], 0.0,
                  jnp.where(tokens > tt[0], 0.5, 1.0)))

    # Dim 8: conversation efficiency, turn bands (ref :752-763).
    turn_t = _TURN_T[agent]
    d_turns = jnp.where(
        turns > turn_t * 3.0, -0.8,
        jnp.where(turns > turn_t * 2.0, -0.3,
                  jnp.where(turns > turn_t, 0.3, 1.0)))

    dims = jnp.stack([d_feedback, d_completion, d_success, d_reliability,
                      d_count, d_duration, d_response, d_token, d_turns])

    # Presence mask — dims appear only when denominators are nonzero
    # (ref: `if (s.totalToolCalls > 0)` :696, `totalToolDurationMs > 0` :720,
    # `totalLLMCalls > 0` :732, `totalTokens > 0` :739, `turns > 0` :755).
    has_tools = tool_calls > 0.0
    mask = jnp.stack([
        jnp.float32(1.0),                       # user_feedback: always
        jnp.float32(1.0),                       # task_completion: always
        has_tools.astype(jnp.float32),          # tool_success_rate
        has_tools.astype(jnp.float32),          # tool_call_reliability
        has_tools.astype(jnp.float32),          # tool_call_efficiency
        (has_tools & (tool_dur > 0.0)).astype(jnp.float32),
        (llm_calls > 0.0).astype(jnp.float32),  # response_efficiency
        (tokens > 0.0).astype(jnp.float32),     # token_efficiency
        (turns > 0.0).astype(jnp.float32),      # conversation_efficiency
    ])

    dims = dims * mask
    total_w = jnp.sum(WEIGHTS * mask)
    final = jnp.sum(dims * WEIGHTS) / jnp.maximum(total_w, 1e-12)
    return RewardOutput(dims=dims, mask=mask, final_reward=final)


# Jitted batch scorer: (B, N_FEATURES) -> RewardOutput of (B, 9)/(B, 9)/(B,).
# Profiled (obs/runtime_profile.py): batch-size variety is the expected
# retrace axis here — the ledger shows whether callers bucket batches.
reward_head_batch = ProfiledFunction(
    jax.jit(jax.vmap(reward_head)), "reward.head_batch",
    storm_threshold=32)
_reward_head_jit = jax.jit(reward_head)


def score_trace(trace: Trace) -> float:
    """Score one host-side trace in place, mirroring the reference's mutation
    of ``trace.summary`` (``_computeRewardSignals`` writes ``rewardDimensions``
    + ``finalReward``, traceCollectorService.ts:786-787)."""
    out = _reward_head_jit(jnp.asarray(trace_features(trace)))
    dims, mask = jax.device_get(out.dims), jax.device_get(out.mask)
    trace.summary.reward_dimensions = [
        {"name": DIM_NAMES[i], "value": float(dims[i])}
        for i in range(N_DIMS) if mask[i] > 0.5
    ]
    trace.summary.final_reward = float(jax.device_get(out.final_reward))
    return trace.summary.final_reward


def score_traces(traces) -> jax.Array:
    """Batch-score traces; returns the (B,) finalReward vector and updates
    each host trace's summary."""
    from ..traces.features import batch_features

    feats = batch_features(traces)
    if feats.shape[0] == 0:
        return jnp.zeros((0,), dtype=jnp.float32)
    out = reward_head_batch(jnp.asarray(feats))
    dims = jax.device_get(out.dims)
    masks = jax.device_get(out.mask)
    finals = jax.device_get(out.final_reward)
    for i, tr in enumerate(traces):
        tr.summary.reward_dimensions = [
            {"name": DIM_NAMES[j], "value": float(dims[i, j])}
            for j in range(N_DIMS) if masks[i, j] > 0.5
        ]
        tr.summary.final_reward = float(finals[i])
    return out.final_reward
