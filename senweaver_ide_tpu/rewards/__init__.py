from .head import (DIM_NAMES, N_DIMS, WEIGHTS, RewardOutput, reward_head,
                   reward_head_batch, score_trace, score_traces)
