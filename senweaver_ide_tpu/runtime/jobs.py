"""Trainer job runner: the control plane's execution side.

``ControlServer.submit`` records jobs; this module RUNS them — the piece
that makes ``senweaver-ctl submit '{"type": "grpo", ...}'`` actually
train (the reference's code-cli drives a live server the same way;
cli/src role, SURVEY.md §2.6 / §7 step 8).

A ``JobRunner`` owns one worker thread (TPU steps serialize on the chip
anyway) draining a queue of submitted jobs. Job specs are dicts:

- ``{"type": "grpo", "tasks": [...], "rounds": N, "group_size": G,
   "ppo_epochs": E, "accum_steps": A}`` — N on-policy rounds through a
  session factory the host process supplies (the runner is transport;
  the factory decides policy/engine/workspace).
- ``{"type": "eval_rules", "rules": [...]}`` — score a rule-set over
  the 6-pattern suite (apo/eval.py), the APO beam's scoring unit.

Progress and results land on the Job record (visible over
``senweaver-ctl status`` / ``watch``); ``stop`` flips the job's status,
which the runner checks between rounds (cooperative cancel).
"""

from __future__ import annotations

import queue
import threading
import traceback
from typing import Any, Callable, Dict, Optional

from .control import ControlServer, Job


class JobRunner:
    """Single-worker executor wired into a ControlServer."""

    def __init__(self, server: ControlServer, *,
                 make_session: Callable[..., "RolloutSession"],
                 train_state=None, model_config=None, mesh=None,
                 reward_override=None, pad_id: int = 0,
                 max_len: Optional[int] = None,
                 apo=None, collector=None, engine=None):
        # Factory contract: make_session() for rollout episodes;
        # make_session(rules=[...]) for rule-scored eval sessions (the
        # rules render into the session's APO prompt section);
        # make_session(rules=..., thread_id=...) for the online loop.
        self.server = server
        self.make_session = make_session
        self.state = train_state
        self.model_config = model_config
        self.mesh = mesh
        self.reward_override = reward_override
        self.pad_id = pad_id
        self.max_len = max_len
        # Online-improvement cycle dependencies (job type "online"):
        # the APOService + shared collector (+ serving engine for
        # weight publication).
        self.apo = apo
        self.collector = collector
        self.engine = engine
        self._queue: "queue.Queue[Job]" = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        server.on_submit = self._enqueue
        server.register("job_result", self._job_result)

    # -- server-side hooks -------------------------------------------------
    def _enqueue(self, job: Job) -> None:
        self._queue.put(job)

    def _job_result(self, params: Any) -> Dict[str, Any]:
        job_id = params.get("job_id") if isinstance(params, dict) else \
            str(params)
        job = self.server.jobs.get(job_id)
        if job is None:
            raise KeyError(f"unknown job: {job_id}")
        return {"job_id": job_id, "status": job.status,
                "result": job.result}

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._work, daemon=True,
                                        name="senweaver-job-runner")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)

    # -- execution ---------------------------------------------------------
    def _work(self) -> None:
        while not self._stop.is_set():
            try:
                job = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            # Status transitions race with the server thread's stop RPC;
            # the server's public check-and-set serializes them so a
            # stop is never clobbered.
            if not self.server.cas_job_status(job, "running"):
                continue                        # cancelled while queued
            try:
                job.result = self._run_job(job)
                self.server.cas_job_status(job, "done")
            except Exception as e:
                # result BEFORE status (a poller keying on the terminal
                # status must find the error populated), and the same
                # CAS discipline as the success path (a stop that
                # already ACKed must not be overwritten).
                job.result = {"error": f"{type(e).__name__}: {e}",
                              "traceback": traceback.format_exc()[-2000:]}
                self.server.cas_job_status(job, "failed")

    def _run_job(self, job: Job) -> Dict[str, Any]:
        spec = job.params if isinstance(job.params, dict) else {}
        kind = spec.get("type", "grpo")
        if kind == "grpo":
            return self._run_grpo(job, spec)
        if kind == "eval_rules":
            return self._run_eval_rules(spec)
        if kind == "online":
            return self._run_online(job, spec)
        raise ValueError(f"unknown job type {kind!r}")

    def _cancelled(self, job: Job) -> bool:
        return job.status == "stopped" or self._stop.is_set()

    def _run_grpo(self, job: Job, spec: Dict[str, Any]) -> Dict[str, Any]:
        if self.state is None or self.model_config is None:
            raise ValueError("runner was built without a train state")
        from ..training import grpo_round

        tasks = spec.get("tasks") or ["improve the workspace"]
        rounds = int(spec.get("rounds", 1))
        round_metrics = []
        for r in range(rounds):
            if self._cancelled(job):
                break
            out = grpo_round(
                self.state, self.model_config, self.mesh,
                self.make_session, tasks,
                group_size=int(spec.get("group_size", 2)),
                pad_id=self.pad_id, max_len=self.max_len,
                ppo_epochs=int(spec.get("ppo_epochs", 1)),
                accum_steps=int(spec.get("accum_steps", 1)),
                reward_override=self.reward_override)
            self.state = out.state
            round_metrics.append(
                {"round": r,
                 "episodes": len(out.episodes),
                 "reward_mean": (sum(e.reward for e in out.episodes)
                                 / max(len(out.episodes), 1)),
                 **{k: round(v, 6) for k, v in out.metrics.items()}})
        return {"rounds_done": len(round_metrics),
                "step": int(self.state.step),
                "metrics": round_metrics}

    def _run_online(self, job: Job, spec: Dict[str, Any]) -> Dict[str, Any]:
        """The full improvement cycle as a control-plane job: GRPO weight
        updates every round + the APO analyze/beam cycle on its gates
        (training/online.py). Requires the runner to be built with
        apo= and collector=."""
        if self.state is None or self.model_config is None:
            raise ValueError("runner was built without a train state")
        if self.apo is None or self.collector is None:
            raise ValueError("online jobs need apo= and collector= on "
                             "the runner")
        from ..training.online import OnlineImprovementLoop

        loop = OnlineImprovementLoop(
            self.state, self.model_config, self.mesh, self.make_session,
            spec.get("tasks") or ["improve the workspace"],
            apo=self.apo, collector=self.collector, engine=self.engine,
            group_size=int(spec.get("group_size", 2)),
            pad_id=self.pad_id, max_len=self.max_len,
            ppo_epochs=int(spec.get("ppo_epochs", 1)),
            # Default is concurrent collection (requires a thread_id-
            # aware factory); a runner built on the legacy rules-only
            # factory contract must SUBMIT {"max_parallel": 1} — serial
            # collection is the attribution-safe mode the loop accepts
            # for such factories.
            max_parallel=int(spec.get("max_parallel", 8)),
            reward_override=self.reward_override)
        rounds = []
        for _ in range(int(spec.get("rounds", 1))):
            if self._cancelled(job):
                break
            r = loop.run_round()
            rounds.append({"round": r.round_idx,
                           "reward_mean": round(r.reward_mean, 4),
                           "episodes": r.episodes,
                           "rules_active": len(r.rules),
                           "analyzed": r.analyzed,
                           "beam_ran": r.beam_ran})
        self.state = loop.state
        return {"rounds_done": len(rounds), "step": int(self.state.step),
                "optimized_rules": loop.current_rules(),
                "rounds": rounds}

    def _run_eval_rules(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        from ..apo.eval import evaluate_rules
        rules = list(spec.get("rules", []))
        score = evaluate_rules(rules, lambda r: self.make_session(rules=r))
        return {"rules": rules, "final_reward": score}
