"""Minimal msgpack codec for the control-plane RPC.

The reference CLI speaks both JSON-RPC and msgpack-RPC to its server
(cli/src/json_rpc.rs, cli/src/msgpack_rpc.rs — SURVEY.md §2.6/L10). This
is the msgpack half for the trainer's control plane: a dependency-free
subset codec covering exactly the types RPC envelopes use — nil, bool,
ints, float64, str, bin, array, map.

Wire-format subset (msgpack spec):
  nil 0xc0 | false 0xc2 | true 0xc3 | float64 0xcb
  positive fixint 0x00-0x7f | negative fixint 0xe0-0xff
  uint8/16/32/64 0xcc-0xcf | int8/16/32/64 0xd0-0xd3
  fixstr 0xa0-0xbf | str8/16/32 0xd9-0xdb | bin8/16/32 0xc4-0xc6
  fixarray 0x90-0x9f | array16/32 0xdc-0xdd
  fixmap 0x80-0x8f | map16/32 0xde-0xdf
"""

from __future__ import annotations

import struct
from typing import Any, Tuple


def pack(obj: Any) -> bytes:
    out = bytearray()
    _pack_into(obj, out)
    return bytes(out)


def _pack_into(obj: Any, out: bytearray) -> None:
    if obj is None:
        out.append(0xC0)
    elif obj is True:
        out.append(0xC3)
    elif obj is False:
        out.append(0xC2)
    elif isinstance(obj, int):
        _pack_int(obj, out)
    elif isinstance(obj, float):
        out.append(0xCB)
        out += struct.pack(">d", obj)
    elif isinstance(obj, str):
        data = obj.encode("utf-8")
        n = len(data)
        if n < 32:
            out.append(0xA0 | n)
        elif n < 0x100:
            out += bytes((0xD9, n))
        elif n < 0x10000:
            out.append(0xDA)
            out += struct.pack(">H", n)
        else:
            out.append(0xDB)
            out += struct.pack(">I", n)
        out += data
    elif isinstance(obj, (bytes, bytearray)):
        n = len(obj)
        if n < 0x100:
            out += bytes((0xC4, n))
        elif n < 0x10000:
            out.append(0xC5)
            out += struct.pack(">H", n)
        else:
            out.append(0xC6)
            out += struct.pack(">I", n)
        out += bytes(obj)
    elif isinstance(obj, (list, tuple)):
        n = len(obj)
        if n < 16:
            out.append(0x90 | n)
        elif n < 0x10000:
            out.append(0xDC)
            out += struct.pack(">H", n)
        else:
            out.append(0xDD)
            out += struct.pack(">I", n)
        for item in obj:
            _pack_into(item, out)
    elif isinstance(obj, dict):
        n = len(obj)
        if n < 16:
            out.append(0x80 | n)
        elif n < 0x10000:
            out.append(0xDE)
            out += struct.pack(">H", n)
        else:
            out.append(0xDF)
            out += struct.pack(">I", n)
        for k, v in obj.items():
            _pack_into(k, out)
            _pack_into(v, out)
    else:
        raise TypeError(f"msgpack_lite cannot pack {type(obj).__name__}")


def _pack_int(v: int, out: bytearray) -> None:
    if 0 <= v < 0x80:
        out.append(v)
    elif -32 <= v < 0:
        out.append(v & 0xFF)
    elif 0 <= v < 0x100:
        out += bytes((0xCC, v))
    elif 0 <= v < 0x10000:
        out.append(0xCD)
        out += struct.pack(">H", v)
    elif 0 <= v < 0x100000000:
        out.append(0xCE)
        out += struct.pack(">I", v)
    elif v >= 0:
        out.append(0xCF)
        out += struct.pack(">Q", v)
    elif v >= -0x80:
        out.append(0xD0)
        out += struct.pack(">b", v)
    elif v >= -0x8000:
        out.append(0xD1)
        out += struct.pack(">h", v)
    elif v >= -0x80000000:
        out.append(0xD2)
        out += struct.pack(">i", v)
    else:
        out.append(0xD3)
        out += struct.pack(">q", v)


MAX_DEPTH = 64     # far beyond any RPC envelope; a ~1 KB payload of
                   # nested fixarray headers must raise ValueError (which
                   # the server's framing probe handles), NOT RecursionError


def unpack(data: bytes) -> Any:
    """Decode one msgpack value; trailing bytes are an error."""
    obj, off = _unpack_from(data, 0)
    if off != len(data):
        raise ValueError(f"{len(data) - off} trailing bytes after value")
    return obj


def unpack_prefix(data: bytes) -> Tuple[Any, int]:
    """Decode one value from the head of ``data``; returns (value, end)."""
    return _unpack_from(data, 0)


def _unpack_from(data: bytes, off: int, depth: int = 0) -> Tuple[Any, int]:
    if depth > MAX_DEPTH:
        raise ValueError(f"nesting exceeds MAX_DEPTH={MAX_DEPTH}")
    if off >= len(data):
        raise ValueError("truncated msgpack data")
    b = data[off]
    off += 1
    if b <= 0x7F:                           # positive fixint
        return b, off
    if b >= 0xE0:                           # negative fixint
        return b - 0x100, off
    if 0x80 <= b <= 0x8F:                   # fixmap
        return _unpack_map(data, off, b & 0x0F, depth)
    if 0x90 <= b <= 0x9F:                   # fixarray
        return _unpack_array(data, off, b & 0x0F, depth)
    if 0xA0 <= b <= 0xBF:                   # fixstr
        return _take_str(data, off, b & 0x1F)
    if b == 0xC0:
        return None, off
    if b == 0xC2:
        return False, off
    if b == 0xC3:
        return True, off
    if b in (0xC4, 0xC5, 0xC6):             # bin8/16/32
        n, off = _take_len(data, off, (1, 2, 4)[b - 0xC4])
        _need(data, off, n)
        return bytes(data[off:off + n]), off + n
    if b == 0xCB:                           # float64
        _need(data, off, 8)
        return struct.unpack_from(">d", data, off)[0], off + 8
    if b == 0xCA:                           # float32
        _need(data, off, 4)
        return struct.unpack_from(">f", data, off)[0], off + 4
    if b in (0xCC, 0xCD, 0xCE, 0xCF):       # uint8/16/32/64
        size = 1 << (b - 0xCC)
        _need(data, off, size)
        return int.from_bytes(data[off:off + size], "big"), off + size
    if b in (0xD0, 0xD1, 0xD2, 0xD3):       # int8/16/32/64
        size = 1 << (b - 0xD0)
        _need(data, off, size)
        return int.from_bytes(data[off:off + size], "big",
                              signed=True), off + size
    if b in (0xD9, 0xDA, 0xDB):             # str8/16/32
        n, off = _take_len(data, off, (1, 2, 4)[b - 0xD9])
        return _take_str(data, off, n)
    if b in (0xDC, 0xDD):                   # array16/32
        n, off = _take_len(data, off, (2, 4)[b - 0xDC])
        return _unpack_array(data, off, n, depth)
    if b in (0xDE, 0xDF):                   # map16/32
        n, off = _take_len(data, off, (2, 4)[b - 0xDE])
        return _unpack_map(data, off, n, depth)
    raise ValueError(f"unsupported msgpack type byte 0x{b:02x}")


def _need(data: bytes, off: int, n: int) -> None:
    if off + n > len(data):
        raise ValueError("truncated msgpack data")


def _take_len(data: bytes, off: int, size: int) -> Tuple[int, int]:
    _need(data, off, size)
    return int.from_bytes(data[off:off + size], "big"), off + size


def _take_str(data: bytes, off: int, n: int) -> Tuple[str, int]:
    _need(data, off, n)
    return data[off:off + n].decode("utf-8", errors="replace"), off + n


def _unpack_array(data: bytes, off: int, n: int,
                  depth: int) -> Tuple[list, int]:
    out = []
    for _ in range(n):
        item, off = _unpack_from(data, off, depth + 1)
        out.append(item)
    return out, off


def _unpack_map(data: bytes, off: int, n: int,
                depth: int) -> Tuple[dict, int]:
    out = {}
    for _ in range(n):
        k, off = _unpack_from(data, off, depth + 1)
        v, off = _unpack_from(data, off, depth + 1)
        out[k] = v
    return out, off


def is_msgpack_request(first_byte: int) -> bool:
    """RPC requests are maps: fixmap / map16 / map32 lead bytes. JSON
    requests start with '{' (0x7b, a positive fixint in msgpack) so the
    two framings are unambiguous at byte 0."""
    return (0x80 <= first_byte <= 0x8F) or first_byte in (0xDE, 0xDF)
