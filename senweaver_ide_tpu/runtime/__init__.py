"""Native runtime: mmap trace ring, batched tokenization, job control.

The TPU-build analogue of the reference's native layer (SURVEY.md §2.6):
where the reference uses prebuilt C++ node deps (@vscode/sqlite3, spdlog,
ripgrep) and a 17.5k-LoC Rust code-cli, this package provides a C++ mmap
ring-buffer span store + batched byte tokenizer (native/trace_ring.cpp,
via ctypes) and the senweaver-ctl CLI (native/senweaver_ctl.cpp) speaking
JSON-RPC over a unix socket to ControlServer.
"""

from .control import (DEFAULT_SOCKET, ControlClient, ControlError,
                      ControlServer, Job)
from .jobs import JobRunner
from .native import (TraceRing, build_native, byte_tokenize_batch,
                     ctl_binary_path, native_available)

__all__ = [
    "DEFAULT_SOCKET", "ControlClient", "ControlError", "ControlServer", "Job", "JobRunner", "TraceRing", "build_native",
    "byte_tokenize_batch", "ctl_binary_path", "native_available",
]
