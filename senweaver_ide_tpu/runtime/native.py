"""ctypes bindings for the native runtime library (native/trace_ring.cpp).

Builds on first use via the Makefile when g++ is available (the image
ships g++/make; pybind11 does not exist here, hence ctypes — SURVEY.md
§2.6). Every consumer has a pure-Python fallback, so the framework works
without the native layer — it is an optimization, not a dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Tuple

import numpy as np

_RUNTIME_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_RUNTIME_DIR, "_build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libsenweaver_native.so")
_CTL_PATH = os.path.join(_BUILD_DIR, "senweaver-ctl")
_NATIVE_SRC = os.path.join(_RUNTIME_DIR, "..", "..", "native")

_lib: Optional[ctypes.CDLL] = None
_build_attempted = False


def build_native(force: bool = False) -> bool:
    """Run the Makefile; returns True when the shared library exists."""
    global _build_attempted
    if (os.path.exists(_LIB_PATH) and os.path.exists(_CTL_PATH)
            and not force):
        return True
    if _build_attempted and not force:
        return os.path.exists(_LIB_PATH)
    _build_attempted = True
    try:
        subprocess.run(["make", "-C", os.path.abspath(_NATIVE_SRC)],
                       check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError):
        return False
    return os.path.exists(_LIB_PATH)


def ctl_binary_path() -> Optional[str]:
    """Path to the senweaver-ctl CLI, building if needed."""
    if not os.path.exists(_CTL_PATH):
        build_native()
    return _CTL_PATH if os.path.exists(_CTL_PATH) else None


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    if not build_native():
        return None
    lib = ctypes.CDLL(_LIB_PATH)
    lib.ring_create.restype = ctypes.c_void_p
    lib.ring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                ctypes.c_uint64]
    lib.ring_open.restype = ctypes.c_void_p
    lib.ring_open.argtypes = [ctypes.c_char_p]
    lib.ring_append.restype = ctypes.c_int64
    lib.ring_append.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_uint32]
    lib.ring_read.restype = ctypes.c_int64
    lib.ring_read.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                              ctypes.c_char_p, ctypes.c_uint32]
    for fn in ("ring_head", "ring_dropped", "ring_capacity"):
        getattr(lib, fn).restype = ctypes.c_uint64
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    lib.ring_close.argtypes = [ctypes.c_void_p]
    lib.byte_tokenize_batch.restype = ctypes.c_int
    lib.byte_tokenize_batch.argtypes = [
        ctypes.POINTER(ctypes.c_char_p),
        np.ctypeslib.ndpointer(np.int32), ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32,
        np.ctypeslib.ndpointer(np.int32),
        np.ctypeslib.ndpointer(np.int32)]
    _lib = lib
    return _lib


def native_available() -> bool:
    return _load() is not None


class TraceRing:
    """mmap ring-buffer span store (native; crash-durable).

    The bound analogue of the reference's bounded trace storage
    (MAX_TRACES×MAX_SPANS, traceCollectorService.ts:219-220): old records
    are overwritten once the ring wraps."""

    def __init__(self, path: str, *, slot_size: int = 4096,
                 n_slots: int = 4096):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable (g++/make "
                               "missing?) — use the JSONL TraceStore")
        self._lib = lib
        self._h = lib.ring_create(path.encode(), slot_size, n_slots)
        if not self._h:
            raise OSError(f"ring_create failed for {path}")
        self.slot_size = slot_size

    def append(self, payload: bytes) -> int:
        """Returns the record's global index; raises on oversize."""
        idx = self._lib.ring_append(self._h, payload, len(payload))
        if idx < 0:
            raise ValueError(f"payload of {len(payload)} bytes exceeds "
                             f"slot size {self.slot_size}")
        return idx

    def read(self, idx: int) -> Optional[bytes]:
        buf = ctypes.create_string_buffer(self.slot_size)
        n = self._lib.ring_read(self._h, idx, buf, self.slot_size)
        if n < 0:
            return None
        return buf.raw[:n]

    @property
    def head(self) -> int:
        return int(self._lib.ring_head(self._h))

    @property
    def dropped(self) -> int:
        return int(self._lib.ring_dropped(self._h))

    @property
    def capacity(self) -> int:
        return int(self._lib.ring_capacity(self._h))

    def window(self) -> Tuple[int, int]:
        """(first_valid_idx, head)."""
        head = self.head
        return max(0, head - self.capacity), head

    def close(self) -> None:
        if self._h:
            self._lib.ring_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def byte_tokenize_batch(texts: List[str], *, max_len: int,
                        bos_id: int = 256, pad_id: int = 258
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """Batched byte tokenization in C++ — the host data-loader hot path
    feeding the JAX pipeline. Falls back to numpy when the native library
    is missing. Returns (tokens (N, max_len) int32, lengths (N,) int32)."""
    n = len(texts)
    out = np.empty((n, max_len), np.int32)
    lens = np.empty((n,), np.int32)
    lib = _load()
    raw = [t.encode("utf-8") for t in texts]
    if lib is not None:
        arr = (ctypes.c_char_p * n)(*raw)
        text_lens = np.asarray([len(b) for b in raw], np.int32)
        lib.byte_tokenize_batch(arr, text_lens, n, max_len,
                                bos_id if bos_id is not None else -1,
                                pad_id, out, lens)
        return out, lens
    for i, b in enumerate(raw):
        ids = ([bos_id] if bos_id is not None else []) + list(b)
        ids = ids[:max_len]
        lens[i] = len(ids)
        out[i, :len(ids)] = ids
        out[i, len(ids):] = pad_id
    return out, lens
