"""JSON-RPC job-control server for the trainer runtime.

The server side of senweaver-ctl (native/senweaver_ctl.cpp): a unix-socket
JSON-RPC 2.0 endpoint through which jobs are submitted, inspected, and
stopped — the trainer-scoped role of the reference's Rust code-cli RPC
(cli/src/json_rpc.rs, SURVEY.md §2.6 / §7 step 8).

Builtin methods: ping, status, submit, stop; arbitrary methods register
via ``register``. Handlers run on the server thread — keep them short
(submit should enqueue, not train)."""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .msgpack_lite import is_msgpack_request, pack, unpack_prefix

DEFAULT_SOCKET = "/tmp/senweaver-ctl.sock"


class ControlError(RuntimeError):
    """JSON-RPC error response surfaced client-side."""

    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code


class ControlClient:
    """Minimal JSON-framing client for :class:`ControlServer`.

    The in-process counterpart of senweaver-ctl's send_request
    (native/senweaver_ctl.cpp): one connection per call, newline-framed
    JSON-RPC 2.0, optional auth token. Used by the dashboard's action
    endpoint and available to tests/tools."""

    def __init__(self, socket_path: str = DEFAULT_SOCKET, *,
                 token: Optional[str] = None, timeout: float = 10.0):
        self.socket_path = socket_path
        self.token = token
        self.timeout = timeout

    def call(self, method: str, params: Any = None, *,
             token: Optional[str] = None) -> Any:
        req: Dict[str, Any] = {"jsonrpc": "2.0", "id": 1, "method": method,
                               "params": params}
        auth = token if token is not None else self.token
        if auth is not None:
            req["auth"] = auth
        with socket.socket(socket.AF_UNIX) as c:
            c.settimeout(self.timeout)
            c.connect(self.socket_path)
            c.sendall(json.dumps(req).encode() + b"\n")
            c.shutdown(socket.SHUT_WR)
            data = b""
            while True:
                chunk = c.recv(65536)
                if not chunk:
                    break
                data += chunk
        resp = json.loads(data.decode())
        if "error" in resp:
            err = resp["error"] or {}
            raise ControlError(err.get("code", -32000),
                               err.get("message", "unknown error"))
        return resp.get("result")


@dataclasses.dataclass
class Job:
    job_id: str
    params: Any
    status: str = "queued"         # queued | running | done | stopped
    submitted_at: float = dataclasses.field(default_factory=time.time)
    result: Any = None


class ControlServer:
    """JSON-RPC / msgpack-RPC job-control endpoint.

    ``token``: when set, every method except ``ping`` requires the
    request to carry a matching ``auth`` field — the trainer-scoped
    analogue of the reference CLI's auth layer (cli/src/auth.rs).
    Requests whose first byte is a msgpack map are answered in msgpack
    (cli/src/msgpack_rpc.rs framing); JSON stays the default.
    """

    def __init__(self, socket_path: str = DEFAULT_SOCKET, *,
                 on_submit: Optional[Callable[[Job], None]] = None,
                 token: Optional[str] = None):
        self.socket_path = socket_path
        self.on_submit = on_submit
        self.token = token
        self.jobs: Dict[str, Job] = {}
        self._handlers: Dict[str, Callable[[Any], Any]] = {
            "ping": lambda p: "pong",
            "status": self._status,
            "submit": self._submit,
            "stop": self._stop,
        }
        self._next_job = 1
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._lock = threading.Lock()

    # -- builtin handlers --------------------------------------------------
    def _status(self, _params: Any) -> List[Dict[str, Any]]:
        return self.list_jobs()

    def list_jobs(self) -> List[Dict[str, Any]]:
        """Public job snapshot (the status RPC's payload) — also consumed
        in-process by the operator dashboard."""
        with self._lock:
            return [{"job_id": j.job_id, "status": j.status,
                     "submitted_at": j.submitted_at}
                    for j in self.jobs.values()]

    def _submit(self, params: Any) -> Dict[str, str]:
        with self._lock:
            job = Job(job_id=f"job-{self._next_job}", params=params)
            self._next_job += 1
            self.jobs[job.job_id] = job
        if self.on_submit:
            self.on_submit(job)
        return {"job_id": job.job_id, "status": job.status}

    def _stop(self, params: Any) -> Dict[str, str]:
        job_id = params.get("job_id") if isinstance(params, dict) else \
            str(params)
        with self._lock:
            job = self.jobs.get(job_id)
            if job is None:
                raise KeyError(f"unknown job: {job_id}")
            job.status = "stopped"
        return {"job_id": job_id, "status": "stopped"}

    def cas_job_status(self, job: Job, new_status: str, *,
                       unless: tuple = ("stopped",)) -> bool:
        """Atomically set ``job.status`` unless it is already in ``unless``.

        The public check-and-set executors need: a worker marking a job
        running/done/failed must not clobber a concurrent ``stop`` RPC
        (which writes under the same lock). Returns True when the
        transition happened."""
        with self._lock:
            if job.status in unless:
                return False
            job.status = new_status
            return True

    def register(self, method: str, fn: Callable[[Any], Any]) -> None:
        self._handlers[method] = fn

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(self.socket_path)
        self._sock.listen(8)
        self._sock.settimeout(0.2)
        self._running = True
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread:
            self._thread.join(timeout=2)
        if self._sock:
            self._sock.close()
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)

    def _serve(self) -> None:
        while self._running:
            try:
                conn, _ = self._sock.accept()  # type: ignore[union-attr]
            except socket.timeout:
                continue
            except OSError:
                break
            with conn:
                try:
                    data = b""
                    conn.settimeout(2.0)
                    msgpack_mode = False
                    while True:
                        chunk = conn.recv(65536)
                        if not chunk:
                            break
                        data += chunk
                        msgpack_mode = is_msgpack_request(data[0])
                        if msgpack_mode:
                            # msgpack has no line terminator: stop once
                            # one complete value has arrived (the client
                            # half-closes after writing anyway).
                            try:
                                unpack_prefix(data)
                                break
                            except ValueError:
                                continue
                        if b"\n" in data:
                            break
                    if msgpack_mode:
                        conn.sendall(self._dispatch_msgpack(data))
                    else:
                        resp = self._dispatch(data.decode(errors="replace"))
                        conn.sendall(resp.encode())
                except OSError:
                    pass

    def _handle_request(self, req: Dict[str, Any]) -> Dict[str, Any]:
        """Shared auth + dispatch core for both wire framings."""
        rid = req.get("id")
        method = req.get("method", "")
        if self.token and method != "ping" \
                and req.get("auth") != self.token:
            return {"jsonrpc": "2.0", "id": rid,
                    "error": {"code": -32001,
                              "message": "unauthorized: bad or missing "
                                         "auth token"}}
        handler = self._handlers.get(method)
        if handler is None:
            return {"jsonrpc": "2.0", "id": rid,
                    "error": {"code": -32601,
                              "message": f"method not found: {method}"}}
        try:
            return {"jsonrpc": "2.0", "id": rid,
                    "result": handler(req.get("params"))}
        except Exception as e:
            return {"jsonrpc": "2.0", "id": rid,
                    "error": {"code": -32000,
                              "message": f"{type(e).__name__}: {e}"}}

    def _dispatch(self, raw: str) -> str:
        # Every failure path must produce an error RESPONSE: an uncaught
        # exception here kills the serve thread (a one-packet DoS).
        try:
            req = json.loads(raw)
        except json.JSONDecodeError as e:
            return json.dumps({"jsonrpc": "2.0", "id": None,
                               "error": {"code": -32700,
                                         "message": f"parse error: {e}"}})
        try:
            if not isinstance(req, dict):
                raise ValueError("request must be a JSON object")
            return json.dumps(self._handle_request(req))
        except Exception as e:   # non-dict req, unserializable result, …
            return json.dumps({"jsonrpc": "2.0", "id": None,
                               "error": {"code": -32000,
                                         "message": f"{type(e).__name__}: "
                                                    f"{e}"}})

    def _dispatch_msgpack(self, raw: bytes) -> bytes:
        try:
            req, _end = unpack_prefix(raw)
            if not isinstance(req, dict):
                raise ValueError("request must be a map")
            # msgpack envelope may carry params as embedded JSON text
            # (the CLI has argv JSON in hand; cf. params_json below).
            if "params_json" in req and "params" not in req:
                pj = req.pop("params_json")
                req["params"] = json.loads(pj) if pj else None
        except (ValueError, json.JSONDecodeError, RecursionError) as e:
            return pack({"jsonrpc": "2.0", "id": None,
                         "error": {"code": -32700,
                                   "message": f"parse error: {e}"}})
        try:
            return pack(self._handle_request(req))
        except Exception as e:   # e.g. a handler result pack() rejects
            return pack({"jsonrpc": "2.0", "id": None,
                         "error": {"code": -32000,
                                   "message": f"{type(e).__name__}: {e}"}})
