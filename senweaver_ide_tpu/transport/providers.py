"""Provider registry: remote LLM endpoints the rollout layer can drive.

Mirrors `electron-main/llmMessage/sendLLMMessage.impl.ts` (:927
sendLLMMessageToProviderImplementation, 20 providers) and
`common/modelCapabilities.ts:17-90` (defaultProviderSettings): each
provider is an endpoint style + base URL + capability flags. In this
framework the LOCAL policy is the primary provider (rollouts and
training); remote providers exist for distillation/eval rollouts and
keep the reference's full registry shape. All remote calls go through
``transport.http_client.OpenAICompatClient`` (every provider below except
the local engine speaks the openai-compatible chat schema, exactly the
reference's `_sendOpenAICompatibleChat` consolidation :338).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class ProviderSettings:
    name: str
    # 'local' | 'openai-compat' | 'anthropic' | 'gemini'
    endpoint_style: str
    base_url: str = ""
    api_key_env: str = ""          # env var carrying the key
    supports_fim: bool = False
    supports_system_message: bool = True
    default_model: str = ""


PROVIDERS: Dict[str, ProviderSettings] = {p.name: p for p in [
    # The primary provider: the in-tree TPU sampler.
    ProviderSettings("local", "local",
                     default_model="qwen2.5-coder-1.5b"),
    ProviderSettings("anthropic", "anthropic",
                     base_url="https://api.anthropic.com",
                     api_key_env="ANTHROPIC_API_KEY",
                     default_model="claude-3-5-sonnet"),
    ProviderSettings("openai", "openai-compat",
                     base_url="https://api.openai.com/v1",
                     api_key_env="OPENAI_API_KEY",
                     default_model="gpt-4o"),
    ProviderSettings("gemini", "gemini",
                     base_url="https://generativelanguage.googleapis.com",
                     api_key_env="GEMINI_API_KEY",
                     default_model="gemini-2.0-flash"),
    ProviderSettings("deepseek", "openai-compat",
                     base_url="https://api.deepseek.com/v1",
                     api_key_env="DEEPSEEK_API_KEY", supports_fim=True,
                     default_model="deepseek-chat"),
    ProviderSettings("mistral", "openai-compat",
                     base_url="https://api.mistral.ai/v1",
                     api_key_env="MISTRAL_API_KEY", supports_fim=True,
                     default_model="codestral-latest"),
    ProviderSettings("xai", "openai-compat",
                     base_url="https://api.x.ai/v1",
                     api_key_env="XAI_API_KEY", default_model="grok-2"),
    ProviderSettings("groq", "openai-compat",
                     base_url="https://api.groq.com/openai/v1",
                     api_key_env="GROQ_API_KEY",
                     default_model="llama-3.3-70b"),
    ProviderSettings("openrouter", "openai-compat",
                     base_url="https://openrouter.ai/api/v1",
                     api_key_env="OPENROUTER_API_KEY"),
    ProviderSettings("ollama", "openai-compat",
                     base_url="http://localhost:11434/v1",
                     default_model="qwen2.5-coder"),
    ProviderSettings("vllm", "openai-compat",
                     base_url="http://localhost:8000/v1"),
    ProviderSettings("lmstudio", "openai-compat",
                     base_url="http://localhost:1234/v1"),
    ProviderSettings("litellm", "openai-compat",
                     base_url="http://localhost:4000"),
    ProviderSettings("moonshot", "openai-compat",
                     base_url="https://api.moonshot.cn/v1",
                     api_key_env="MOONSHOT_API_KEY"),
    ProviderSettings("zai", "openai-compat",
                     base_url="https://open.bigmodel.cn/api/paas/v4",
                     api_key_env="ZAI_API_KEY"),
    ProviderSettings("alibailian", "openai-compat",
                     base_url="https://dashscope.aliyuncs.com"
                              "/compatible-mode/v1",
                     api_key_env="DASHSCOPE_API_KEY"),
    ProviderSettings("openai-compatible", "openai-compat"),
    ProviderSettings("own-provider", "openai-compat",
                     base_url="https://api.newpoc.com/v1",
                     api_key_env="SENWEAVER_API_KEY"),
]}


def get_provider(name: str) -> Optional[ProviderSettings]:
    return PROVIDERS.get(name)


def resolve_model(provider: str,
                  model: Optional[str] = None) -> Tuple[str, str]:
    """(provider, model) with registry defaults applied."""
    p = PROVIDERS.get(provider) or PROVIDERS["local"]
    return p.name, model or p.default_model
