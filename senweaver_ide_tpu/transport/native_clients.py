"""Native provider clients: anthropic-messages and gemini-generateContent.

The reference speaks two non-openai wire formats natively — the Anthropic
SDK (``sendAnthropicChat``, sendLLMMessage.impl.ts:529) and Google GenAI
(``sendGeminiChat``, :786); every other provider consolidates onto the
openai-compatible client. r1 listed both styles in the provider registry
but shipped no client for them (dead entries); these stdlib-urllib
implementations make the entries live. Both are PolicyClient-shaped, so
the agent loop / distillation rollouts can drive them interchangeably
with the local TPU engine.
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

from ..agents.llm import (ChatMessage, ContextLengthError, LLMResponse,
                          LLMUsage, RateLimitError)
from ..context.rate_limiter import TPMRateLimiter, tpm_rate_limiter
from .http_client import OpenAICompatClient, TransportUnavailable
from .providers import ProviderSettings, get_provider

ANTHROPIC_VERSION = "2023-06-01"


def _post_json(url: str, body: dict, headers: Dict[str, str],
               timeout_s: float, provider: str,
               limiter: TPMRateLimiter) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **headers},
        method="POST")
    limiter.record_request_start(provider)
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            payload = json.loads(resp.read())
    except urllib.error.HTTPError as e:
        detail = ""
        try:
            detail = e.read().decode(errors="replace")[:500]
        except Exception:
            pass
        if e.code == 429:
            retry_after = None
            ra = e.headers.get("retry-after") if e.headers else None
            if ra:
                try:
                    retry_after = float(ra)
                except ValueError:
                    pass
            limiter.record_rate_limit_error(provider, retry_after)
            raise RateLimitError(f"{provider}: 429 {detail}",
                                 retry_after_s=retry_after)
        low = detail.lower()
        if e.code in (400, 413) and ("context" in low or "token" in low
                                     or "too long" in low):
            raise ContextLengthError(f"{provider}: {detail}")
        raise RuntimeError(f"{provider}: HTTP {e.code} {detail}")
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        raise TransportUnavailable(f"{provider} unreachable at {url}: {e}")
    limiter.record_success(provider)
    return payload


def _split_system(messages: List[ChatMessage]
                  ) -> Tuple[str, List[ChatMessage]]:
    system = "\n\n".join(m.content for m in messages if m.role == "system")
    return system, [m for m in messages if m.role != "system"]


class AnthropicMessagesClient:
    """PolicyClient over POST /v1/messages (the anthropic-native style the
    reference reaches through @anthropic-ai/sdk)."""

    def __init__(self, *, model: Optional[str] = None,
                 base_url: Optional[str] = None,
                 api_key: Optional[str] = None,
                 timeout_s: float = 120.0,
                 max_tokens_default: int = 4096,
                 rate_limiter: Optional[TPMRateLimiter] = None):
        settings = get_provider("anthropic")
        self.model = model or settings.default_model
        self.base_url = (base_url or settings.base_url).rstrip("/")
        self.api_key = api_key or os.environ.get(settings.api_key_env, "")
        self.timeout_s = timeout_s
        self.max_tokens_default = max_tokens_default
        self.limiter = rate_limiter or tpm_rate_limiter

    def chat(self, messages: List[ChatMessage], *,
             temperature: Optional[float] = None,
             max_tokens: Optional[int] = None,
             on_text=None) -> LLMResponse:
        system, rest = _split_system(messages)
        body = {
            "model": self.model,
            # max_tokens is REQUIRED by the messages API.
            "max_tokens": max_tokens or self.max_tokens_default,
            "messages": [
                {"role": "assistant" if m.role == "assistant" else "user",
                 "content": m.content if m.role != "tool"
                 else f"[{m.tool_name or 'tool'} result]\n{m.content}"}
                for m in rest],
        }
        if system:
            body["system"] = system
        if temperature is not None:
            body["temperature"] = temperature
        payload = _post_json(
            f"{self.base_url}/v1/messages", body,
            {"x-api-key": self.api_key,
             "anthropic-version": ANTHROPIC_VERSION},
            self.timeout_s, "anthropic", self.limiter)
        text = "".join(block.get("text", "")
                       for block in payload.get("content", [])
                       if block.get("type") == "text")
        usage = payload.get("usage") or {}
        resp = LLMResponse(
            text=text,
            usage=LLMUsage(input_tokens=int(usage.get("input_tokens", 0)),
                           output_tokens=int(usage.get("output_tokens", 0))),
            model=payload.get("model", self.model))
        if on_text is not None and resp.text:
            on_text(resp.text)      # end-flush: non-streaming transport
        return resp


class GeminiClient:
    """PolicyClient over POST /v1beta/models/{model}:generateContent (the
    gemini-native style of sendGeminiChat)."""

    def __init__(self, *, model: Optional[str] = None,
                 base_url: Optional[str] = None,
                 api_key: Optional[str] = None,
                 timeout_s: float = 120.0,
                 rate_limiter: Optional[TPMRateLimiter] = None):
        settings = get_provider("gemini")
        self.model = model or settings.default_model
        self.base_url = (base_url or settings.base_url).rstrip("/")
        self.api_key = api_key or os.environ.get(settings.api_key_env, "")
        self.timeout_s = timeout_s
        self.limiter = rate_limiter or tpm_rate_limiter

    def chat(self, messages: List[ChatMessage], *,
             temperature: Optional[float] = None,
             max_tokens: Optional[int] = None,
             on_text=None) -> LLMResponse:
        system, rest = _split_system(messages)
        contents = []
        for m in rest:
            role = "model" if m.role == "assistant" else "user"
            text = (m.content if m.role != "tool"
                    else f"[{m.tool_name or 'tool'} result]\n{m.content}")
            contents.append({"role": role, "parts": [{"text": text}]})
        body: dict = {"contents": contents}
        if system:
            body["systemInstruction"] = {"parts": [{"text": system}]}
        gen_cfg = {}
        if temperature is not None:
            gen_cfg["temperature"] = temperature
        if max_tokens is not None:
            gen_cfg["maxOutputTokens"] = max_tokens
        if gen_cfg:
            body["generationConfig"] = gen_cfg
        payload = _post_json(
            f"{self.base_url}/v1beta/models/{self.model}:generateContent",
            body, {"x-goog-api-key": self.api_key}, self.timeout_s,
            "gemini", self.limiter)
        cands = payload.get("candidates") or [{}]
        parts = ((cands[0].get("content") or {}).get("parts")) or []
        text = "".join(p.get("text", "") for p in parts)
        meta = payload.get("usageMetadata") or {}
        resp = LLMResponse(
            text=text,
            usage=LLMUsage(
                input_tokens=int(meta.get("promptTokenCount", 0)),
                output_tokens=int(meta.get("candidatesTokenCount", 0))),
            model=payload.get("modelVersion", self.model))
        if on_text is not None and resp.text:
            on_text(resp.text)      # end-flush: non-streaming transport
        return resp


def make_client(provider: str, **kwargs):
    """Instantiate the right transport for a registry provider — the
    dispatch table of sendLLMMessageToProviderImplementation
    (sendLLMMessage.impl.ts:927), minus the local engine (built via
    rollout.EnginePolicyClient)."""
    settings = get_provider(provider) or ProviderSettings(
        provider, "openai-compat")
    style = settings.endpoint_style
    if style == "anthropic":
        return AnthropicMessagesClient(**kwargs)
    if style == "gemini":
        return GeminiClient(**kwargs)
    if style == "openai-compat":
        return OpenAICompatClient(provider, **kwargs)
    raise ValueError(
        f"provider {provider!r} has endpoint style {style!r}; use the "
        f"rollout engine for the local policy")
