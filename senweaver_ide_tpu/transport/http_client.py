"""OpenAI-compatible HTTP chat client (PolicyClient-shaped).

The remote half of the transport layer: one client covers every
openai-compatible provider in the registry, exactly as the reference
consolidates 18 of its 20 providers onto `_sendOpenAICompatibleChat`
(sendLLMMessage.impl.ts:338 + newOpenAICompatibleSDK :94-181). Built on
urllib (no SDK deps); rate limiting is the reactive TPM limiter
(context/rate_limiter.py) and errors map onto the agent loop's retry
classes (RateLimitError / ContextLengthError).

Hermetic environments have zero egress: calls fail fast with a clear
TransportUnavailable unless the environment provides connectivity — the
registry + client still define the full API surface.
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request
from typing import List, Optional

from ..agents.llm import (ChatMessage, ContextLengthError, LLMResponse,
                          LLMUsage, RateLimitError)
from ..context.rate_limiter import TPMRateLimiter, tpm_rate_limiter
from .providers import ProviderSettings, get_provider


class TransportUnavailable(RuntimeError):
    pass


class OpenAICompatClient:
    """PolicyClient over an openai-compatible /chat/completions endpoint."""

    def __init__(self, provider: str, *, model: Optional[str] = None,
                 base_url: Optional[str] = None,
                 api_key: Optional[str] = None,
                 timeout_s: float = 120.0,
                 rate_limiter: Optional[TPMRateLimiter] = None):
        settings = get_provider(provider) or ProviderSettings(
            provider, "openai-compat")
        self.settings = settings
        self.provider = settings.name
        self.model = model or settings.default_model
        self.base_url = (base_url or settings.base_url).rstrip("/")
        if not self.base_url:
            raise ValueError(f"provider {provider} needs a base_url")
        self.api_key = api_key or (os.environ.get(settings.api_key_env)
                                   if settings.api_key_env else None)
        self.timeout_s = timeout_s
        self.limiter = rate_limiter or tpm_rate_limiter

    def chat(self, messages: List[ChatMessage], *,
             temperature: Optional[float] = None,
             max_tokens: Optional[int] = None,
             on_text=None) -> LLMResponse:
        body = {
            "model": self.model,
            "messages": [{"role": m.role if m.role != "tool" else "user",
                          "content": m.content} for m in messages],
        }
        if temperature is not None:
            body["temperature"] = temperature
        if max_tokens is not None:
            body["max_tokens"] = max_tokens
        payload = self._post("/chat/completions", body)
        choice = (payload.get("choices") or [{}])[0]
        usage = payload.get("usage") or {}
        resp = LLMResponse(
            text=(choice.get("message") or {}).get("content") or "",
            usage=LLMUsage(
                input_tokens=int(usage.get("prompt_tokens", 0)),
                output_tokens=int(usage.get("completion_tokens", 0))),
            model=payload.get("model", self.model))
        if on_text is not None and resp.text:
            on_text(resp.text)      # end-flush: no HTTP streaming here
        return resp

    def fim_complete(self, prefix: str, suffix: str = "", *,
                     max_tokens: int = 64,
                     temperature: float = 0.0) -> str:
        """Remote fill-in-the-middle completion.

        The reference exposes FIM for exactly two remote providers
        (sendLLMMessage.impl.ts): mistral via its dedicated
        ``/fim/completions`` endpoint and deepseek via the beta
        prompt+suffix ``/completions`` shape (:174). Everything else
        raises — callers fall back to pseudo-FIM chat or the local policy
        (editor/autocomplete.py).
        """
        if not self.settings.supports_fim:
            # Unregistered providers get the __init__ fallback settings
            # (supports_fim=False), so they raise here too — no silent
            # POST to an endpoint that likely doesn't exist.
            raise TransportUnavailable(
                f"provider {self.provider} does not support remote FIM")
        body = {"model": self.model, "prompt": prefix, "suffix": suffix,
                "max_tokens": max_tokens, "temperature": temperature}
        if self.provider == "mistral":
            payload = self._post("/fim/completions", body)
        elif self.provider == "deepseek" and self.base_url.endswith("/v1"):
            # deepseek serves prompt+suffix completions only under the
            # /beta base, not /v1 (the beta API of
            # sendLLMMessage.impl.ts:174)
            payload = self._post("/completions", body,
                                 base=self.base_url[:-len("v1")] + "beta")
        else:
            payload = self._post("/completions", body)
        choice = (payload.get("choices") or [{}])[0]
        # mistral replies chat-shaped, deepseek completion-shaped
        return (choice.get("text")
                or (choice.get("message") or {}).get("content") or "")

    def _post(self, path: str, body: dict,
              base: Optional[str] = None) -> dict:
        """POST with rate limiting + the reference's error taxonomy."""
        wait = self.limiter.get_wait_time(self.provider)
        if wait > 0:
            import time
            time.sleep(wait)
        req = urllib.request.Request(
            f"{base or self.base_url}{path}",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json",
                     **({"Authorization": f"Bearer {self.api_key}"}
                        if self.api_key else {})},
            method="POST")
        self.limiter.record_request_start(self.provider)
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as resp:
                payload = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            detail = ""
            try:
                detail = e.read().decode(errors="replace")[:500]
            except Exception:
                pass
            if e.code == 429:
                retry_after = None
                ra = e.headers.get("retry-after") if e.headers else None
                if ra:
                    try:
                        retry_after = float(ra)
                    except ValueError:
                        pass
                self.limiter.record_rate_limit_error(self.provider,
                                                     retry_after)
                raise RateLimitError(f"{self.provider}: 429 {detail}",
                                     retry_after_s=retry_after)
            if e.code == 400 and ("context" in detail.lower()
                                  or "token" in detail.lower()):
                raise ContextLengthError(f"{self.provider}: {detail}")
            raise RuntimeError(f"{self.provider}: HTTP {e.code} {detail}")
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise TransportUnavailable(
                f"{self.provider} unreachable at {self.base_url}: {e}")
        self.limiter.record_success(self.provider)
        return payload
