"""LLM transport: provider registry + openai-compatible HTTP client.

The TPU-build analogue of L1/L2 (SURVEY.md §2.3): the local TPU sampler
is the primary provider; the registry keeps the reference's 20-provider
surface for distillation/eval rollouts, consolidated onto one
openai-compatible client the way sendLLMMessage.impl.ts consolidates 18
providers onto _sendOpenAICompatibleChat.
"""

from .http_client import OpenAICompatClient, TransportUnavailable
from .native_clients import (AnthropicMessagesClient, GeminiClient,
                             make_client)
from .providers import (PROVIDERS, ProviderSettings, get_provider,
                        resolve_model)

__all__ = ["OpenAICompatClient", "TransportUnavailable",
           "AnthropicMessagesClient", "GeminiClient", "make_client",
           "PROVIDERS", "ProviderSettings", "get_provider", "resolve_model"]
