"""Decoder-only transformer — functional JAX, layer-stacked, scan-compiled.

TPU-first design decisions (vs a PyTorch-style module port):
- Params are a plain pytree of layer-STACKED arrays (leading axis L) and the
  forward pass is one ``lax.scan`` over layers: the layer body is traced once,
  giving O(1) compile time in depth and a natural pipeline-parallel axis.
- All matmuls are einsums in bf16 with fp32 softmax/norm accumulation — the
  shapes XLA tiles directly onto the MXU.
- KV cache is a pre-allocated (L, B, Smax, Hkv, Dh) pair updated with
  ``dynamic_update_slice`` — static shapes, no reallocation during decode.
- Sharding lives entirely in ``parallel/sharding.py`` PartitionSpecs; the
  model code is sharding-agnostic (GSPMD propagates).

Architectures covered: Qwen2.5-Coder (GQA + QKV bias, tied embeddings at
0.5B/1.5B) and DeepSeek-Coder/LLaMA (MHA, untied) — see models/config.py.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.attention import attention
from ..ops.norms import rms_norm
from ..ops.rotary import apply_rope, rope_cos_sin
from .config import ModelConfig

Params = Dict[str, Any]


class KVCache(NamedTuple):
    k: jax.Array  # (L, B, Smax, Hkv, Dh) — bf16, or int8 when quantized
    v: jax.Array  # (L, B, Smax, Hkv, Dh)
    # () int32 — tokens currently in cache; or (B,) int32 for per-slot
    # lengths (continuous batching, rollout/engine.py).
    length: jax.Array
    # Per-(layer, slot, position, head) dequantization scales, present
    # only for the int8 cache (absmax/127 over head_dim). Halving cache
    # bytes is a CAPACITY lever: a 16 GB chip serving deepseek-6.7b
    # (13.4 GB bf16 weights) fits 2× the decode batch.
    k_scale: Optional[jax.Array] = None  # (L, B, Smax, Hkv) f32
    v_scale: Optional[jax.Array] = None

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def init_kv_cache(config: ModelConfig, batch: int, max_len: int,
                  dtype=None, *, quantized: Optional[bool] = None) -> KVCache:
    quantized = config.kv_quant if quantized is None else quantized
    shape = (config.num_layers, batch, max_len, config.num_kv_heads,
             config.head_dim)
    if quantized:
        sshape = shape[:-1]
        return KVCache(k=jnp.zeros(shape, jnp.int8),
                       v=jnp.zeros(shape, jnp.int8),
                       length=jnp.zeros((), jnp.int32),
                       k_scale=jnp.zeros(sshape, jnp.float32),
                       v_scale=jnp.zeros(sshape, jnp.float32))
    dtype = dtype or config.dtype
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   length=jnp.zeros((), jnp.int32))


def _quantize_kv(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(B, S, H, D) → int8 values + (B, S, H) f32 absmax/127 scales."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def _dequantize_kv(q: jnp.ndarray, scale: jnp.ndarray,
                   dtype) -> jnp.ndarray:
    """int8 (B, S, H, D) + (B, S, H) scales → ``dtype`` values."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def init_params(config: ModelConfig, key: jax.Array) -> Params:
    """Random init (normal / sqrt(fan_in)); layer params stacked on axis 0."""
    c = config
    k_embed, k_layers, k_head = jax.random.split(key, 3)

    def dense(key, shape, fan_in):
        # Generate directly in the target dtype: the fp32-then-cast
        # pattern materializes an fp32 transient of every stacked tensor
        # (5.8 GB for deepseek-6.7b's w_gate alone), OOMing a 16 GB chip
        # whose bf16 weights otherwise fit.
        scale = jnp.asarray(1.0 / float(fan_in) ** 0.5, c.dtype)
        return jax.random.normal(key, shape, c.dtype) * scale

    L, D, F = c.num_layers, c.hidden_size, c.intermediate_size
    ks = jax.random.split(k_layers, 8)
    layers = {
        "attn_norm": jnp.ones((L, D), c.dtype),
        "wq": dense(ks[0], (L, D, c.q_dim), D),
        "wk": dense(ks[1], (L, D, c.kv_dim), D),
        "wv": dense(ks[2], (L, D, c.kv_dim), D),
        "wo": dense(ks[3], (L, c.q_dim, D), c.q_dim),
        "mlp_norm": jnp.ones((L, D), c.dtype),
    }
    if c.num_experts > 0:
        E = c.num_experts
        layers["router"] = dense(ks[7], (L, D, E), D)
        layers["w_gate"] = dense(ks[4], (L, E, D, F), D)
        layers["w_up"] = dense(ks[5], (L, E, D, F), D)
        layers["w_down"] = dense(ks[6], (L, E, F, D), F)
    else:
        layers["w_gate"] = dense(ks[4], (L, D, F), D)
        layers["w_up"] = dense(ks[5], (L, D, F), D)
        layers["w_down"] = dense(ks[6], (L, F, D), F)
    if c.qkv_bias:
        layers["bq"] = jnp.zeros((L, c.q_dim), c.dtype)
        layers["bk"] = jnp.zeros((L, c.kv_dim), c.dtype)
        layers["bv"] = jnp.zeros((L, c.kv_dim), c.dtype)

    params: Params = {
        "embed": (jax.random.normal(k_embed, (c.vocab_size, D), c.dtype)
                  * jnp.asarray(0.02, c.dtype)),
        "layers": layers,
        "final_norm": jnp.ones((D,), c.dtype),
    }
    if not c.tie_word_embeddings:
        params["lm_head"] = dense(k_head, (D, c.vocab_size), D)
    return params


def _qkv(c: ModelConfig, lp: Dict[str, jax.Array], h: jax.Array,
         cos: jax.Array, sin: jax.Array):
    """Project + rotate. h: (B, S, D) → q (B,S,Hq,Dh), k/v (B,S,Hkv,Dh)."""
    b, s, _ = h.shape
    q = jnp.einsum("bsd,de->bse", h, lp["wq"])
    k = jnp.einsum("bsd,de->bse", h, lp["wk"])
    v = jnp.einsum("bsd,de->bse", h, lp["wv"])
    if c.qkv_bias:
        q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    q = apply_rope(q.reshape(b, s, c.num_heads, c.head_dim), cos, sin)
    k = apply_rope(k.reshape(b, s, c.num_kv_heads, c.head_dim), cos, sin)
    v = v.reshape(b, s, c.num_kv_heads, c.head_dim)
    return q, k, v


def _self_attention(c: ModelConfig, q, k, v, kv_mask, mesh):
    """No-cache attention dispatch per ``c.attn_impl`` (training/scoring
    path). q (B,S,Hq,Dh), k/v (B,S,Hkv,Dh) → (B,S,Hq,Dh)."""
    if c.attn_impl == "einsum":
        return attention(q, k, v, q_offset=0, kv_mask=kv_mask, causal=True,
                         window=c.sliding_window)
    if c.sliding_window is not None:
        raise NotImplementedError(
            f"sliding_window is implemented for attn_impl='einsum' only "
            f"(got {c.attn_impl!r}); the flash/ring kernels would silently "
            f"attend outside the window")
    if c.attn_impl == "flash":
        from ..ops.flash_attention import flash_attention
        return flash_attention(q, k, v, q_offset=0, kv_mask=kv_mask,
                               causal=True)
    if c.attn_impl in ("ring", "ulysses"):
        from ..parallel.ring_attention import (make_ring_attention,
                                               make_ulysses_attention)
        if mesh is None or "sp" not in mesh.axis_names:
            raise ValueError(
                f"attn_impl={c.attn_impl!r} needs forward(mesh=...) with an "
                f"'sp' axis; got {mesh}")
        if c.attn_impl == "ulysses":
            if kv_mask is not None:
                raise NotImplementedError(
                    "ulysses attention does not take a kv mask; pre-mask "
                    "k/v or use attn_impl='ring'")
            return make_ulysses_attention(mesh)(q, k, v)
        if kv_mask is not None:
            return make_ring_attention(mesh, with_mask=True)(q, k, v, kv_mask)
        return make_ring_attention(mesh)(q, k, v)
    raise ValueError(f"unknown attn_impl {c.attn_impl!r}; expected "
                     f"einsum|flash|ring|ulysses")


def _cache_attention(c: ModelConfig, q, k_full, v_full, length, kv_mask,
                     flash_decode_ok: bool):
    """Cache-path attention dispatch: einsum over the whole cache, or the
    streamed flash-decode kernel when the step shape allows it."""
    if flash_decode_ok:
        from ..ops.flash_decode import flash_decode
        smax = k_full.shape[1]
        blk = 128 if smax % 128 == 0 else smax
        # post-write valid count: the current token's k/v is in the cache
        return flash_decode(q, k_full, v_full, length + 1, block_kv=blk)
    return attention(q, k_full, v_full, q_offset=length, kv_mask=kv_mask,
                     causal=True, window=c.sliding_window)


def _layer(c: ModelConfig, lp: Dict[str, jax.Array], x: jax.Array,
           cos: jax.Array, sin: jax.Array,
           cache_kv: Optional[Tuple[jax.Array, jax.Array, jax.Array]],
           kv_mask, mesh=None, flash_decode_ok: bool = False):
    """One transformer block. x: (B, S, D).

    Without cache_kv: full self-attention over the block's own k/v, via the
    ``c.attn_impl`` kernel (einsum / flash / ring / ulysses — the latter two
    shard the sequence axis over the mesh's 'sp' axis).
    With cache_kv=(k_cache, v_cache, length): writes new k/v at ``length``,
    attends over the whole cache. Returns (x', (k_cache', v_cache'), aux)
    — in the no-cache case the returned pair is the block's own (k, v);
    aux is the MoE load-balancing loss (0 for dense layers).
    """
    b, s, _ = x.shape
    h = rms_norm(x, lp["attn_norm"], c.rms_norm_eps)
    q, k, v = _qkv(c, lp, h, cos, sin)

    if cache_kv is not None and len(cache_kv) == 5:
        # int8 cache: quantize the block's new k/v, scatter values AND
        # scales, attend over the dequantized cache (transient in compute
        # dtype; the HBM-resident cache stays int8).
        k_cache, v_cache, length, k_scale, v_scale = cache_kv
        kq, ks = _quantize_kv(k)
        vq, vs = _quantize_kv(v)
        if length.ndim == 0:
            k_cache = jax.lax.dynamic_update_slice(k_cache, kq,
                                                   (0, length, 0, 0))
            v_cache = jax.lax.dynamic_update_slice(v_cache, vq,
                                                   (0, length, 0, 0))
            k_scale = jax.lax.dynamic_update_slice(k_scale, ks,
                                                   (0, length, 0))
            v_scale = jax.lax.dynamic_update_slice(v_scale, vs,
                                                   (0, length, 0))
        else:
            slot = jnp.arange(b)[:, None]                      # (B, 1)
            pos = length[:, None] + jnp.arange(s)[None, :]     # (B, s)
            k_cache = k_cache.at[slot, pos].set(kq, mode="drop")
            v_cache = v_cache.at[slot, pos].set(vq, mode="drop")
            k_scale = k_scale.at[slot, pos].set(ks, mode="drop")
            v_scale = v_scale.at[slot, pos].set(vs, mode="drop")
        out = _cache_attention(c, q,
                               _dequantize_kv(k_cache, k_scale, x.dtype),
                               _dequantize_kv(v_cache, v_scale, x.dtype),
                               length, kv_mask, flash_decode_ok)
        kv_out = (k_cache, v_cache, k_scale, v_scale)
    elif cache_kv is not None:
        k_cache, v_cache, length = cache_kv
        if length.ndim == 0:
            k_cache = jax.lax.dynamic_update_slice(
                k_cache, k.astype(k_cache.dtype), (0, length, 0, 0))
            v_cache = jax.lax.dynamic_update_slice(
                v_cache, v.astype(v_cache.dtype), (0, length, 0, 0))
        else:
            # Per-slot write offsets (continuous batching): scatter each
            # slot's s new positions at its own length.
            slot = jnp.arange(b)[:, None]                      # (B, 1)
            pos = length[:, None] + jnp.arange(s)[None, :]     # (B, s)
            k_cache = k_cache.at[slot, pos].set(k.astype(k_cache.dtype),
                                                mode="drop")
            v_cache = v_cache.at[slot, pos].set(v.astype(v_cache.dtype),
                                                mode="drop")
        out = _cache_attention(c, q, k_cache, v_cache, length, kv_mask,
                               flash_decode_ok)
        kv_out = (k_cache, v_cache)
    else:
        out = _self_attention(c, q, k, v, kv_mask, mesh)
        kv_out = (k, v)

    x = x + jnp.einsum("bse,ed->bsd", out.reshape(b, s, c.q_dim), lp["wo"])

    h = rms_norm(x, lp["mlp_norm"], c.rms_norm_eps)
    if c.num_experts > 0:
        from ..parallel.expert import MoEConfig, moe_ffn
        moe_cfg = MoEConfig(hidden_size=c.hidden_size,
                            intermediate_size=c.intermediate_size,
                            num_experts=c.num_experts,
                            top_k=c.num_experts_per_tok,
                            capacity_factor=c.expert_capacity_factor,
                            dtype=c.dtype)
        moe_params = {"router": lp["router"], "w_gate": lp["w_gate"],
                      "w_up": lp["w_up"], "w_down": lp["w_down"]}
        ffn_out, aux = moe_ffn(moe_params, moe_cfg, h)
        return x + ffn_out, kv_out, aux
    gate = jnp.einsum("bsd,df->bsf", h, lp["w_gate"])
    up = jnp.einsum("bsd,df->bsf", h, lp["w_up"])
    act = jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype) * up
    return (x + jnp.einsum("bsf,fd->bsd", act, lp["w_down"]), kv_out,
            jnp.zeros((), jnp.float32))


def forward(
    params: Params,
    config: ModelConfig,
    tokens: jax.Array,                 # (B, S) int32
    *,
    cache: Optional[KVCache] = None,
    positions: Optional[jax.Array] = None,   # (B, S) absolute positions
    attn_mask: Optional[jax.Array] = None,   # (B, S_kv) True = valid
    with_aux: bool = False,
    mesh=None,                               # required for ring/ulysses attn
):
    """Run the model. Without cache: full causal self-attention over ``tokens``.
    With cache: ``tokens`` are appended at ``cache.length`` and attend to
    everything up to that point (prefill and decode use the same path).

    ``mesh`` (jax.sharding.Mesh) is only consulted when
    ``config.attn_impl`` is 'ring'/'ulysses' — the sequence axis then
    shards over its 'sp' axis inside shard_map.

    Returns (logits (B, S, V) fp32, updated cache or None); with
    ``with_aux=True`` also the summed MoE load-balancing loss (the router
    must see it in the objective or it is free to collapse).
    """
    c = config
    if c.matmul_precision is not None:
        with jax.default_matmul_precision(c.matmul_precision):
            out = _forward_impl(params, c, tokens, cache=cache,
                                positions=positions, attn_mask=attn_mask,
                                mesh=mesh)
    else:
        out = _forward_impl(params, c, tokens, cache=cache,
                            positions=positions, attn_mask=attn_mask,
                            mesh=mesh)
    logits, new_cache, aux = out
    if with_aux:
        return logits, new_cache, aux
    return logits, new_cache


def _forward_impl(params, c, tokens, *, cache, positions, attn_mask,
                  mesh=None):
    b, s = tokens.shape
    x = params["embed"][tokens]  # gather; sharded vocab → XLA collective

    if positions is None:
        base = cache.length if cache is not None else jnp.zeros((), jnp.int32)
        if base.ndim == 1:
            base = base[:, None]                       # per-slot lengths
        positions = base + jnp.arange(s, dtype=jnp.int32)[None, :]
        positions = jnp.broadcast_to(positions, (b, s))
    cos, sin = rope_cos_sin(positions, c.head_dim, c.rope_theta)

    if cache is None:
        def body(carry, lp):
            x, aux = carry
            x, _, layer_aux = _layer(c, lp, x, cos, sin, None, attn_mask,
                                     mesh=mesh)
            return (x, aux + layer_aux), None

        (x, aux_total), _ = jax.lax.scan(
            body, (x, jnp.zeros((), jnp.float32)), params["layers"],
            unroll=c.scan_unroll)
        new_cache = None
    else:
        max_len = cache.k.shape[2]
        # kv validity: only slots < length + s are real.
        kv_pos = jnp.arange(max_len)[None, :]
        length = cache.length
        bound = (length[:, None] if length.ndim == 1 else length) + s
        valid = jnp.broadcast_to(kv_pos < bound, (b, max_len))
        if attn_mask is not None:
            valid = valid & attn_mask
        # Flash-decode applies only when the validity mask is exactly
        # "pos < length + 1" (single new token, no extra mask) and the
        # cache splits into proper KV blocks: either 128-aligned (the
        # streamed multi-block grid) or small enough that one whole-cache
        # block still fits VMEM comfortably. An unaligned LARGE cache
        # would degenerate to block_kv = max_len — no per-slot skipping
        # and a VMEM-busting block — so it falls back to einsum instead.
        tileable = (max_len % 128 == 0
                    or (max_len % 8 == 0 and max_len <= 512))
        # Sliding window changes the valid-kv lower bound; flash_decode
        # only models "pos < length + 1", so SWA configs stay on einsum.
        flash_ok = (c.decode_attn_impl == "flash" and s == 1
                    and attn_mask is None and tileable
                    and c.sliding_window is None)

        if cache.quantized:
            def body_q(carry, inputs):
                x, aux = carry
                lp, k_c, v_c, k_s, v_s = inputs
                x, kv_out, layer_aux = _layer(
                    c, lp, x, cos, sin,
                    (k_c, v_c, cache.length, k_s, v_s), valid,
                    flash_decode_ok=flash_ok)
                return (x, aux + layer_aux), kv_out

            (x, aux_total), (k_upd, v_upd, ks_upd, vs_upd) = jax.lax.scan(
                body_q, (x, jnp.zeros((), jnp.float32)),
                (params["layers"], cache.k, cache.v, cache.k_scale,
                 cache.v_scale), unroll=c.scan_unroll)
            new_cache = KVCache(k=k_upd, v=v_upd, length=cache.length + s,
                                k_scale=ks_upd, v_scale=vs_upd)
        else:
            def body(carry, inputs):
                x, aux = carry
                lp, k_cache, v_cache = inputs
                x, (k_cache, v_cache), layer_aux = _layer(
                    c, lp, x, cos, sin, (k_cache, v_cache, cache.length),
                    valid, flash_decode_ok=flash_ok)
                return (x, aux + layer_aux), (k_cache, v_cache)

            (x, aux_total), (k_upd, v_upd) = jax.lax.scan(
                body, (x, jnp.zeros((), jnp.float32)),
                (params["layers"], cache.k, cache.v), unroll=c.scan_unroll)
            new_cache = KVCache(k=k_upd, v=v_upd, length=cache.length + s)

    x = rms_norm(x, params["final_norm"], c.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:  # tied embeddings
        logits = jnp.einsum("bsd,vd->bsv", x, params["embed"])
    else:
        logits = jnp.einsum("bsd,dv->bsv", x, head)
    return logits.astype(jnp.float32), new_cache, aux_total


def count_params(params: Params) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
