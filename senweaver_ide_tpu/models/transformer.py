"""Decoder-only transformer — functional JAX, layer-stacked, scan-compiled.

TPU-first design decisions (vs a PyTorch-style module port):
- Params are a plain pytree of layer-STACKED arrays (leading axis L) and the
  forward pass is one ``lax.scan`` over layers: the layer body is traced once,
  giving O(1) compile time in depth and a natural pipeline-parallel axis.
- All matmuls are einsums in bf16 with fp32 softmax/norm accumulation — the
  shapes XLA tiles directly onto the MXU.
- KV cache is a pre-allocated (L, B, Smax, Hkv, Dh) pair updated with
  ``dynamic_update_slice`` — static shapes, no reallocation during decode.
- Sharding lives entirely in ``parallel/sharding.py`` PartitionSpecs; the
  model code is sharding-agnostic (GSPMD propagates).

Architectures covered: Qwen2.5-Coder (GQA + QKV bias, tied embeddings at
0.5B/1.5B) and DeepSeek-Coder/LLaMA (MHA, untied) — see models/config.py.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.attention import attention
from ..ops.norms import rms_norm
from ..ops.rotary import apply_rope, rope_cos_sin
from .config import ModelConfig

Params = Dict[str, Any]


class KVCache(NamedTuple):
    k: jax.Array  # (L, B, Smax, Hkv, Dh) — bf16, or int8 when quantized
    v: jax.Array  # (L, B, Smax, Hkv, Dh)
    # () int32 — tokens currently in cache; or (B,) int32 for per-slot
    # lengths (continuous batching, rollout/engine.py).
    length: jax.Array
    # Per-(layer, slot, position, head) dequantization scales, present
    # only for the int8 cache (absmax/127 over head_dim). Halving cache
    # bytes is a CAPACITY lever: a 16 GB chip serving deepseek-6.7b
    # (13.4 GB bf16 weights) fits 2× the decode batch.
    k_scale: Optional[jax.Array] = None  # (L, B, Smax, Hkv) f32
    v_scale: Optional[jax.Array] = None

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def ring_capacity(config: ModelConfig, max_len: int) -> int:
    """KV capacity actually allocated for ``max_len`` requested positions.

    Sliding-window configs keep only the trailing ``sliding_window``
    positions (ring buffer, written at pos % capacity) — THE memory
    benefit of SWA: a mistral-7b 32k-context decode holds 4096 cache
    slots, not 32768. Rounded up to a multiple of 8 for TPU lane
    tiling; when the window itself is flash-tileable the capacity
    equals it exactly, keeping the flash-decode path eligible."""
    if config.sliding_window is None:
        return max_len
    return min(max_len, -(-config.sliding_window // 8) * 8)


def _is_ring(c: ModelConfig, cap: int) -> bool:
    """Ring (modular-write) semantics apply only when the cache can hold
    the whole window: cap < window would overwrite keys still inside the
    window on every wrap (write-then-attend is only safe because the slot
    being overwritten, pos − cap, lies outside the window when
    cap ≥ window). Short SWA caches (cap < aligned window) therefore use
    ABSOLUTE positions — plain bounded cache with the positional window
    mask, never wrapping."""
    return (c.sliding_window is not None
            and cap >= -(-c.sliding_window // 8) * 8)


def init_kv_cache(config: ModelConfig, batch: int, max_len: int,
                  dtype=None, *, quantized: Optional[bool] = None) -> KVCache:
    quantized = config.kv_quant if quantized is None else quantized
    max_len = ring_capacity(config, max_len)
    shape = (config.num_layers, batch, max_len, config.num_kv_heads,
             config.head_dim)
    if quantized:
        sshape = shape[:-1]
        return KVCache(k=jnp.zeros(shape, jnp.int8),
                       v=jnp.zeros(shape, jnp.int8),
                       length=jnp.zeros((), jnp.int32),
                       k_scale=jnp.zeros(sshape, jnp.float32),
                       v_scale=jnp.zeros(sshape, jnp.float32))
    dtype = dtype or config.dtype
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   length=jnp.zeros((), jnp.int32))


def _quantize_kv(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(B, S, H, D) → int8 values + (B, S, H) f32 absmax/127 scales."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def _dequantize_kv(q: jnp.ndarray, scale: jnp.ndarray,
                   dtype) -> jnp.ndarray:
    """int8 (B, S, H, D) + (B, S, H) scales → ``dtype`` values."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def pool_qmax(dtype) -> float:
    """Clip magnitude of a quantized paged-KV payload dtype (the scale
    denominator: scale = absmax / qmax)."""
    if np.dtype(dtype) == np.int8:
        return 127.0
    return 448.0  # float8_e4m3fn


def quantize_pool_kv(x: jnp.ndarray, dtype) -> Tuple[jnp.ndarray,
                                                     jnp.ndarray]:
    """Per-vector absmax quantization over the trailing head_dim axis:
    ``(..., D)`` full-width → (payload in ``dtype``, ``(...)`` f32
    scales). Used both inside the fused step (quantize-at-write) and by
    :func:`rollout.paged_kv.install_blocks` (quantize-at-install), so a
    block written token-by-token and a block installed wholesale hold
    bit-identical payloads."""
    qmax = pool_qmax(dtype)
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(absmax, 1e-8) / qmax
    y = xf / scale[..., None]
    if np.dtype(dtype) == np.int8:
        q = jnp.clip(jnp.round(y), -qmax, qmax).astype(jnp.int8)
    else:
        q = jnp.clip(y, -qmax, qmax).astype(dtype)
    return q, scale


def dequantize_pool_kv(q: jnp.ndarray, scale: jnp.ndarray,
                       dtype) -> jnp.ndarray:
    """Inverse of :func:`quantize_pool_kv`: ``(..., D)`` payload +
    ``(...)`` scales → ``dtype`` values."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def init_params(config: ModelConfig, key: jax.Array) -> Params:
    """Random init (normal / sqrt(fan_in)); layer params stacked on axis 0."""
    c = config
    k_embed, k_layers, k_head = jax.random.split(key, 3)

    def dense(key, shape, fan_in):
        # Generate directly in the target dtype: the fp32-then-cast
        # pattern materializes an fp32 transient of every stacked tensor
        # (5.8 GB for deepseek-6.7b's w_gate alone), OOMing a 16 GB chip
        # whose bf16 weights otherwise fit.
        scale = jnp.asarray(1.0 / float(fan_in) ** 0.5, c.dtype)
        return jax.random.normal(key, shape, c.dtype) * scale

    L, D, F = c.num_layers, c.hidden_size, c.intermediate_size
    ks = jax.random.split(k_layers, 8)
    layers = {
        "attn_norm": jnp.ones((L, D), c.dtype),
        "wq": dense(ks[0], (L, D, c.q_dim), D),
        "wk": dense(ks[1], (L, D, c.kv_dim), D),
        "wv": dense(ks[2], (L, D, c.kv_dim), D),
        "wo": dense(ks[3], (L, c.q_dim, D), c.q_dim),
        "mlp_norm": jnp.ones((L, D), c.dtype),
    }
    if c.num_experts > 0:
        E = c.num_experts
        layers["router"] = dense(ks[7], (L, D, E), D)
        layers["w_gate"] = dense(ks[4], (L, E, D, F), D)
        layers["w_up"] = dense(ks[5], (L, E, D, F), D)
        layers["w_down"] = dense(ks[6], (L, E, F, D), F)
    else:
        layers["w_gate"] = dense(ks[4], (L, D, F), D)
        layers["w_up"] = dense(ks[5], (L, D, F), D)
        layers["w_down"] = dense(ks[6], (L, F, D), F)
    if c.qkv_bias:
        layers["bq"] = jnp.zeros((L, c.q_dim), c.dtype)
        layers["bk"] = jnp.zeros((L, c.kv_dim), c.dtype)
        layers["bv"] = jnp.zeros((L, c.kv_dim), c.dtype)
    if c.qk_norm:
        layers["q_norm"] = jnp.ones((L, c.head_dim), c.dtype)
        layers["k_norm"] = jnp.ones((L, c.head_dim), c.dtype)

    params: Params = {
        "embed": (jax.random.normal(k_embed, (c.vocab_size, D), c.dtype)
                  * jnp.asarray(0.02, c.dtype)),
        "layers": layers,
        "final_norm": jnp.ones((D,), c.dtype),
    }
    if not c.tie_word_embeddings:
        params["lm_head"] = dense(k_head, (D, c.vocab_size), D)
    return params


def _dense(h: jax.Array, lp: Dict[str, jax.Array], name: str,
           spec: str) -> jax.Array:
    """``einsum(spec, h, lp[name])`` with transparent weight-only int8.

    When the stored weight is int8 (see ``models.quantize``), the matmul
    upcasts it in-compute and applies the per-output-channel scale to the
    (much smaller) output. Decode is weight-HBM-bound (BENCH_NOTES.md
    roofline: 2116 tok/s ≈ the bf16 bandwidth ceiling), so halving the
    bytes each step streams is the one remaining 2×-class lever; the
    scale multiply is an elementwise epilogue XLA fuses into the dot."""
    w = lp[name]
    if w.dtype == jnp.int8:
        out = jnp.einsum(spec, h, w.astype(h.dtype))
        out = (out.astype(jnp.float32)
               * lp[name + "_scale"]).astype(h.dtype)
    else:
        out = jnp.einsum(spec, h, w)
    la = lp.get(name + "_lora_a")
    if la is not None:
        # Low-rank adapter (training/lora.py): y += (h @ A) @ B, with
        # the alpha/rank scaling baked into A at merge time. Factored
        # order keeps the FLOPs O(r·(in+out)) instead of materializing
        # the (in, out) delta; works over an int8 base (QLoRA-style).
        lb = lp[name + "_lora_b"]
        out = out + jnp.einsum("bsr,ro->bso",
                               jnp.einsum("bsi,ir->bsr", h, la), lb)
    return out


def _adapter_delta(h: jax.Array, adapters, adapter_ids, name: str):
    """Gathered multi-LoRA delta for a flat token batch: each row t
    applies ITS adapter's factors, ``B[ids[t]] @ (A[ids[t]] @ h[t])``.

    ``adapters`` is the pool's rank ladder (rollout/adapter_pool.py) —
    one bank dict per rung, each leaf ``(slots+1, d_in, r)`` /
    ``(slots+1, r, d_out)`` after the layer scan consumes the leading
    L axis — and ``adapter_ids`` the matching per-rung ``(T,)`` slot
    vectors. Slot 0 of every rung is the permanent null adapter
    (A = B = 0), so base-only rows contribute exact zeros and the sum
    over rungs needs no masking: a row is non-null in at most one
    rung. Returns None when no bank carries this target."""
    out = None
    for bank, ids in zip(adapters, adapter_ids):
        a = bank.get(name + "_lora_a")
        if a is None:
            continue
        b = bank[name + "_lora_b"]
        d = jnp.einsum("tsr,tro->tso",
                       jnp.einsum("tsi,tir->tsr", h, a[ids]), b[ids])
        out = d if out is None else out + d
    return out


def _with_adapter(out: jax.Array, h: jax.Array, adapters, adapter_ids,
                  name: str) -> jax.Array:
    if adapters is None:
        return out
    d = _adapter_delta(h, adapters, adapter_ids, name)
    return out if d is None else out + d


def _qkv(c: ModelConfig, lp: Dict[str, jax.Array], h: jax.Array,
         cos: jax.Array, sin: jax.Array, adapters=None, adapter_ids=None):
    """Project + rotate. h: (B, S, D) → q (B,S,Hq,Dh), k/v (B,S,Hkv,Dh)."""
    b, s, _ = h.shape
    q = _dense(h, lp, "wq", "bsd,de->bse")
    k = _dense(h, lp, "wk", "bsd,de->bse")
    v = _dense(h, lp, "wv", "bsd,de->bse")
    # Per-row adapter deltas land where the merged-LoRA ``_dense`` hook
    # would: after the base matmul, before bias/reshape/norm/rope.
    q = _with_adapter(q, h, adapters, adapter_ids, "wq")
    k = _with_adapter(k, h, adapters, adapter_ids, "wk")
    v = _with_adapter(v, h, adapters, adapter_ids, "wv")
    if c.qkv_bias:
        q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    q = q.reshape(b, s, c.num_heads, c.head_dim)
    k = k.reshape(b, s, c.num_kv_heads, c.head_dim)
    if c.qk_norm:
        # Qwen3: per-head RMSNorm over head_dim BEFORE RoPE
        q = rms_norm(q, lp["q_norm"], c.rms_norm_eps)
        k = rms_norm(k, lp["k_norm"], c.rms_norm_eps)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    v = v.reshape(b, s, c.num_kv_heads, c.head_dim)
    return q, k, v


def _self_attention(c: ModelConfig, q, k, v, kv_mask, mesh):
    """No-cache attention dispatch per ``c.attn_impl`` (training/scoring
    path). q (B,S,Hq,Dh), k/v (B,S,Hkv,Dh) → (B,S,Hq,Dh)."""
    if c.attn_impl == "einsum":
        return attention(q, k, v, q_offset=0, kv_mask=kv_mask, causal=True,
                         window=c.sliding_window)
    if c.attn_impl == "flash":
        from ..ops.flash_attention import flash_attention
        return flash_attention(q, k, v, q_offset=0, kv_mask=kv_mask,
                               causal=True, window=c.sliding_window)
    if c.sliding_window is not None:
        raise NotImplementedError(
            f"sliding_window is implemented for attn_impl='einsum'/'flash' "
            f"only (got {c.attn_impl!r}); the ring kernels would silently "
            f"attend outside the window")
    if c.attn_impl in ("ring", "ulysses"):
        from ..parallel.ring_attention import (make_ring_attention,
                                               make_ulysses_attention)
        if mesh is None or "sp" not in mesh.axis_names:
            raise ValueError(
                f"attn_impl={c.attn_impl!r} needs forward(mesh=...) with an "
                f"'sp' axis; got {mesh}")
        if c.attn_impl == "ulysses":
            if kv_mask is not None:
                raise NotImplementedError(
                    "ulysses attention does not take a kv mask; pre-mask "
                    "k/v or use attn_impl='ring'")
            return make_ulysses_attention(mesh)(q, k, v)
        if kv_mask is not None:
            return make_ring_attention(mesh, with_mask=True)(q, k, v, kv_mask)
        return make_ring_attention(mesh)(q, k, v)
    raise ValueError(f"unknown attn_impl {c.attn_impl!r}; expected "
                     f"einsum|flash|ring|ulysses")


def _cache_attention(c: ModelConfig, q, k_full, v_full, length, kv_mask,
                     flash_decode_ok: bool):
    """Cache-path attention dispatch: einsum over the whole cache, or the
    streamed flash-decode kernel when the step shape allows it.

    Ring caches (SWA): ``kv_mask`` arrives as the full per-query
    (B, Sq, cap) validity mask — fill, causality, and window are all
    baked in by ``_forward_impl`` in ring coordinates, so the positional
    causal/window mask here must be OFF (ring index != absolute
    position). Flash-decode stays valid on a ring whose capacity equals
    the window: live entries are exactly indices < min(length+1, cap)
    and online softmax is order-invariant."""
    if flash_decode_ok:
        from ..ops.flash_decode import flash_decode
        smax = k_full.shape[1]
        blk = 128 if smax % 128 == 0 else smax
        # post-write valid count: the current token's k/v is in the cache
        valid_count = jnp.minimum(length + 1, smax)
        return flash_decode(q, k_full, v_full, valid_count, block_kv=blk)
    if _is_ring(c, k_full.shape[1]):
        return attention(q, k_full, v_full, kv_mask=kv_mask, causal=False)
    return attention(q, k_full, v_full, q_offset=length, kv_mask=kv_mask,
                     causal=True, window=c.sliding_window)


def _layer(c: ModelConfig, lp: Dict[str, jax.Array], x: jax.Array,
           cos: jax.Array, sin: jax.Array,
           cache_kv: Optional[Tuple[jax.Array, jax.Array, jax.Array]],
           kv_mask, mesh=None, flash_decode_ok: bool = False):
    """One transformer block. x: (B, S, D).

    Without cache_kv: full self-attention over the block's own k/v, via the
    ``c.attn_impl`` kernel (einsum / flash / ring / ulysses — the latter two
    shard the sequence axis over the mesh's 'sp' axis).
    With cache_kv=(k_cache, v_cache, length): writes new k/v at ``length``,
    attends over the whole cache. Returns (x', (k_cache', v_cache'), aux)
    — in the no-cache case the returned pair is the block's own (k, v);
    aux is the MoE load-balancing loss (0 for dense layers).
    """
    b, s, _ = x.shape
    h = rms_norm(x, lp["attn_norm"], c.rms_norm_eps)
    q, k, v = _qkv(c, lp, h, cos, sin)

    if cache_kv is not None and len(cache_kv) == 5:
        # int8 cache: quantize the block's new k/v, scatter values AND
        # scales, attend over the dequantized cache (transient in compute
        # dtype; the HBM-resident cache stays int8).
        k_cache, v_cache, length, k_scale, v_scale = cache_kv
        kq, ks = _quantize_kv(k)
        vq, vs = _quantize_kv(v)
        cap = k_cache.shape[1]
        ring = _is_ring(c, cap)
        out = None
        if ring and s > 1:
            # Attend BEFORE writing (see _forward_impl's ring notes): a
            # wrapping chunk's writes would destroy keys still inside
            # earlier queries' windows. kv axis = [pre-write cache ‖ chunk]
            # — unless the mask is chunk-width (fresh cache, nothing old
            # to read): then skip the concat and its masked-out FLOPs.
            if kv_mask.shape[-1] == s:
                out = attention(q, k, v, kv_mask=kv_mask, causal=False)
            else:
                k_all = jnp.concatenate(
                    [_dequantize_kv(k_cache, k_scale, x.dtype), k], axis=1)
                v_all = jnp.concatenate(
                    [_dequantize_kv(v_cache, v_scale, x.dtype), v], axis=1)
                out = attention(q, k_all, v_all, kv_mask=kv_mask,
                                causal=False)
        if length.ndim == 0 and not ring:
            k_cache = jax.lax.dynamic_update_slice(k_cache, kq,
                                                   (0, length, 0, 0))
            v_cache = jax.lax.dynamic_update_slice(v_cache, vq,
                                                   (0, length, 0, 0))
            k_scale = jax.lax.dynamic_update_slice(k_scale, ks,
                                                   (0, length, 0))
            v_scale = jax.lax.dynamic_update_slice(v_scale, vs,
                                                   (0, length, 0))
        elif length.ndim == 0:
            idx = (length + jnp.arange(s)) % cap               # ring write
            k_cache = k_cache.at[:, idx].set(kq)
            v_cache = v_cache.at[:, idx].set(vq)
            k_scale = k_scale.at[:, idx].set(ks)
            v_scale = v_scale.at[:, idx].set(vs)
        else:
            slot = jnp.arange(b)[:, None]                      # (B, 1)
            pos = length[:, None] + jnp.arange(s)[None, :]     # (B, s)
            if ring:
                pos = pos % cap
            k_cache = k_cache.at[slot, pos].set(kq, mode="drop")
            v_cache = v_cache.at[slot, pos].set(vq, mode="drop")
            k_scale = k_scale.at[slot, pos].set(ks, mode="drop")
            v_scale = v_scale.at[slot, pos].set(vs, mode="drop")
        if out is None:
            out = _cache_attention(c, q,
                                   _dequantize_kv(k_cache, k_scale, x.dtype),
                                   _dequantize_kv(v_cache, v_scale, x.dtype),
                                   length, kv_mask, flash_decode_ok)
        kv_out = (k_cache, v_cache, k_scale, v_scale)
    elif cache_kv is not None:
        k_cache, v_cache, length = cache_kv
        cap = k_cache.shape[1]
        ring = _is_ring(c, cap)
        out = None
        if ring and s > 1:
            # Attend BEFORE writing — see the quantized branch above.
            if kv_mask.shape[-1] == s:
                out = attention(q, k, v, kv_mask=kv_mask, causal=False)
            else:
                k_all = jnp.concatenate([k_cache.astype(x.dtype), k], axis=1)
                v_all = jnp.concatenate([v_cache.astype(x.dtype), v], axis=1)
                out = attention(q, k_all, v_all, kv_mask=kv_mask,
                                causal=False)
        if length.ndim == 0 and not ring:
            k_cache = jax.lax.dynamic_update_slice(
                k_cache, k.astype(k_cache.dtype), (0, length, 0, 0))
            v_cache = jax.lax.dynamic_update_slice(
                v_cache, v.astype(v_cache.dtype), (0, length, 0, 0))
        elif length.ndim == 0:
            idx = (length + jnp.arange(s)) % cap               # ring write
            k_cache = k_cache.at[:, idx].set(k.astype(k_cache.dtype))
            v_cache = v_cache.at[:, idx].set(v.astype(v_cache.dtype))
        else:
            # Per-slot write offsets (continuous batching): scatter each
            # slot's s new positions at its own length.
            slot = jnp.arange(b)[:, None]                      # (B, 1)
            pos = length[:, None] + jnp.arange(s)[None, :]     # (B, s)
            if ring:
                pos = pos % cap
            k_cache = k_cache.at[slot, pos].set(k.astype(k_cache.dtype),
                                                mode="drop")
            v_cache = v_cache.at[slot, pos].set(v.astype(v_cache.dtype),
                                                mode="drop")
        if out is None:
            out = _cache_attention(c, q, k_cache, v_cache, length, kv_mask,
                                   flash_decode_ok)
        kv_out = (k_cache, v_cache)
    else:
        out = _self_attention(c, q, k, v, kv_mask, mesh)
        kv_out = (k, v)

    x = x + _dense(out.reshape(b, s, c.q_dim), lp, "wo", "bse,ed->bsd")
    x, aux = _mlp(c, lp, x)
    return x, kv_out, aux


def _mlp(c: ModelConfig, lp: Dict[str, jax.Array], x: jax.Array):
    """Post-attention FFN block (dense silu-gate or MoE), shared by the
    contiguous-cache and paged layer bodies. Returns
    (x + ffn(norm(x)), moe aux loss — 0 for dense layers)."""
    h = rms_norm(x, lp["mlp_norm"], c.rms_norm_eps)
    if c.num_experts > 0:
        from ..parallel.expert import MoEConfig, moe_ffn
        moe_cfg = MoEConfig(hidden_size=c.hidden_size,
                            intermediate_size=c.intermediate_size,
                            num_experts=c.num_experts,
                            top_k=c.num_experts_per_tok,
                            capacity_factor=c.expert_capacity_factor,
                            dtype=c.dtype)
        moe_params = {"router": lp["router"], "w_gate": lp["w_gate"],
                      "w_up": lp["w_up"], "w_down": lp["w_down"]}
        for _n in ("w_gate_scale", "w_up_scale", "w_down_scale"):
            if _n in lp:       # int8 expert banks (models/quantize.py)
                moe_params[_n] = lp[_n]
        ffn_out, aux = moe_ffn(moe_params, moe_cfg, h)
        return x + ffn_out, aux
    gate = _dense(h, lp, "w_gate", "bsd,df->bsf")
    up = _dense(h, lp, "w_up", "bsd,df->bsf")
    act = jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype) * up
    return (x + _dense(act, lp, "w_down", "bsf,fd->bsd"),
            jnp.zeros((), jnp.float32))


def forward(
    params: Params,
    config: ModelConfig,
    tokens: jax.Array,                 # (B, S) int32
    *,
    cache: Optional[KVCache] = None,
    positions: Optional[jax.Array] = None,   # (B, S) absolute positions
    attn_mask: Optional[jax.Array] = None,   # (B, S_kv) True = valid
    with_aux: bool = False,
    mesh=None,                               # required for ring/ulysses attn
    fresh_cache: bool = False,               # static: cache holds nothing yet
):
    """Run the model. Without cache: full causal self-attention over ``tokens``.
    With cache: ``tokens`` are appended at ``cache.length`` and attend to
    everything up to that point (prefill and decode use the same path).

    ``mesh`` (jax.sharding.Mesh) is only consulted when
    ``config.attn_impl`` is 'ring'/'ulysses' — the sequence axis then
    shards over its 'sp' axis inside shard_map.

    Returns (logits (B, S, V) fp32, updated cache or None); with
    ``with_aux=True`` also the summed MoE load-balancing loss (the router
    must see it in the objective or it is free to collapse).
    """
    c = config
    if c.matmul_precision is not None:
        with jax.default_matmul_precision(c.matmul_precision):
            out = _forward_impl(params, c, tokens, cache=cache,
                                positions=positions, attn_mask=attn_mask,
                                mesh=mesh, fresh_cache=fresh_cache)
    else:
        out = _forward_impl(params, c, tokens, cache=cache,
                            positions=positions, attn_mask=attn_mask,
                            mesh=mesh, fresh_cache=fresh_cache)
    logits, new_cache, aux = out
    if with_aux:
        return logits, new_cache, aux
    return logits, new_cache


def _forward_impl(params, c, tokens, *, cache, positions, attn_mask,
                  mesh=None, fresh_cache=False):
    b, s = tokens.shape
    x = params["embed"][tokens]  # gather; sharded vocab → XLA collective

    if positions is None:
        base = cache.length if cache is not None else jnp.zeros((), jnp.int32)
        if base.ndim == 1:
            base = base[:, None]                       # per-slot lengths
        positions = base + jnp.arange(s, dtype=jnp.int32)[None, :]
        positions = jnp.broadcast_to(positions, (b, s))
    cos, sin = rope_cos_sin(positions, c.head_dim, c.rope_theta,
                            scaling=c.rope_scaling)

    if cache is None:
        def one_layer(x, lp, cos, sin):
            x, _, layer_aux = _layer(c, lp, x, cos, sin, None, attn_mask,
                                     mesh=mesh)
            return x, layer_aux

        if c.remat:
            # Per-layer rematerialization: backward recomputes this
            # layer's activations instead of holding all L layers' —
            # O(1) activation memory in depth for O(L) extra forward
            # FLOPs. "dots" keeps matmul outputs (cheaper backward,
            # more memory); True/"full" keeps nothing.
            policy = (jax.checkpoint_policies.checkpoint_dots
                      if c.remat == "dots" else None)
            # prevent_cse=False: under lax.scan the CSE barrier is
            # unnecessary (per the jax.checkpoint docs) and its
            # optimization_barrier ops would block fusion across every
            # layer boundary of the training hot path.
            one_layer = jax.checkpoint(one_layer, policy=policy,
                                       prevent_cse=False)

        def body(carry, lp):
            x, aux = carry
            x, layer_aux = one_layer(x, lp, cos, sin)
            return (x, aux + layer_aux), None

        (x, aux_total), _ = jax.lax.scan(
            body, (x, jnp.zeros((), jnp.float32)), params["layers"],
            unroll=c.scan_unroll)
        new_cache = None
    else:
        max_len = cache.k.shape[2]
        length = cache.length
        if _is_ring(c, max_len):
            # Ring cache: capacity `cap` slots written at pos % cap.
            #
            # s == 1 (decode): write-then-attend is safe — the single new
            # token only overwrites the slot holding pos qp − cap, which
            # is outside its own window (cap ≥ window). Ring index i then
            # holds absolute position p(i) = the latest p ≡ i (mod cap);
            # the query may attend iff 0 ≤ p(i) ≤ qp > qp − window. Fill,
            # causality and the window all live in this one mask —
            # attention() runs with causal=False (ring index is NOT
            # absolute position).
            #
            # s > 1 (chunked prefill): write-first would DESTROY keys
            # still inside earlier queries' windows whenever the chunk
            # wraps (any wrapping chunk when cap == window), so _layer
            # attends BEFORE writing, over [pre-write cache ‖ chunk]:
            # the mask here is (B, s, cap + s) — old slots valid by their
            # pre-chunk positions, intra-chunk causal+window on the tail.
            cap = max_len
            if s > cap:
                raise ValueError(
                    f"chunk of {s} tokens exceeds the ring capacity "
                    f"{cap} (window {c.sliding_window}); prefill in "
                    f"chunks of at most the window size")
            base = length[:, None, None] if length.ndim == 1 else length
            i = jnp.arange(cap)[None, None, :]
            qp = base + jnp.arange(s)[None, :, None]           # query abs pos
            if s == 1:
                if attn_mask is not None:
                    raise NotImplementedError(
                        "attn_mask on a ring-cache decode step: ring "
                        "indices are modular positions — combine masks "
                        "upstream instead")
                total = base + 1                               # after write
                p = (total - 1) - ((total - 1 - i) % cap)      # pos per slot
                valid = (p >= 0) & (p <= qp) & (p > qp - c.sliding_window)
                valid = jnp.broadcast_to(valid, (b, 1, cap))
            else:
                t = jnp.arange(s)[None, None, :]               # chunk kv idx
                j = jnp.arange(s)[None, :, None]               # chunk q idx
                valid_new = (t <= j) & (j - t < c.sliding_window)
                if attn_mask is not None:
                    # Contract (serving-engine prefill): only meaningful
                    # on a FRESH slot (length == 0, nothing old to mask);
                    # positions then coincide with chunk indices.
                    valid_new = valid_new & attn_mask[:, None, :s]
                if fresh_cache:
                    # Chunk-width mask: _layer skips the [cache ‖ chunk]
                    # concat and its fully-masked score columns.
                    valid = jnp.broadcast_to(valid_new, (b, s, s))
                else:
                    p_old = (base - 1) - ((base - 1 - i) % cap)  # pre-chunk
                    valid_old = ((p_old >= 0)
                                 & (p_old > qp - c.sliding_window))
                    valid = jnp.concatenate(
                        [jnp.broadcast_to(valid_old, (b, s, cap)),
                         jnp.broadcast_to(valid_new, (b, s, s))], axis=-1)
        else:
            # kv validity: only slots < length + s are real.
            kv_pos = jnp.arange(max_len)[None, :]
            bound = (length[:, None] if length.ndim == 1 else length) + s
            valid = jnp.broadcast_to(kv_pos < bound, (b, max_len))
            if attn_mask is not None:
                valid = valid & attn_mask
        # Flash-decode applies only when the validity mask is exactly
        # "pos < valid_count" (single new token, no extra mask) and the
        # cache splits into proper KV blocks: either 128-aligned (the
        # streamed multi-block grid) or small enough that one whole-cache
        # block still fits VMEM comfortably. An unaligned LARGE cache
        # would degenerate to block_kv = max_len — no per-slot skipping
        # and a VMEM-busting block — so it falls back to einsum instead.
        # SWA eligibility: a RING cache qualifies exactly when capacity
        # == window (live entries are indices < min(length+1, cap), all
        # inside the window, and online softmax is order-invariant; cap >
        # window would leave stale slots the length model can't mask). An
        # ABSOLUTE short cache (cap < aligned window) qualifies when cap
        # ≤ window: every position it can hold is within any query's
        # window, so the plain "pos < length+1" model is already exact.
        tileable = (max_len % 128 == 0
                    or (max_len % 8 == 0 and max_len <= 512))
        if c.sliding_window is None:
            swa_flash = True
        elif _is_ring(c, max_len):
            swa_flash = max_len == c.sliding_window
        else:
            swa_flash = max_len <= c.sliding_window
        flash_ok = (c.decode_attn_impl == "flash" and s == 1
                    and attn_mask is None and tileable and swa_flash)

        if cache.quantized:
            def body_q(carry, inputs):
                x, aux = carry
                lp, k_c, v_c, k_s, v_s = inputs
                x, kv_out, layer_aux = _layer(
                    c, lp, x, cos, sin,
                    (k_c, v_c, cache.length, k_s, v_s), valid,
                    flash_decode_ok=flash_ok)
                return (x, aux + layer_aux), kv_out

            (x, aux_total), (k_upd, v_upd, ks_upd, vs_upd) = jax.lax.scan(
                body_q, (x, jnp.zeros((), jnp.float32)),
                (params["layers"], cache.k, cache.v, cache.k_scale,
                 cache.v_scale), unroll=c.scan_unroll)
            new_cache = KVCache(k=k_upd, v=v_upd, length=cache.length + s,
                                k_scale=ks_upd, v_scale=vs_upd)
        else:
            def body(carry, inputs):
                x, aux = carry
                lp, k_cache, v_cache = inputs
                x, (k_cache, v_cache), layer_aux = _layer(
                    c, lp, x, cos, sin, (k_cache, v_cache, cache.length),
                    valid, flash_decode_ok=flash_ok)
                return (x, aux + layer_aux), (k_cache, v_cache)

            (x, aux_total), (k_upd, v_upd) = jax.lax.scan(
                body, (x, jnp.zeros((), jnp.float32)),
                (params["layers"], cache.k, cache.v), unroll=c.scan_unroll)
            new_cache = KVCache(k=k_upd, v=v_upd, length=cache.length + s)

    x = rms_norm(x, params["final_norm"], c.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:  # tied embeddings
        if "tied_head_q8" in params:
            # int8 shadow of the embed table (models/quantize.py): the
            # head matmul streams half the bytes; _dense applies the
            # per-vocab-row scale as the shared fused epilogue
            logits = _dense(x, params, "tied_head_q8", "bsd,vd->bsv")
        else:
            logits = jnp.einsum("bsd,vd->bsv", x, params["embed"])
    else:
        logits = _dense(x, params, "lm_head", "bsd,dv->bsv")
    return logits.astype(jnp.float32), new_cache, aux_total


def _paged_layer(c: ModelConfig, lp: Dict[str, jax.Array], x: jax.Array,
                 cos: jax.Array, sin: jax.Array,
                 k_pool: jax.Array, v_pool: jax.Array,
                 tables: jax.Array, seq_row: jax.Array,
                 positions: jax.Array, write_block: jax.Array,
                 write_off: jax.Array, use_kernel: bool = False,
                 adapters=None, adapter_ids=None,
                 k_scale_pool=None, v_scale_pool=None):
    """One transformer block over a paged KV pool (rollout/paged_kv.py).

    ``x`` is a flat token batch ``(T, 1, D)`` — T independent
    (sequence, position) pairs, decode steps and chunked-prefill
    segments mixed freely. This layer's pool is
    ``k_pool``/``v_pool`` ``(num_blocks, block_size, Hkv, Dh)``; each
    token first scatters its new k/v at
    ``(write_block[t], write_off[t])`` (``write_block == num_blocks``
    drops the write — padding and rescore entries), then attends over
    its own sequence through the block-table indirection
    ``tables[seq_row[t]]``. The scatter lands before the gather, so a
    chunk's later tokens see its earlier ones at the same layer —
    flat-batch chunked prefill is exactly block prefill.

    The gathered view is a contiguous ``(T, MB*BS, Hkv, Dh)`` cache
    per token, attended with the SAME mask and attention call as the
    slot path (`kv_pos < pos+1`, causal with per-row ``q_offset``), so
    paged and slot decode agree to numerical identity of the masking
    and matmul shapes' element-wise dot products.
    """
    t = x.shape[0]
    quantized = k_scale_pool is not None
    h = rms_norm(x, lp["attn_norm"], c.rms_norm_eps)
    q, k, v = _qkv(c, lp, h, cos, sin, adapters, adapter_ids)
    # q (T,1,Hq,Dh), k/v (T,1,Hkv,Dh)
    if quantized:
        # Quantize-at-write: payload and scale scatter through the SAME
        # (write_block, write_off) indices with the same mode="drop"
        # out-of-range sentinel, so dropped writes (padding / rescore
        # entries) leave both tensors untouched and quantization
        # commutes with the sentinel, fork refcounts, and COW — those
        # act on whole blocks via the pool movers, never element-wise.
        kq, ks = quantize_pool_kv(k[:, 0], k_pool.dtype)
        vq, vs = quantize_pool_kv(v[:, 0], v_pool.dtype)
        k_pool = k_pool.at[write_block, write_off].set(kq, mode="drop")
        v_pool = v_pool.at[write_block, write_off].set(vq, mode="drop")
        k_scale_pool = k_scale_pool.at[write_block, write_off].set(
            ks, mode="drop")
        v_scale_pool = v_scale_pool.at[write_block, write_off].set(
            vs, mode="drop")
    else:
        k_pool = k_pool.at[write_block, write_off].set(
            k[:, 0].astype(k_pool.dtype), mode="drop")
        v_pool = v_pool.at[write_block, write_off].set(
            v[:, 0].astype(v_pool.dtype), mode="drop")
    if use_kernel:
        from ..ops.paged_attention import paged_flash_decode
        out = paged_flash_decode(q[:, 0], k_pool, v_pool,
                                 tables[seq_row], positions + 1,
                                 k_scale=k_scale_pool,
                                 v_scale=v_scale_pool)[:, None]
    else:
        nb, bs, hkv, dh = k_pool.shape
        tbl = tables[seq_row]                              # (T, MB)
        mb = tbl.shape[1]
        k_seq = k_pool[tbl].reshape(t, mb * bs, hkv, dh)
        v_seq = v_pool[tbl].reshape(t, mb * bs, hkv, dh)
        if quantized:
            k_seq = dequantize_pool_kv(
                k_seq, k_scale_pool[tbl].reshape(t, mb * bs, hkv), x.dtype)
            v_seq = dequantize_pool_kv(
                v_seq, v_scale_pool[tbl].reshape(t, mb * bs, hkv), x.dtype)
        kv_pos = jnp.arange(mb * bs)[None, :]
        valid = kv_pos < positions[:, None] + 1
        out = attention(q, k_seq.astype(x.dtype), v_seq.astype(x.dtype),
                        q_offset=positions, kv_mask=valid, causal=True)
    attn_in = out.reshape(t, 1, c.q_dim)
    attn_out = _dense(attn_in, lp, "wo", "bse,ed->bsd")
    attn_out = _with_adapter(attn_out, attn_in, adapters, adapter_ids, "wo")
    x = x + attn_out
    x, aux = _mlp(c, lp, x)
    if quantized:
        return x, (k_pool, v_pool, k_scale_pool, v_scale_pool), aux
    return x, (k_pool, v_pool), aux


def forward_paged(
    params: Params,
    config: ModelConfig,
    tokens: jax.Array,            # (T,) int32 — flat token batch
    *,
    pool,                         # rollout.paged_kv.PagedKVPool (duck-
                                  # typed pytree: k/v payload arrays,
                                  # optional k_scale/v_scale/k_hi/v_hi)
    tables: jax.Array,            # (R, MB) int32 — physical block per
                                  # (row, logical block)
    seq_row: jax.Array,           # (T,) int32 — table row per token
    positions: jax.Array,         # (T,) int32 — absolute position
    write_block: jax.Array,       # (T,) int32 — pool block to write
                                  # (num_blocks = drop)
    write_off: jax.Array,         # (T,) int32 — offset within block
    use_kernel: bool = False,     # static: Pallas paged-decode kernel
    adapters=None,                # per-rung LoRA bank dicts, leading L
    adapter_ids=None,             # per-rung (T,) int32 slot ids
):
    """Run the model over a paged KV pool: every entry of the flat
    ``(T,)`` token batch is one (sequence, position) pair — a decode
    step or one token of a chunked-prefill segment — reading KV through
    the ``(row, logical_block) -> physical_block`` table. Returns
    ``(logits (T, V) fp32, pool')``. Token t's logits predict its next
    token, so the engine samples from the rows it flagged (decode
    entries and final prompt tokens) and ignores the rest.

    ``pool`` is the whole ``PagedKVPool`` pytree (accepted duck-typed
    to avoid a models → rollout import cycle). A quantized pool
    (``k_scale is not None``) stores int8/fp8 payloads with per-token
    per-head f32 absmax scales, quantized AT WRITE TIME inside this one
    traced function — no extra device round-trips. An optional
    ``k_hi``/``v_hi`` full-width prefix holds the first
    ``pool.hi_layers`` layers (``kv_dtype_per_layer`` ladder: early
    layers, where divergence concentrates, stay bf16)."""
    c = config
    if c.matmul_precision is not None:
        with jax.default_matmul_precision(c.matmul_precision):
            return _forward_paged_impl(
                params, c, tokens, pool=pool,
                tables=tables, seq_row=seq_row, positions=positions,
                write_block=write_block, write_off=write_off,
                use_kernel=use_kernel, adapters=adapters,
                adapter_ids=adapter_ids)
    return _forward_paged_impl(
        params, c, tokens, pool=pool, tables=tables,
        seq_row=seq_row, positions=positions, write_block=write_block,
        write_off=write_off, use_kernel=use_kernel, adapters=adapters,
        adapter_ids=adapter_ids)


def _forward_paged_impl(params, c, tokens, *, pool, tables,
                        seq_row, positions, write_block, write_off,
                        use_kernel, adapters=None, adapter_ids=None):
    x = params["embed"][tokens][:, None, :]            # (T, 1, D)
    cos, sin = rope_cos_sin(positions[:, None], c.head_dim, c.rope_theta,
                            scaling=c.rope_scaling)
    aux0 = jnp.zeros((), jnp.float32)
    # Both are STATIC under jit: derived from pytree structure (None-ness
    # and shapes), so the precision ladder never adds a trace argument.
    n_hi = 0 if pool.k_hi is None else pool.k_hi.shape[0]
    quantized = pool.k_scale is not None

    def full_body(carry, inputs):
        x, aux = carry
        # Adapter banks carry a leading L axis (rollout/adapter_pool),
        # so they ride the layer scan as xs; ``adapters is None`` scans
        # as an empty pytree and unpacks back to None here.
        lp, k_l, v_l, ad = inputs
        x, (k_l, v_l), layer_aux = _paged_layer(
            c, lp, x, cos, sin, k_l, v_l, tables, seq_row, positions,
            write_block, write_off, use_kernel=use_kernel,
            adapters=ad, adapter_ids=adapter_ids)
        return (x, aux + layer_aux), (k_l, v_l)

    def quant_body(carry, inputs):
        x, aux = carry
        lp, k_l, v_l, ks_l, vs_l, ad = inputs
        x, (k_l, v_l, ks_l, vs_l), layer_aux = _paged_layer(
            c, lp, x, cos, sin, k_l, v_l, tables, seq_row, positions,
            write_block, write_off, use_kernel=use_kernel,
            adapters=ad, adapter_ids=adapter_ids,
            k_scale_pool=ks_l, v_scale_pool=vs_l)
        return (x, aux + layer_aux), (k_l, v_l, ks_l, vs_l)

    layers, lo_ad = params["layers"], adapters
    upd = {}
    carry = (x, aux0)
    if n_hi:
        # Full-width prefix layers scan first, then the quantized tail:
        # two scans over layer slices instead of one (the per-layer
        # ladder is a partition, so the slices are contiguous).
        sl_hi = functools.partial(jax.tree_util.tree_map,
                                  lambda a: a[:n_hi])
        sl_lo = functools.partial(jax.tree_util.tree_map,
                                  lambda a: a[n_hi:])
        carry, (k_hi, v_hi) = jax.lax.scan(
            full_body, carry,
            (sl_hi(layers), pool.k_hi, pool.v_hi, sl_hi(adapters)),
            unroll=c.scan_unroll)
        upd["k_hi"], upd["v_hi"] = k_hi, v_hi
        layers, lo_ad = sl_lo(layers), sl_lo(adapters)
    if quantized:
        carry, (k_upd, v_upd, ks_upd, vs_upd) = jax.lax.scan(
            quant_body, carry,
            (layers, pool.k, pool.v, pool.k_scale, pool.v_scale, lo_ad),
            unroll=c.scan_unroll)
        upd.update(k=k_upd, v=v_upd, k_scale=ks_upd, v_scale=vs_upd)
    else:
        carry, (k_upd, v_upd) = jax.lax.scan(
            full_body, carry, (layers, pool.k, pool.v, lo_ad),
            unroll=c.scan_unroll)
        upd.update(k=k_upd, v=v_upd)
    x, _aux = carry

    x = rms_norm(x, params["final_norm"], c.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:  # tied embeddings
        if "tied_head_q8" in params:
            logits = _dense(x, params, "tied_head_q8", "bsd,vd->bsv")
        else:
            logits = jnp.einsum("bsd,vd->bsv", x, params["embed"])
    else:
        logits = _dense(x, params, "lm_head", "bsd,dv->bsv")
    return logits[:, 0].astype(jnp.float32), pool._replace(**upd)


def count_params(params: Params) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
