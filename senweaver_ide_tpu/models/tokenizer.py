"""Tokenizers: HF wrapper when tokenizer files exist locally, byte-level
fallback otherwise (this environment has zero egress, so the fallback is the
default in tests and benches; throughput numbers are tokenizer-independent).
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence


class ByteTokenizer:
    """Byte-level tokenizer: ids 0-255 = bytes, then specials."""

    def __init__(self):
        self.bos_id = 256
        self.eos_id = 257
        self.pad_id = 258
        self.vocab_size = 259

    def encode(self, text: str, *, add_bos: bool = False,
               add_eos: bool = False) -> List[int]:
        ids = list(text.encode("utf-8"))
        if add_bos:
            ids = [self.bos_id] + ids
        if add_eos:
            ids = ids + [self.eos_id]
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """Wrapper over a locally-available HuggingFace tokenizer directory."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.bos_id = self._tok.bos_token_id
        self.eos_id = self._tok.eos_token_id
        self.pad_id = self._tok.pad_token_id or self.eos_id
        self.vocab_size = len(self._tok)

    def encode(self, text: str, *, add_bos: bool = False,
               add_eos: bool = False) -> List[int]:
        ids = self._tok.encode(text, add_special_tokens=False)
        if add_bos and self.bos_id is not None:
            ids = [self.bos_id] + ids
        if add_eos and self.eos_id is not None:
            ids = ids + [self.eos_id]
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)


def load_tokenizer(path: Optional[str] = None):
    """HF tokenizer if ``path`` has files, else the byte fallback."""
    if path and os.path.isdir(path):
        try:
            return HFTokenizer(path)
        except Exception:
            pass
    return ByteTokenizer()
