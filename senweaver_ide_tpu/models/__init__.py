from .config import (ModelConfig, PRESETS, RopeScaling, get_config,
                     qwen2_5_coder_0_5b, qwen2_5_coder_1_5b, qwen2_5_coder_7b,
                     deepseek_coder_1_3b, deepseek_coder_6_7b, llama_3_1_8b,
                     llama_3_2_1b, small_test, tiny_test)
from .transformer import (KVCache, Params, count_params, forward,
                          init_kv_cache, init_params)
from .load import available_hf_keys, export_hf_params, load_hf_params
from .quantize import is_quantized, quantize_weights_int8, quantized_bytes
from .tokenizer import ByteTokenizer, HFTokenizer, load_tokenizer
from .capabilities import (ModelCapabilities, get_model_capabilities,
                           get_reserved_output_token_space)
