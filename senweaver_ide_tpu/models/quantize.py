"""Weight-only int8 quantization for serving.

Decode on one v5e chip is weight-HBM-bound (BENCH_NOTES.md: 2116
tok/s/chip for the 1.5B ≈ the bf16 roofline 819 GB/s ÷ 3.1 GB). Storing
the dense matmul weights as int8 with one fp32 scale per OUTPUT channel
(absmax over the contraction axis) halves the bytes every decode step
must stream, raising the bandwidth ceiling ~2× at <1% relative logit
error; the MXU still computes in the activation dtype (the int8→bf16
upcast happens at tile load, the scale is a fused output epilogue — see
``transformer._dense``).

Scope: the seven stacked per-layer dense matrices + ``lm_head`` +
the 4-D MoE expert banks (per-expert per-output-channel scales — with
expert parallelism this is what fits Mixtral-class weights on a small
pod slice). Excluded on purpose:
  - norms/biases (tiny, precision-critical),
  - the MoE router (routing decisions are precision-sensitive and the
    matrix is tiny),
  - ``embed`` (a gather, not a matmul; tied-head quality is sensitive).

This is a SERVING transform: quantized params are not differentiable
and must never enter ``train_step``. The actor/learner bridge
(``RolloutEngine.update_params``) re-applies it on publish when the
engine was built with quantized weights.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

# Stacked (L, in, out) layer matrices + the 2-D head; in all of them the
# contraction axis is -2, so per-output-channel absmax is over axis=-2.
QUANTIZABLE = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def dense_family_shapes(config) -> Dict[str, tuple]:
    """(fan_in, out) per dense family for a NON-MoE config — the one
    source of truth for sizing tables and direct-int8 initializers
    (bench/eval scripts otherwise each restate this table and drift)."""
    c = config
    if c.num_experts > 0:
        raise ValueError("dense_family_shapes: MoE configs carry (L, E, "
                         "in, out) expert banks — size those explicitly")
    D, F = c.hidden_size, c.intermediate_size
    q_dim, kv_dim = c.q_dim, c.kv_dim
    return {"wq": (D, q_dim), "wk": (D, kv_dim), "wv": (D, kv_dim),
            "wo": (q_dim, D), "w_gate": (D, F), "w_up": (D, F),
            "w_down": (F, D)}


def _quantize_matrix(w: jax.Array):
    """(…, in, out) → int8 values + fp32 (…, out) per-channel scales."""
    wf = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=-2)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / scale[..., None, :]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def quantize_weights_int8(params: Dict) -> Dict:
    """Return a new param pytree with dense weights int8-quantized.

    Idempotent (already-int8 tensors pass through); anything outside
    QUANTIZABLE (router, norms, biases, embed) is left untouched."""
    out = dict(params)
    layers = dict(params["layers"])
    for name in QUANTIZABLE:
        w = layers.get(name)
        # 3-D: stacked dense (L, in, out); 4-D: stacked MoE expert banks
        # (L, E, in, out) — _quantize_matrix is rank-generic (absmax
        # over the contraction axis -2, scales (..., out)).
        if w is None or w.dtype == jnp.int8 or w.ndim not in (3, 4):
            continue
        layers[name], layers[name + "_scale"] = _quantize_matrix(w)
    out["layers"] = layers
    head = params.get("lm_head")
    if head is not None and head.dtype != jnp.int8:
        out["lm_head"], out["lm_head_scale"] = _quantize_matrix(head)
    elif head is None and "tied_head_q8" not in params:
        # Tied embeddings: the head matmul streams the FULL (V, D) table
        # every decode step (the largest single tensor of the 1.5B
        # flagship, ~15% of its weight bytes). Keep the bf16 embed for
        # the GATHER (quality-sensitive, reads only B rows) and store an
        # int8 SHADOW with per-vocab-row scales for the head matmul —
        # +50% of embed's footprint, −50% of its per-step traffic.
        emb = params["embed"].astype(jnp.float32)          # (V, D)
        absmax = jnp.max(jnp.abs(emb), axis=-1)            # (V,)
        scale = jnp.maximum(absmax, 1e-8) / 127.0
        out["tied_head_q8"] = jnp.clip(
            jnp.round(emb / scale[:, None]), -127, 127).astype(jnp.int8)
        # _scale suffix on the weight's own key: transformer._dense's
        # shared int8 epilogue resolves it by name
        out["tied_head_q8_scale"] = scale
    return out


def is_quantized(params: Dict) -> bool:
    w = params.get("layers", {}).get("wq")
    return w is not None and w.dtype == jnp.int8


def quantized_bytes(params: Dict) -> int:
    return sum(x.size * x.dtype.itemsize
               for x in jax.tree_util.tree_leaves(params))
