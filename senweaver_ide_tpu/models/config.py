"""Model configurations for the policy LLM families.

The reference targets remote/provider-hosted models (capability DB in
``common/modelCapabilities.ts``); the north star pins the local policy ladder
Qwen2.5-Coder-1.5B → DeepSeek-Coder-7B (BASELINE.json configs 3-5). Both
families are decoder-only pre-norm transformers with RoPE + SwiGLU; Qwen2 uses
GQA + QKV biases, DeepSeek-Coder is LLaMA-architecture (MHA at 1.3B/6.7B,
no attention biases).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class RopeScaling:
    """Llama-3-style NTK-by-parts RoPE scaling (HF ``rope_type: llama3``).

    Frozen (hashable) because ModelConfig rides jit static args. Fields
    mirror the HF ``rope_scaling`` dict of Llama-3.1+ checkpoints."""
    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position: int = 8192


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    max_seq_len: int
    rope_theta: float = 10000.0
    # Llama-3.1+ long-context frequency scaling; None = plain RoPE.
    rope_scaling: Optional[RopeScaling] = None
    rms_norm_eps: float = 1e-6
    tie_word_embeddings: bool = False
    qkv_bias: bool = False
    # Qwen3-style per-head RMSNorm on q and k (over head_dim, before
    # RoPE) — replaces Qwen2's qkv biases as the attention stabilizer.
    qk_norm: bool = False
    # int8 KV cache with per-(position, head) scales: halves cache HBM so
    # memory-capacity-bound serving (6.7b on one 16 GB chip) fits 2× the
    # decode batch. See models/transformer.py _quantize_kv.
    kv_quant: bool = False
    dtype: jnp.dtype = jnp.bfloat16
    # Sliding-window attention width (None = full causal).
    sliding_window: Optional[int] = None
    # Attention implementation for the no-cache (training/scoring) path:
    #   "einsum"  — XLA einsum attention (ops/attention.py), materializes
    #               the (Sq, Skv) score matrix; fine for short sequences.
    #   "flash"   — Pallas flash-attention kernel (ops/flash_attention.py),
    #               O(S·block) memory; interpret-mode on non-TPU backends.
    #   "ring"    — ring attention over the 'sp' mesh axis
    #               (parallel/ring_attention.py); requires forward(mesh=...)
    #               with an sp axis and S divisible by its size.
    #   "ulysses" — Ulysses all-to-all head/sequence swap over 'sp'; head
    #               counts must divide by the sp axis size.
    # The KV-cache (decode) path has its own selection below.
    attn_impl: str = "einsum"
    # Attention implementation for the KV-cache single-token decode path:
    #   "einsum" — ops/attention.py over the whole cache (materializes
    #              the (B, Hkv, rep, 1, Smax) fp32 scores per step).
    #   "flash"  — ops/flash_decode.py: streamed KV blocks with online
    #              softmax and per-slot length skipping; interpret-mode
    #              on non-TPU backends. Applies only when s == 1 and no
    #              extra attention mask is in play (prefill keeps einsum).
    decode_attn_impl: str = "einsum"
    # lax.scan unroll factor for the layer loop. Decode steps are tiny
    # programs; TPU loop overhead per scan iteration is material at
    # sq=1, and unrolling trades compile time for it. 1 = no unroll.
    scan_unroll: int = 1
    # Rematerialize layer activations in the no-cache (training) path:
    # jax.checkpoint around each scanned layer, so backward recomputes
    # activations instead of saving L layers of them — the HBM-for-FLOPs
    # trade that fits 7B long-trajectory batches (with ring attention and
    # train_step(accum_steps=...)). "dots" saves matmul outputs only
    # (checkpoint_dots); True/"full" saves nothing.
    remat: object = False    # False | True | "full" | "dots"
    # jax.default_matmul_precision for the forward pass. None = platform
    # default (bf16 MXU passes — the fast path for real models). The fp32
    # test config pins "highest" so cache-vs-full decode parity is exact.
    matmul_precision: Optional[str] = None
    # Mixture-of-experts FFN: 0 = dense. When > 0, every layer's MLP is a
    # top-k routed expert bank (parallel/expert.py semantics) and the
    # expert axis shards over 'ep'.
    num_experts: int = 0
    num_experts_per_tok: int = 2
    expert_capacity_factor: float = 1.25
    # HF checkpoint layout for the expert banks on EXPORT ("mixtral":
    # block_sparse_moe w1/w3/w2; "qwen3": mlp.experts gate/up/down_proj).
    # The loader autodetects from the checkpoint keys.
    moe_layout: str = "mixtral"

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim


def qwen2_5_coder_0_5b() -> ModelConfig:
    return ModelConfig(
        name="qwen2.5-coder-0.5b", vocab_size=151_936, hidden_size=896,
        intermediate_size=4864, num_layers=24, num_heads=14, num_kv_heads=2,
        head_dim=64, max_seq_len=32_768, rope_theta=1_000_000.0,
        tie_word_embeddings=True, qkv_bias=True)


def qwen2_5_coder_1_5b() -> ModelConfig:
    """The flagship bench model (BASELINE config 3).

    Pretrained weights: point ``models.load.load_hf_params`` at a local
    HF-layout directory (e.g. a downloaded Qwen/Qwen2.5-Coder-1.5B snapshot
    containing model.safetensors[.index.json]); same for every preset here.
    """
    return ModelConfig(
        name="qwen2.5-coder-1.5b", vocab_size=151_936, hidden_size=1536,
        intermediate_size=8960, num_layers=28, num_heads=12, num_kv_heads=2,
        head_dim=128, max_seq_len=32_768, rope_theta=1_000_000.0,
        tie_word_embeddings=True, qkv_bias=True)


def qwen2_5_coder_7b() -> ModelConfig:
    return ModelConfig(
        name="qwen2.5-coder-7b", vocab_size=152_064, hidden_size=3584,
        intermediate_size=18_944, num_layers=28, num_heads=28, num_kv_heads=4,
        head_dim=128, max_seq_len=131_072, rope_theta=1_000_000.0,
        qkv_bias=True)


def mistral_7b() -> ModelConfig:
    """Mistral-7B-v0.1: the sliding-window-attention family.

    The reference serves Mistral models through its mistral provider
    (codestral FIM entry in the capability DB; provider registry
    ``transport/providers.py``); this preset gives that family a local
    policy architecture: LLaMA-style GQA with a 4096-token sliding
    window — each token attends only to its trailing 4096 positions
    (``ops/attention.py causal_mask(window=...)``). HF-layout weights
    load via ``models.load`` (same q/k/v/gate/up/down key scheme)."""
    return ModelConfig(
        name="mistral-7b", vocab_size=32_000, hidden_size=4096,
        intermediate_size=14_336, num_layers=32, num_heads=32,
        num_kv_heads=8, head_dim=128, max_seq_len=32_768,
        rope_theta=10_000.0, rms_norm_eps=1e-5, sliding_window=4096)


def mixtral_8x7b() -> ModelConfig:
    """Mixtral-8x7B-v0.1: the SWA + MoE composition.

    Mistral-family GQA with an 8-expert top-2 routed FFN — exercises
    the expert-parallel path (parallel/expert.py, 'ep' mesh axis) on a
    real released architecture. Released Mixtral-8x7B checkpoints use
    FULL dense attention over 32k (HF config.json: sliding_window null),
    so this preset does too — serving real weights with a window would
    silently mask attention past it and corrupt long-context logits.
    (The SWA+MoE *composition* is still covered: tiny-moe-test + a
    sliding_window override exercises the ring KV cache with experts.)
    Reference serves Mixtral through its mistral/openai providers
    (capability DB substring families)."""
    return ModelConfig(
        name="mixtral-8x7b", vocab_size=32_000, hidden_size=4096,
        intermediate_size=14_336, num_layers=32, num_heads=32,
        num_kv_heads=8, head_dim=128, max_seq_len=32_768,
        rope_theta=1_000_000.0, rms_norm_eps=1e-5, sliding_window=None,
        num_experts=8, num_experts_per_tok=2)


def deepseek_coder_1_3b() -> ModelConfig:
    return ModelConfig(
        name="deepseek-coder-1.3b", vocab_size=32_256, hidden_size=2048,
        intermediate_size=5504, num_layers=24, num_heads=16, num_kv_heads=16,
        head_dim=128, max_seq_len=16_384, rope_theta=100_000.0)


def deepseek_coder_6_7b() -> ModelConfig:
    """The GRPO target (BASELINE config 4)."""
    return ModelConfig(
        name="deepseek-coder-6.7b", vocab_size=32_256, hidden_size=4096,
        intermediate_size=11_008, num_layers=32, num_heads=32, num_kv_heads=32,
        head_dim=128, max_seq_len=16_384, rope_theta=100_000.0)


def tiny_moe_test() -> ModelConfig:
    """MoE policy variant for unit tests / EP dry runs."""
    return ModelConfig(
        name="tiny-moe-test", vocab_size=512, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_seq_len=128, qkv_bias=True,
        dtype=jnp.float32, matmul_precision="highest",
        num_experts=4, num_experts_per_tok=2)


def tiny_test() -> ModelConfig:
    """Small config for unit tests and CPU-mesh dry runs."""
    return ModelConfig(
        name="tiny-test", vocab_size=512, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_seq_len=128, qkv_bias=True,
        dtype=jnp.float32, matmul_precision="highest")


def qwen3_1_7b() -> ModelConfig:
    """Qwen3-1.7B: QK-norm GQA, no attention biases, tied embeddings."""
    return ModelConfig(
        name="qwen3-1.7b", vocab_size=151_936, hidden_size=2048,
        intermediate_size=6144, num_layers=28, num_heads=16, num_kv_heads=8,
        head_dim=128, max_seq_len=32_768, rope_theta=1_000_000.0,
        tie_word_embeddings=True, qk_norm=True)


def qwen3_8b() -> ModelConfig:
    """Qwen3-8B: the 7B-class member of the Qwen3 ladder."""
    return ModelConfig(
        name="qwen3-8b", vocab_size=151_936, hidden_size=4096,
        intermediate_size=12_288, num_layers=36, num_heads=32,
        num_kv_heads=8, head_dim=128, max_seq_len=32_768,
        rope_theta=1_000_000.0, qk_norm=True)


def qwen3_30b_a3b() -> ModelConfig:
    """Qwen3-30B-A3B: the MoE member of the Qwen3 ladder (128 experts,
    8 active, QK-norm; ~3B active params per token)."""
    return ModelConfig(
        name="qwen3-30b-a3b", vocab_size=151_936, hidden_size=2048,
        intermediate_size=768, num_layers=48, num_heads=32, num_kv_heads=4,
        head_dim=128, max_seq_len=32_768, rope_theta=1_000_000.0,
        qk_norm=True, num_experts=128, num_experts_per_tok=8,
        moe_layout="qwen3")


def llama_3_2_1b() -> ModelConfig:
    """Llama-3.2-1B: GQA, tied embeddings, llama3 RoPE scaling (the
    128k-context serving config of an 8k-trained base)."""
    return ModelConfig(
        name="llama-3.2-1b", vocab_size=128_256, hidden_size=2048,
        intermediate_size=8192, num_layers=16, num_heads=32, num_kv_heads=8,
        head_dim=64, max_seq_len=131_072, rope_theta=500_000.0,
        rope_scaling=RopeScaling(factor=32.0), rms_norm_eps=1e-5,
        tie_word_embeddings=True)


def llama_3_1_8b() -> ModelConfig:
    """Llama-3.1-8B: the 7B-class member of the Llama family ladder."""
    return ModelConfig(
        name="llama-3.1-8b", vocab_size=128_256, hidden_size=4096,
        intermediate_size=14_336, num_layers=32, num_heads=32,
        num_kv_heads=8, head_dim=128, max_seq_len=131_072,
        rope_theta=500_000.0, rope_scaling=RopeScaling(factor=8.0),
        rms_norm_eps=1e-5)


def small_test() -> ModelConfig:
    """Between tiny-test and the real presets: enough capacity for
    prompt-CONDITIONAL behavior (the contextual learning eval needs the
    task tokens, buried in an ~1.8k-token prompt, to actually route the
    output distribution — tiny-test's 2×d64 could not; see
    ROUND3_NOTES.md §16), still seconds-per-round on one chip."""
    return ModelConfig(
        name="small-test", vocab_size=512, hidden_size=128,
        intermediate_size=384, num_layers=4, num_heads=8, num_kv_heads=4,
        head_dim=32, max_seq_len=4096, qkv_bias=True,
        dtype=jnp.float32, matmul_precision="highest")


PRESETS = {
    "qwen2.5-coder-0.5b": qwen2_5_coder_0_5b,
    "qwen2.5-coder-1.5b": qwen2_5_coder_1_5b,
    "qwen2.5-coder-7b": qwen2_5_coder_7b,
    "mistral-7b": mistral_7b,
    "mixtral-8x7b": mixtral_8x7b,
    "deepseek-coder-1.3b": deepseek_coder_1_3b,
    "deepseek-coder-6.7b": deepseek_coder_6_7b,
    "llama-3.2-1b": llama_3_2_1b,
    "llama-3.1-8b": llama_3_1_8b,
    "qwen3-1.7b": qwen3_1_7b,
    "qwen3-8b": qwen3_8b,
    "qwen3-30b-a3b": qwen3_30b_a3b,
    "tiny-test": tiny_test,
    "tiny-moe-test": tiny_moe_test,
    "small-test": small_test,
}


def get_config(name: str) -> ModelConfig:
    if name not in PRESETS:
        raise KeyError(f"unknown model {name!r}; available: {sorted(PRESETS)}")
    return PRESETS[name]()
