"""Model capability database.

The analogue of `common/modelCapabilities.ts` (2211 LoC): a static table of
per-model capabilities — context window, reserved output space, FIM
support, reasoning/think-tag behavior — keyed by model-name substring. The
reference's table covers 20 remote providers; this build's table covers
the local policy families it trains/serves (Qwen2.5-Coder, DeepSeek-Coder)
plus the remote families rollouts may call for distillation, with the same
lookup semantics (substring match, specific-first, default fallback).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class ModelCapabilities:
    """Schema mirror of modelCapabilities.ts:214-263."""
    context_window: int
    reserved_output_token_space: int = 4096
    supports_fim: bool = False
    fim_tokens: Optional[Tuple[str, str, str]] = None   # prefix/suffix/middle
    supports_system_message: bool = True
    reasoning_think_tags: Optional[Tuple[str, str]] = None
    max_output_tokens: int = 4096


_QWEN_FIM = ("<|fim_prefix|>", "<|fim_suffix|>", "<|fim_middle|>")
_DEEPSEEK_FIM = ("<｜fim▁begin｜>", "<｜fim▁hole｜>", "<｜fim▁end｜>")

_THINK = ("<think>", "</think>")

# Ordered: first substring match wins (specific before generic) — the
# reference's lookup discipline (modelCapabilities.ts substring families,
# specific keys above family keys). One entry per flagship family of
# every registered provider (transport/providers.py), so the 18-provider
# surface resolves real capabilities instead of the fallback.
_CAPABILITIES: Tuple[Tuple[str, ModelCapabilities], ...] = (
    # --- local policy ladder (BASELINE configs) --------------------------
    # deepseek keys sort ABOVE the qwen family: R1 qwen-distill ids
    # ("deepseek-r1-distill-qwen-7b") contain BOTH substrings and must
    # resolve the reasoning entry, not generic qwen.
    ("deepseek-coder", ModelCapabilities(
        context_window=16_384, supports_fim=True,
        fim_tokens=_DEEPSEEK_FIM)),
    ("deepseek-r1", ModelCapabilities(
        context_window=65_536, reasoning_think_tags=_THINK)),
    ("deepseek-reasoner", ModelCapabilities(
        context_window=65_536, reasoning_think_tags=_THINK,
        max_output_tokens=8192)),
    ("deepseek", ModelCapabilities(context_window=65_536,
                                   max_output_tokens=8192)),
    ("qwen2.5-coder", ModelCapabilities(
        context_window=32_768, supports_fim=True, fim_tokens=_QWEN_FIM)),
    ("qwen3", ModelCapabilities(context_window=131_072,
                                reasoning_think_tags=_THINK)),
    ("qwq", ModelCapabilities(context_window=131_072,
                              reasoning_think_tags=_THINK)),
    ("qwen", ModelCapabilities(context_window=131_072)),
    # --- mistral family --------------------------------------------------
    ("codestral", ModelCapabilities(
        context_window=262_144, supports_fim=True)),
    # Mistral-7B (the local SWA policy preset, models/config.py
    # mistral_7b): 32k context via the 4096-token sliding window. Keyed
    # on the full preset name — a bare "mistral" key would also match
    # remote API models (mistral-large: 128k) and cap them wrongly.
    ("mistral-7b", ModelCapabilities(context_window=32_768)),
    ("mixtral-8x7b", ModelCapabilities(context_window=32_768)),
    ("mistral-large", ModelCapabilities(context_window=131_072)),
    ("devstral", ModelCapabilities(context_window=131_072)),
    # --- anthropic -------------------------------------------------------
    ("claude", ModelCapabilities(context_window=200_000,
                                 reserved_output_token_space=8192,
                                 max_output_tokens=8192)),
    # --- openai ----------------------------------------------------------
    ("gpt-4o", ModelCapabilities(context_window=128_000,
                                 max_output_tokens=16_384)),
    ("gpt-4.1", ModelCapabilities(context_window=1_047_576,
                                  max_output_tokens=32_768)),
    ("gpt-4", ModelCapabilities(context_window=128_000)),
    ("o1", ModelCapabilities(context_window=200_000,
                             supports_system_message=False,
                             max_output_tokens=100_000)),
    ("o3", ModelCapabilities(context_window=200_000,
                             max_output_tokens=100_000)),
    ("o4-mini", ModelCapabilities(context_window=200_000,
                                  max_output_tokens=100_000)),
    # --- google ----------------------------------------------------------
    ("gemini", ModelCapabilities(context_window=1_048_576,
                                 max_output_tokens=8192)),
    ("gemma", ModelCapabilities(context_window=131_072)),
    # --- xai / groq / meta ----------------------------------------------
    ("grok", ModelCapabilities(context_window=131_072)),
    ("llama-3.3", ModelCapabilities(context_window=131_072)),
    ("llama-3", ModelCapabilities(context_window=131_072)),
    ("llama-4", ModelCapabilities(context_window=1_048_576)),
    ("llama", ModelCapabilities(context_window=131_072)),
    # --- moonshot / zai / alibaba ---------------------------------------
    ("kimi-k2", ModelCapabilities(context_window=131_072)),
    ("kimi", ModelCapabilities(context_window=131_072)),
    ("moonshot", ModelCapabilities(context_window=131_072)),
    ("glm-4", ModelCapabilities(context_window=131_072)),
    ("glm", ModelCapabilities(context_window=131_072)),
    # --- local test config ----------------------------------------------
    ("tiny-test", ModelCapabilities(context_window=2_048,
                                    reserved_output_token_space=256,
                                    max_output_tokens=256)),
    ("tiny-moe-test", ModelCapabilities(context_window=2_048,
                                        reserved_output_token_space=256,
                                        max_output_tokens=256)),
)

_DEFAULT = ModelCapabilities(context_window=128_000)


def get_model_capabilities(model_name: str) -> ModelCapabilities:
    lower = model_name.lower()
    for key, caps in _CAPABILITIES:
        if key in lower:
            return caps
    return _DEFAULT


def get_reserved_output_token_space(model_name: str) -> int:
    return get_model_capabilities(model_name).reserved_output_token_space
