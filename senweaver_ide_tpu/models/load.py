"""Pretrained-weight loading: local HF-layout safetensors → stacked pytree.

The north star fine-tunes real checkpoints (Qwen2.5-Coder-1.5B …
DeepSeek-Coder-6.7B, BASELINE configs 3-5; the reference's policy models
live behind provider APIs — ``common/modelCapabilities.ts:300+``). This
module converts a locally-downloaded HuggingFace model directory (zero
egress: files must already be on disk) into the layer-STACKED param pytree
``models/transformer.py`` consumes, and can export back.

Conventions bridged:
- torch ``nn.Linear`` stores (out_features, in_features); our einsum
  weights are (in, out) → every projection transposes.
- Per-layer HF tensors (``model.layers.{i}.…``) stack on a new leading L
  axis (the ``lax.scan``/pipeline axis).
- RoPE: both sides use the half-rotation (rotate_half) layout, so q/k
  projections need NO row permutation (ops/rotary.py matches HF Qwen2/LLaMA).

Supported families: Qwen2/Qwen2.5 (GQA + QKV bias, optionally tied
embeddings), Qwen3 (QK-norm) incl. Qwen3-MoE (``mlp.experts`` layout),
LLaMA-architecture DeepSeek-Coder (MHA, no biases), Llama-3.x (rope
scaling), Mistral (GQA + sliding window), and Mixtral (block-sparse
MoE: ``block_sparse_moe.gate`` router + per-expert w1/w3/w2) — the
same coverage as models/config.py PRESETS.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

import numpy as np

from .config import ModelConfig
from .transformer import Params

__all__ = ["load_hf_params", "export_hf_params", "available_hf_keys"]


def _safetensor_files(model_dir: str) -> List[str]:
    index = os.path.join(model_dir, "model.safetensors.index.json")
    if os.path.exists(index):
        with open(index) as f:
            weight_map = json.load(f)["weight_map"]
        return sorted({os.path.join(model_dir, v)
                       for v in weight_map.values()})
    files = sorted(
        os.path.join(model_dir, f) for f in os.listdir(model_dir)
        if f.endswith(".safetensors"))
    if not files:
        raise FileNotFoundError(
            f"no .safetensors files under {model_dir!r} (expected an "
            f"HF-layout checkpoint directory)")
    return files


def _load_raw(model_dir: str) -> Dict[str, np.ndarray]:
    from safetensors.numpy import load_file

    tensors: Dict[str, np.ndarray] = {}
    for path in _safetensor_files(model_dir):
        tensors.update(load_file(path))
    return tensors


def available_hf_keys(model_dir: str) -> List[str]:
    """Tensor names present in the checkpoint (debugging aid)."""
    return sorted(_load_raw(model_dir))


def _take(raw: Dict[str, np.ndarray], key: str, shape) -> np.ndarray:
    if key not in raw:
        close = [k for k in raw if key.rsplit(".", 2)[-2] in k][:5]
        raise KeyError(f"checkpoint is missing {key!r}; nearby keys: {close}")
    t = raw.pop(key)
    if tuple(t.shape) != tuple(shape):
        raise ValueError(f"{key}: checkpoint shape {tuple(t.shape)} != "
                         f"expected {tuple(shape)} for this ModelConfig")
    return t


# Expert-bank key wiring per HF MoE family: (module base, gate, up, down).
_MOE_LAYOUTS = {
    "mixtral": ("block_sparse_moe", "w1", "w3", "w2"),
    "qwen3": ("mlp", "gate_proj", "up_proj", "down_proj"),
}


def load_hf_params(model_dir: str, config: ModelConfig, *,
                   dtype=None, strict: bool = True) -> Params:
    """Read an HF-layout safetensors dir into the stacked param pytree.

    ``strict`` rejects leftover (unconsumed) checkpoint tensors, which
    catches silently-ignored weights from an architecture mismatch.
    """
    import jax.numpy as jnp

    c = config
    dtype = dtype or c.dtype
    raw = _load_raw(model_dir)
    D, F, L, V = c.hidden_size, c.intermediate_size, c.num_layers, c.vocab_size

    def stacked(fmt: str, shape, transpose: bool) -> np.ndarray:
        per_layer = []
        for i in range(L):
            t = _take(raw, fmt.format(i=i), shape)
            per_layer.append(t.T if transpose else t)
        return np.stack(per_layer)

    p = "model.layers.{i}."
    layers: Dict[str, Any] = {
        "attn_norm": stacked(p + "input_layernorm.weight", (D,), False),
        "wq": stacked(p + "self_attn.q_proj.weight", (c.q_dim, D), True),
        "wk": stacked(p + "self_attn.k_proj.weight", (c.kv_dim, D), True),
        "wv": stacked(p + "self_attn.v_proj.weight", (c.kv_dim, D), True),
        "wo": stacked(p + "self_attn.o_proj.weight", (D, c.q_dim), True),
        "mlp_norm": stacked(p + "post_attention_layernorm.weight", (D,),
                            False),
    }
    if c.num_experts > 0:
        # Two HF MoE layouts, autodetected from the checkpoint keys:
        #   mixtral: block_sparse_moe.gate + experts.N.{w1,w3,w2}
        #   qwen3-moe: mlp.gate + experts.N.{gate,up,down}_proj
        # Router is (E, D) in both; expert matrices (F, D)/(D, F).
        E = c.num_experts
        qwen3_moe = "model.layers.0.mlp.gate.weight" in raw
        base, g_key, u_key, d_key = _MOE_LAYOUTS[
            "qwen3" if qwen3_moe else "mixtral"]
        layers["router"] = stacked(p + base + ".gate.weight", (E, D), True)

        def experts(sub: str, shape) -> np.ndarray:
            per_layer = []
            for i in range(L):
                per_layer.append(np.stack([
                    _take(raw, f"model.layers.{i}.{base}."
                               f"experts.{e}.{sub}.weight", shape).T
                    for e in range(E)]))
            return np.stack(per_layer)          # (L, E, in, out)

        layers["w_gate"] = experts(g_key, (F, D))
        layers["w_up"] = experts(u_key, (F, D))
        layers["w_down"] = experts(d_key, (D, F))
    else:
        layers["w_gate"] = stacked(p + "mlp.gate_proj.weight", (F, D), True)
        layers["w_up"] = stacked(p + "mlp.up_proj.weight", (F, D), True)
        layers["w_down"] = stacked(p + "mlp.down_proj.weight", (D, F), True)
    if c.qkv_bias:
        layers["bq"] = stacked(p + "self_attn.q_proj.bias", (c.q_dim,), False)
        layers["bk"] = stacked(p + "self_attn.k_proj.bias", (c.kv_dim,),
                               False)
        layers["bv"] = stacked(p + "self_attn.v_proj.bias", (c.kv_dim,),
                               False)
    if c.qk_norm:
        layers["q_norm"] = stacked(p + "self_attn.q_norm.weight",
                                   (c.head_dim,), False)
        layers["k_norm"] = stacked(p + "self_attn.k_norm.weight",
                                   (c.head_dim,), False)

    params: Params = {
        "embed": _take(raw, "model.embed_tokens.weight", (V, D)),
        "layers": layers,
        "final_norm": _take(raw, "model.norm.weight", (D,)),
    }
    if not c.tie_word_embeddings:
        # Some tied-embedding exports still materialize lm_head; only
        # consume it when the config expects a separate head.
        params["lm_head"] = _take(raw, "lm_head.weight", (V, D)).T
    else:
        raw.pop("lm_head.weight", None)

    # RoPE inv_freq buffers etc. are derived, not parameters.
    leftover = [k for k in raw if not k.endswith("rotary_emb.inv_freq")]
    if leftover and strict:
        raise ValueError(
            f"{len(leftover)} unconsumed checkpoint tensors (architecture "
            f"mismatch?): {leftover[:8]}")

    import jax

    return jax.tree_util.tree_map(lambda x: jnp.asarray(x, dtype), params)


def export_hf_params(params: Params, config: ModelConfig,
                     out_dir: str) -> str:
    """Write the stacked pytree back to an HF-layout safetensors file —
    round-trip partner of :func:`load_hf_params` (lets a GRPO-tuned policy
    be served by any HF-ecosystem runtime)."""
    from safetensors.numpy import save_file

    from .quantize import is_quantized

    if is_quantized(params):
        # transposing the +/-127 codes without their scales would write a
        # garbage checkpoint that loads cleanly elsewhere
        raise TypeError("export_hf_params received int8-quantized params "
                        "(models/quantize.py is a serving transform); "
                        "export the full-precision train-state params")
    c = config
    if c.num_experts > 0 and c.moe_layout not in _MOE_LAYOUTS:
        raise ValueError(f"unknown moe_layout {c.moe_layout!r}; "
                         f"available: {sorted(_MOE_LAYOUTS)}")
    os.makedirs(out_dir, exist_ok=True)
    lp = params["layers"]

    def t(x):
        # safetensors serializes the raw buffer IGNORING strides, and
        # device_get on TPU can return non-C-contiguous arrays — every
        # tensor must be materialized contiguously before save.
        return np.ascontiguousarray(np.asarray(x))

    def tt(x):  # back to torch's (out, in) layout
        return np.ascontiguousarray(np.asarray(x).T)

    out: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": t(params["embed"]),
        "model.norm.weight": t(params["final_norm"]),
    }
    if "lm_head" in params:
        out["lm_head.weight"] = tt(params["lm_head"])
    for i in range(c.num_layers):
        p = f"model.layers.{i}."
        out[p + "input_layernorm.weight"] = t(lp["attn_norm"][i])
        out[p + "self_attn.q_proj.weight"] = tt(lp["wq"][i])
        out[p + "self_attn.k_proj.weight"] = tt(lp["wk"][i])
        out[p + "self_attn.v_proj.weight"] = tt(lp["wv"][i])
        out[p + "self_attn.o_proj.weight"] = tt(lp["wo"][i])
        out[p + "post_attention_layernorm.weight"] = t(lp["mlp_norm"][i])
        if c.num_experts > 0:
            # layout mirrors the loader's autodetected families
            # (validated once, before the per-layer loop — see below)
            base, g_key, u_key, d_key = _MOE_LAYOUTS[c.moe_layout]
            out[p + base + ".gate.weight"] = tt(lp["router"][i])
            for e in range(c.num_experts):
                ep = p + f"{base}.experts.{e}."
                out[ep + g_key + ".weight"] = tt(lp["w_gate"][i, e])
                out[ep + u_key + ".weight"] = tt(lp["w_up"][i, e])
                out[ep + d_key + ".weight"] = tt(lp["w_down"][i, e])
        else:
            out[p + "mlp.gate_proj.weight"] = tt(lp["w_gate"][i])
            out[p + "mlp.up_proj.weight"] = tt(lp["w_up"][i])
            out[p + "mlp.down_proj.weight"] = tt(lp["w_down"][i])
        if c.qkv_bias:
            out[p + "self_attn.q_proj.bias"] = t(lp["bq"][i])
            out[p + "self_attn.k_proj.bias"] = t(lp["bk"][i])
            out[p + "self_attn.v_proj.bias"] = t(lp["bv"][i])
        if c.qk_norm:
            out[p + "self_attn.q_norm.weight"] = t(lp["q_norm"][i])
            out[p + "self_attn.k_norm.weight"] = t(lp["k_norm"][i])
    path = os.path.join(out_dir, "model.safetensors")
    save_file(out, path)
    return path
