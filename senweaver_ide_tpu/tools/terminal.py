"""Terminal tools: ephemeral + persistent command execution.

Mirrors `browser/terminalToolService.ts` (388 LoC) semantics inside the
rollout sandbox:

- run_command: spawn, stream output, resolve on exit or after
  MAX_TERMINAL_INACTIVE_TIME_S (8 s) of output inactivity → {type:'timeout'}
  (TerminalResolveReason, toolsServiceTypes.ts:8).
- open/run/kill persistent terminals: a long-lived shell per ID; commands
  return after MAX_TERMINAL_BG_COMMAND_TIME_S (5 s) with output-so-far and
  keep running in the background (prompts.ts:29-31 caps).
- Output capped at MAX_TERMINAL_CHARS (100k), later re-capped to
  TERMINAL_OUTPUT_MAX_CHARS (5k) by the stringifier.

Commands run with cwd inside the sandbox; the environment is scrubbed to a
minimal allowlist for reproducibility (SURVEY.md §7 hermeticity).
"""

from __future__ import annotations

import dataclasses
import os
import re
import selectors
import signal
import subprocess
import time
from typing import Dict, Optional

from ..context.token_config import (MAX_TERMINAL_BG_COMMAND_TIME_S,
                                    MAX_TERMINAL_CHARS,
                                    MAX_TERMINAL_INACTIVE_TIME_S)

_ENV_ALLOWLIST = ("PATH", "HOME", "LANG", "TERM", "PYTHONPATH")

# Model-generated shell must not reach the network: rollout rewards depend
# on reproducibility, and an autonomous policy with host network access is
# a safety hazard at scale. Linux user+net namespaces (unshare -r -n) give
# no-network confinement without privileges; probed once per process.
_ISOLATION_PREFIX = ("unshare", "-r", "-n")
_isolation_supported: Optional[bool] = None


def isolation_available() -> bool:
    global _isolation_supported
    if _isolation_supported is None:
        try:
            _isolation_supported = subprocess.run(
                [*_ISOLATION_PREFIX, "true"], capture_output=True,
                timeout=10).returncode == 0
        except Exception:
            _isolation_supported = False
    return _isolation_supported


@dataclasses.dataclass
class CommandResult:
    output: str
    resolve_reason: str          # 'done' | 'timeout' | 'bgtimeout' | 'killed'
    exit_code: Optional[int]
    duration_s: float


def _scrubbed_env() -> Dict[str, str]:
    return {k: os.environ[k] for k in _ENV_ALLOWLIST if k in os.environ}


def _read_until(proc: subprocess.Popen, *, inactive_timeout: float,
                hard_timeout: Optional[float] = None) -> tuple[str, str]:
    """Drain stdout until exit, inactivity timeout, or hard timeout.
    Returns (output, reason). stdout must be in non-blocking mode: a
    backgrounded grandchild can inherit the pipe and keep it open long after
    the shell exits, so every read here must be unable to block."""
    os.set_blocking(proc.stdout.fileno(), False)  # type: ignore[union-attr]
    sel = selectors.DefaultSelector()
    sel.register(proc.stdout, selectors.EVENT_READ)  # type: ignore[arg-type]
    chunks: list[bytes] = []
    total = 0
    start = time.monotonic()
    last_activity = start

    def drain() -> None:
        nonlocal total
        while True:
            data = proc.stdout.read(65536)  # type: ignore[union-attr]
            if not data:
                return
            last = time.monotonic()
            nonlocal last_activity
            last_activity = last
            if total < MAX_TERMINAL_CHARS:
                chunks.append(data)
                total += len(data)

    while True:
        now = time.monotonic()
        if proc.poll() is not None:
            drain()  # non-blocking: grabs whatever is buffered, no more
            return (b"".join(chunks).decode(errors="replace"), "done")
        if hard_timeout is not None and now - start >= hard_timeout:
            return (b"".join(chunks).decode(errors="replace"), "bgtimeout")
        if now - last_activity >= inactive_timeout:
            return (b"".join(chunks).decode(errors="replace"), "timeout")
        if sel.select(timeout=0.1):
            drain()


class TerminalManager:
    """Ephemeral run_command + persistent terminal pool for one sandbox."""

    def __init__(self, cwd: str, *, isolation: str = "auto"):
        """``isolation``: 'auto' = user+net namespaces when the kernel
        allows (else unisolated), 'netns' = require them (raise if
        unavailable), 'none' = plain subprocesses. ``self.isolated``
        reports the outcome — ToolsService denies terminal-class approval
        by default when it is False."""
        self.cwd = cwd
        if isolation == "none":
            self.isolated = False
        elif isolation in ("auto", "netns"):
            self.isolated = isolation_available()
            if isolation == "netns" and not self.isolated:
                raise RuntimeError(
                    "terminal isolation required but user+net namespaces "
                    "are unavailable (unshare -r -n failed)")
        else:
            raise ValueError(f"unknown isolation mode {isolation!r}")
        self._persistent: Dict[str, subprocess.Popen] = {}
        self._next_id = 1
        self._sentinel_n = 0

    def _argv(self, argv: list) -> list:
        return [*_ISOLATION_PREFIX, *argv] if self.isolated else argv

    def run_command(self, command: str, *, cwd: Optional[str] = None,
                    inactive_timeout: float = MAX_TERMINAL_INACTIVE_TIME_S
                    ) -> CommandResult:
        start = time.monotonic()
        proc = subprocess.Popen(
            self._argv(["/bin/sh", "-c", command]), cwd=cwd or self.cwd,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=_scrubbed_env(), start_new_session=True)
        out, reason = _read_until(proc, inactive_timeout=inactive_timeout)
        if reason != "done":
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            proc.wait()
        return CommandResult(output=out[:MAX_TERMINAL_CHARS],
                             resolve_reason=reason,
                             exit_code=proc.returncode if reason == "done"
                             else None,
                             duration_s=time.monotonic() - start)

    # -- persistent terminals ---------------------------------------------
    def open_persistent(self, *, cwd: Optional[str] = None) -> str:
        tid = f"terminal-{self._next_id}"
        self._next_id += 1
        proc = subprocess.Popen(
            self._argv(["/bin/sh"]), cwd=cwd or self.cwd,
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=_scrubbed_env(), start_new_session=True)
        os.set_blocking(proc.stdout.fileno(), False)  # type: ignore
        self._persistent[tid] = proc
        return tid

    def run_persistent(self, terminal_id: str, command: str,
                       *, bg_timeout: float = MAX_TERMINAL_BG_COMMAND_TIME_S
                       ) -> CommandResult:
        proc = self._persistent.get(terminal_id)
        if proc is None or proc.poll() is not None:
            raise KeyError(f"no persistent terminal: {terminal_id}")
        # Discard late output from a previous bgtimeout'd command so it is
        # not misattributed to this one. Bounded: a still-running command
        # that streams output forever must not wedge the drain.
        drain_deadline = time.monotonic() + 0.25
        while (proc.stdout.read(65536)  # type: ignore[union-attr]
               and time.monotonic() < drain_deadline):
            pass
        start = time.monotonic()
        # Sentinel echo so fast commands resolve immediately instead of
        # idling the full bg window (the reference resolves on completion;
        # only still-running commands hit the 5 s return-and-continue path).
        self._sentinel_n += 1
        sentinel = f"__SW_DONE_{self._sentinel_n}__"
        proc.stdin.write(  # type: ignore[union-attr]
            (command + f"\nprintf '%s\\n' {sentinel}\n").encode())
        proc.stdin.flush()  # type: ignore[union-attr]
        buf = b""
        done = False
        while time.monotonic() - start < bg_timeout:
            data = proc.stdout.read(65536)  # type: ignore[union-attr]
            if data:
                buf += data
                if sentinel.encode() in buf:   # exact CURRENT sentinel only
                    done = True
                    break
            else:
                time.sleep(0.02)
        if done:
            buf = buf[:buf.find(sentinel.encode())]
        # Anything up to a LOWER-numbered sentinel is late output of a
        # previously bgtimeout'd command that escaped the pre-drain window —
        # discard it rather than misattribute it to this command.
        stale = None
        for m in re.finditer(rb"__SW_DONE_(\d+)__\n?", buf):
            if int(m.group(1)) < self._sentinel_n:
                stale = m
        if stale is not None:
            buf = buf[stale.end():]
        out = re.sub(r"__SW_DONE_\d+__\n?", "",
                     buf.decode(errors="replace"))
        return CommandResult(
            output=out[:MAX_TERMINAL_CHARS],
            resolve_reason="done" if done else "bgtimeout",
            exit_code=None,
            duration_s=time.monotonic() - start)

    def kill_persistent(self, terminal_id: str) -> None:
        proc = self._persistent.pop(terminal_id, None)
        if proc is None:
            raise KeyError(f"no persistent terminal: {terminal_id}")
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.wait()

    def close(self) -> None:
        for tid in list(self._persistent):
            self.kill_persistent(tid)
