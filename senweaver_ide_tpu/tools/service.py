"""ToolsService: validate → approve → execute → stringify.

The sandboxed analogue of `browser/toolsService.ts` (3947 LoC):
- ``validate_params`` coerces/validates raw (string-valued) model params
  per tool (validateParams, toolsService.ts:1138; error style :860-934).
- ``call_tool`` dispatches with the approval gate collapsed to policy flags
  (auto-approve map, chatThreadService.ts:984-992 + settings key
  autoApprove) — a denied call returns a ToolDeniedError result, which the
  trace records as a failed tool call (reward dim 3/4 inputs).
- ``string_of_result`` renders results for the model under the
  TOOL_RESULT_OPTIMIZATION caps (stringOfResult, toolsService.ts:3265;
  caps tokenOptimizationConfig.ts:148-170).

Network/document tools are registered (full API surface) but their backends
— the reference's Node sidecar servers (start*.cjs, SURVEY §2.5) — are
external processes; handlers can be plugged in via ``register_handler``.
Unplugged, they fail deterministically as unavailable, keeping rollouts
hermetic.
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, List, Optional

from ..context.token_config import TOOL_RESULT_OPTIMIZATION, cap_text
from .registry import TOOL_SCHEMAS
from .sandbox import Workspace
from .search_replace import apply_search_replace
from .terminal import TerminalManager
from .types import (APPROVAL_TYPE_OF_TOOL, ApprovalType, ToolDeniedError,
                    ToolResult, ToolUnavailableError, ToolValidationError)

_TRUTHY = {"true", "1", "yes", "y"}


def _as_bool(v: Any, default: bool = False) -> bool:
    if v is None or v == "":
        return default
    if isinstance(v, bool):
        return v
    return str(v).strip().lower() in _TRUTHY


def _as_int(v: Any, name: str, default: Optional[int] = None,
            minimum: Optional[int] = None) -> Optional[int]:
    if v is None or v == "":
        return default
    try:
        i = int(str(v).strip())
    except ValueError:
        raise ToolValidationError(
            f"param {name} must be an integer, got: {v!r}")
    if minimum is not None and i < minimum:
        raise ToolValidationError(f"param {name} must be >= {minimum}: {i}")
    return i


def _req_str(params: Dict[str, Any], name: str) -> str:
    v = params.get(name)
    if v is None:
        raise ToolValidationError(
            f"required param {name} was not provided")
    if not isinstance(v, str):
        raise ToolValidationError(
            f"param {name} must be a string, got {type(v).__name__}: "
            f"{json.dumps(v, default=str)[:100]}")
    if not v.strip():
        raise ToolValidationError(f"param {name} must not be empty")
    return v


class ToolsService:
    """One instance per rollout sandbox."""

    def __init__(self, workspace: Workspace, *,
                 auto_approve: Optional[Dict[ApprovalType, bool]] = None,
                 terminal_isolation: str = "auto"):
        self.workspace = workspace
        self.terminals = TerminalManager(str(workspace.root),
                                         isolation=terminal_isolation)
        # Rollout policy defaults: file/edit tools auto-approve (they are
        # sandbox-confined), but terminal-class tools auto-approve ONLY
        # when the shell is namespace-isolated (no network) — an
        # unconfined model-generated shell breaks both hermeticity and
        # safety. Callers may override explicitly via ``auto_approve``.
        self.auto_approve = {t: True for t in ApprovalType}
        self.auto_approve[ApprovalType.TERMINAL] = self.terminals.isolated
        if auto_approve:
            self.auto_approve.update(auto_approve)
        self._handlers: Dict[str, Callable[[Dict[str, Any]], Any]] = {}
        self._lint_provider: Optional[Callable[[str], List[str]]] = None
        self._pre_execute_hooks: List[
            Callable[[str, Dict[str, Any]], None]] = []
        self.call_log: List[ToolResult] = []

    # -- extension points --------------------------------------------------
    def register_handler(self, tool: str,
                         fn: Callable[[Dict[str, Any]], Any]) -> None:
        """Plug in a backend for a gated tool (network/document/agents) —
        the analogue of the sidecar servers + subagent/skill services."""
        if tool not in TOOL_SCHEMAS:
            raise KeyError(f"unknown tool: {tool}")
        self._handlers[tool] = fn

    def set_lint_provider(self, fn: Callable[[str], List[str]]) -> None:
        self._lint_provider = fn

    def add_pre_execute_hook(
            self, fn: Callable[[str, Dict[str, Any]], None]) -> None:
        """Called with (tool, validated_params) after validation+approval,
        before execution — e.g. before-edit file snapshots. Hook errors
        are swallowed (observers must not fail the tool call)."""
        self._pre_execute_hooks.append(fn)

    # -- validation --------------------------------------------------------
    def validate_params(self, tool: str,
                        raw: Dict[str, Any]) -> Dict[str, Any]:
        schema = TOOL_SCHEMAS.get(tool)
        if schema is None:
            raise ToolValidationError(f"unknown tool name: {tool}")
        # Per-tool coercion into typed params; required params are enforced
        # by the _req_str calls inside each branch.
        p: Dict[str, Any] = {}
        get = raw.get
        if tool == "read_file":
            p["uri"] = _req_str(raw, "uri")
            p["start_line"] = _as_int(get("start_line"), "start_line",
                                      minimum=1)
            p["end_line"] = _as_int(get("end_line"), "end_line", minimum=1)
            p["page_number"] = _as_int(get("page_number"), "page_number", 1,
                                       minimum=1)
        elif tool == "ls_dir":
            p["uri"] = str(get("uri") or "")
            p["page_number"] = _as_int(get("page_number"), "page_number", 1,
                                       minimum=1)
        elif tool == "get_dir_tree":
            p["uri"] = _req_str(raw, "uri")
        elif tool == "search_pathnames_only":
            p["query"] = _req_str(raw, "query")
            p["include_pattern"] = get("include_pattern") or None
            p["page_number"] = _as_int(get("page_number"), "page_number", 1,
                                       minimum=1)
        elif tool == "search_for_files":
            p["query"] = _req_str(raw, "query")
            p["is_regex"] = _as_bool(get("is_regex"))
            p["search_in_folder"] = get("search_in_folder") or None
            p["page_number"] = _as_int(get("page_number"), "page_number", 1,
                                       minimum=1)
        elif tool == "search_in_file":
            p["uri"] = _req_str(raw, "uri")
            p["query"] = _req_str(raw, "query")
            p["is_regex"] = _as_bool(get("is_regex"))
        elif tool == "read_lint_errors":
            p["uri"] = _req_str(raw, "uri")
        elif tool == "create_file_or_folder":
            p["uri"] = _req_str(raw, "uri")
        elif tool == "delete_file_or_folder":
            p["uri"] = _req_str(raw, "uri")
            p["is_recursive"] = _as_bool(get("is_recursive"))
        elif tool == "edit_file":
            p["uri"] = _req_str(raw, "uri")
            blocks = _req_str(raw, "search_replace_blocks")
            if "<<<<<<< ORIGINAL" not in blocks:
                preview = blocks[:100]
                raise ToolValidationError(
                    'search_replace_blocks must contain "<<<<<<< ORIGINAL" '
                    f'markers. You provided: "{preview}...". To replace an '
                    "entire file use rewrite_file instead.")
            p["search_replace_blocks"] = blocks
        elif tool == "rewrite_file":
            p["uri"] = _req_str(raw, "uri")
            nc = raw.get("new_content")
            if nc is None or not isinstance(nc, str):
                raise ToolValidationError(
                    "required param new_content must be a string")
            p["new_content"] = nc
        elif tool == "run_command":
            p["command"] = _req_str(raw, "command")
            p["cwd"] = get("cwd") or None
        elif tool == "open_persistent_terminal":
            p["cwd"] = get("cwd") or None
        elif tool == "run_persistent_command":
            p["command"] = _req_str(raw, "command")
            p["persistent_terminal_id"] = _req_str(
                raw, "persistent_terminal_id")
        elif tool == "kill_persistent_terminal":
            p["persistent_terminal_id"] = _req_str(
                raw, "persistent_terminal_id")
        elif tool in ("open_browser", "fetch_url", "api_request"):
            url = _req_str(raw, "url")
            if not url.startswith(("http://", "https://")):
                raise ToolValidationError(
                    f"Invalid URL: must start with http:// or https://. "
                    f"Got: {url}")
            p = dict(raw)
        elif tool == "web_search":
            p["query"] = _req_str(raw, "query")
            mr = _as_int(get("max_results"), "max_results", 10, minimum=1)
            if mr is not None and mr > 50:
                raise ToolValidationError(
                    f"max_results must be between 1 and 50. Got: {mr}")
            p["max_results"] = mr
        elif tool in ("analyze_image", "screenshot_to_code", "read_document",
                      "edit_document", "create_document", "pdf_operation",
                      "document_convert", "document_merge",
                      "document_extract"):
            for r in TOOL_SCHEMAS[tool].required:
                _req_str(raw, r)
            p = dict(raw)
        elif tool == "spawn_subagent":
            p["agent_type"] = _req_str(raw, "agent_type")
            p["task"] = _req_str(raw, "task")
            p["context"] = get("context") or ""
        elif tool == "edit_agent":
            p["uri"] = _req_str(raw, "uri")
            p["instructions"] = _req_str(raw, "instructions")
            p["mode"] = get("mode") or "edit"
        elif tool == "skill":
            p["name"] = _req_str(raw, "name")
        else:  # pragma: no cover
            p = dict(raw)
        return p

    # -- execution ---------------------------------------------------------
    def call_tool(self, tool: str, raw_params: Dict[str, Any]) -> ToolResult:
        started = time.time()
        t0 = time.monotonic()
        try:
            params = self.validate_params(tool, raw_params)
            approval = APPROVAL_TYPE_OF_TOOL.get(tool)
            if approval is not None and not self.auto_approve.get(approval,
                                                                  False):
                raise ToolDeniedError(
                    f"tool {tool} requires '{approval.value}' approval, "
                    "which this rollout policy denies")
            for hook in self._pre_execute_hooks:
                try:
                    hook(tool, params)
                except Exception:
                    pass
            result = self._execute(tool, params)
            tr = ToolResult(tool=tool, params=params, result=result,
                            started_at=started,
                            duration_ms=(time.monotonic() - t0) * 1e3)
        except Exception as e:
            tr = ToolResult(tool=tool, params=dict(raw_params),
                            error=f"{type(e).__name__}: {e}",
                            started_at=started,
                            duration_ms=(time.monotonic() - t0) * 1e3)
        self.call_log.append(tr)
        return tr

    def _execute(self, tool: str, p: Dict[str, Any]) -> Any:
        ws = self.workspace
        if tool in self._handlers:
            return self._handlers[tool](p)
        if tool == "read_file":
            text, more = ws.read_file(p["uri"], start_line=p["start_line"],
                                      end_line=p["end_line"],
                                      page_number=p["page_number"])
            return {"contents": text, "has_next_page": more}
        if tool == "ls_dir":
            children, more = ws.ls(p["uri"], page_number=p["page_number"])
            return {"children": children, "has_next_page": more}
        if tool == "get_dir_tree":
            return {"tree": ws.dir_tree(p["uri"])}
        if tool == "search_pathnames_only":
            hits, more = ws.search_pathnames(
                p["query"], include_pattern=p["include_pattern"],
                page_number=p["page_number"])
            return {"uris": hits, "has_next_page": more}
        if tool == "search_for_files":
            hits, more = ws.search_files(
                p["query"], is_regex=p["is_regex"],
                search_in_folder=p["search_in_folder"],
                page_number=p["page_number"])
            return {"uris": hits, "has_next_page": more}
        if tool == "search_in_file":
            return {"lines": ws.search_in_file(p["uri"], p["query"],
                                               is_regex=p["is_regex"])}
        if tool == "read_lint_errors":
            if self._lint_provider is None:
                return {"lint_errors": []}
            return {"lint_errors": self._lint_provider(p["uri"])}
        if tool == "create_file_or_folder":
            path = ws.create(p["uri"])
            return {"created": ws.display(path)}
        if tool == "delete_file_or_folder":
            ws.delete(p["uri"], is_recursive=p["is_recursive"])
            return {"deleted": p["uri"]}
        if tool == "edit_file":
            text = ws.read_text(p["uri"])
            new_text = apply_search_replace(text, p["search_replace_blocks"])
            ws.write_file(p["uri"], new_text)
            old_lines, new_lines = text.count("\n"), new_text.count("\n")
            return {"applied": p["uri"],
                    "lines_added": max(0, new_lines - old_lines),
                    "lines_removed": max(0, old_lines - new_lines)}
        if tool == "rewrite_file":
            existed = True
            try:
                ws.read_text(p["uri"])
            except FileNotFoundError:
                existed = False
            ws.write_file(p["uri"], p["new_content"])
            return {"rewrote": p["uri"], "is_new_file": not existed}
        if tool == "run_command":
            cwd = str(ws.resolve(p["cwd"])) if p["cwd"] else None
            r = self.terminals.run_command(p["command"], cwd=cwd)
            return {"output": r.output, "resolve_reason": r.resolve_reason,
                    "exit_code": r.exit_code,
                    "duration_s": round(r.duration_s, 3)}
        if tool == "open_persistent_terminal":
            cwd = str(ws.resolve(p["cwd"])) if p["cwd"] else None
            return {"persistent_terminal_id":
                    self.terminals.open_persistent(cwd=cwd)}
        if tool == "run_persistent_command":
            r = self.terminals.run_persistent(p["persistent_terminal_id"],
                                              p["command"])
            return {"output": r.output, "resolve_reason": r.resolve_reason}
        if tool == "kill_persistent_terminal":
            self.terminals.kill_persistent(p["persistent_terminal_id"])
            return {"killed": p["persistent_terminal_id"]}
        # Gated tools without a registered handler:
        raise ToolUnavailableError(
            f"tool {tool} has no backend in this hermetic sandbox "
            "(register a handler to enable it)")

    # -- stringification ---------------------------------------------------
    def string_of_result(self, tr: ToolResult) -> str:
        """Render a ToolResult for the model, applying per-tool caps."""
        caps = TOOL_RESULT_OPTIMIZATION
        if tr.error is not None:
            return f"Error calling {tr.tool}: {tr.error}"
        r = tr.result
        if tr.tool == "read_file":
            body = cap_text(r["contents"], caps["FILE_READ_MAX_CHARS"])
            more = "\n(more pages available)" if r["has_next_page"] else ""
            return body + more
        if tr.tool == "ls_dir":
            items = r["children"][:caps["LS_DIR_MAX_ITEMS"]]
            lines = [name for name, _ in items]
            extra = len(r["children"]) - len(items)
            if extra > 0 or r["has_next_page"]:
                lines.append(f"... ({extra} more entries; paginate for the "
                             "rest)")
            return "\n".join(lines) if lines else "(empty folder)"
        if tr.tool == "get_dir_tree":
            return cap_text(r["tree"], caps["MAX_TOOL_RESULT_CHARS"])
        if tr.tool in ("search_pathnames_only", "search_for_files"):
            hits = r["uris"][:caps["SEARCH_RESULT_MAX_MATCHES"]]
            out = "\n".join(hits) if hits else "(no matches)"
            extra = len(r["uris"]) - len(hits)
            if extra > 0 or r["has_next_page"]:
                out += f"\n... ({extra} more matches; paginate or narrow " \
                       "the query)"
            return out
        if tr.tool == "search_in_file":
            return ("match at lines: "
                    + ", ".join(map(str, r["lines"]))) if r["lines"] \
                else "(no matches)"
        if tr.tool == "read_lint_errors":
            errs = r["lint_errors"]
            return "\n".join(errs) if errs else "(no lint errors)"
        if tr.tool in ("run_command", "run_persistent_command"):
            out = cap_text(r["output"], caps["TERMINAL_OUTPUT_MAX_CHARS"])
            tail = ""
            if r["resolve_reason"] == "timeout":
                tail = "\n(command timed out after 8s of inactivity)"
            elif r["resolve_reason"] == "bgtimeout":
                tail = "\n(command still running in background)"
            elif r.get("exit_code") is not None:
                tail = f"\n(exit code {r['exit_code']})"
            return (out or "(no output)") + tail
        if tr.tool == "web_search":
            return cap_text(str(r), caps["WEB_SEARCH_MAX_CHARS"])
        if tr.tool == "fetch_url":
            return cap_text(str(r), caps["FETCH_URL_MAX_CHARS"])
        if isinstance(r, str):
            return cap_text(r, caps["MAX_TOOL_RESULT_CHARS"])
        return cap_text(json.dumps(r, default=str),
                        caps["MAX_TOOL_RESULT_CHARS"])

    def close(self) -> None:
        self.terminals.close()
