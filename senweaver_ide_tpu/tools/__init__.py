"""Hermetic tool sandbox for agent rollouts.

The TPU-build analogue of the reference's tool stack
(`browser/toolsService.ts` + `common/toolsServiceTypes.ts` +
`prompt/prompts.ts` builtinTools): same 31-tool API surface, validation and
result-cap semantics, confined to a reproducible sandbox so rollout rewards
are valid (SURVEY.md §7).
"""

from .documents import (DocumentServices, docx_write, image_info,
                        minipdf_extract_pages, minipdf_write, pptx_text,
                        pptx_write, xlsx_write)
from .registry import TOOL_SCHEMAS, ToolSchema
from .sandbox import SandboxViolation, Workspace
from .search_replace import (DIVIDER, FINAL, ORIGINAL, MalformedBlocksError,
                             SearchNotFoundError, SearchReplaceBlock,
                             apply_blocks, apply_search_replace,
                             extract_blocks)
from .service import ToolsService
from .terminal import CommandResult, TerminalManager
from .types import (APPROVAL_TYPE_OF_TOOL, BUILTIN_TOOL_NAMES, ApprovalType,
                    ToolDeniedError, ToolResult, ToolUnavailableError,
                    ToolValidationError)

__all__ = [
    "DocumentServices", "docx_write", "image_info",
    "minipdf_extract_pages", "minipdf_write", "pptx_text", "pptx_write",
    "xlsx_write",
    "TOOL_SCHEMAS", "ToolSchema", "SandboxViolation", "Workspace",
    "ORIGINAL", "DIVIDER", "FINAL", "MalformedBlocksError",
    "SearchNotFoundError", "SearchReplaceBlock", "apply_blocks",
    "apply_search_replace", "extract_blocks", "ToolsService",
    "CommandResult", "TerminalManager", "APPROVAL_TYPE_OF_TOOL",
    "BUILTIN_TOOL_NAMES", "ApprovalType", "ToolDeniedError", "ToolResult",
    "ToolUnavailableError", "ToolValidationError",
]
