"""Hermetic workspace sandbox for rollout tool calls.

The reference's tools operate on the user's real workspace through VS Code's
IFileService/ISearchService (toolsService.ts). For RL rollouts the reward's
validity depends on reproducibility (SURVEY.md §7 "Agent-loop hermeticity"),
so the TPU build confines every file tool to a sandbox root: paths are
resolved, normalized, and rejected if they escape the root. Semantics of the
individual operations mirror the reference tools:

- folder-vs-file creation by trailing slash (prompts.ts create_file_or_folder
  description; toolsService.ts callTool['create_file_or_folder'])
- recursive delete flag (delete_file_or_folder)
- paginated reads: MAX_FILE_CHARS_PAGE chars/page (prompts.ts:25)
- ls pagination: MAX_CHILDREN_URIS_PAGE entries/page (prompts.ts:26)
- bounded dir tree (directoryStrService.ts caps, prompts.ts:19-22)
"""

from __future__ import annotations

import fnmatch
import os
import re
import shutil
from pathlib import Path
from typing import Iterator, List, Optional, Tuple

from ..context.token_config import (DIRECTORY_OPTIMIZATION,
                                    MAX_CHILDREN_URIS_PAGE,
                                    MAX_FILE_CHARS_PAGE)

# Directories never worth walking (reference search relies on ripgrep's
# default ignores; we approximate with a fixed skip list).
_SKIP_DIRS = {".git", "node_modules", "__pycache__", ".venv", "venv",
              ".cache", ".mypy_cache", ".pytest_cache", "dist", "build"}


class SandboxViolation(PermissionError):
    pass


class Workspace:
    """A rooted, escape-proof view of one rollout's filesystem."""

    def __init__(self, root: str | os.PathLike):
        self.root = Path(root).resolve()
        self.root.mkdir(parents=True, exist_ok=True)

    # -- path resolution ---------------------------------------------------
    def resolve(self, path: str | os.PathLike) -> Path:
        """Resolve a model-provided path inside the sandbox root.

        Absolute paths are re-rooted (the model sees sandbox-absolute paths);
        anything resolving outside the root raises SandboxViolation.
        """
        p = str(path).strip()
        p = re.sub(r"<[^>]*>", "", p).strip()  # XML-tag cleanup, cf.
        # toolsService.ts:884-894 (URI cleaning of stray tags)
        if not p:
            raise SandboxViolation("empty path")
        candidate = Path(p)
        if candidate.is_absolute():
            try:
                rel = candidate.resolve().relative_to(self.root)
                candidate = self.root / rel
            except ValueError:
                # Re-root: /foo/bar → <root>/foo/bar
                candidate = self.root / p.lstrip("/")
        else:
            candidate = self.root / candidate
        # Full non-strict resolution: follows symlinks INCLUDING a dangling
        # final component (exists() is False for those, so a parent-only
        # resolve would let `ln -s /etc/target x` + write_file(x) create a
        # file outside the root).
        resolved = candidate.resolve(strict=False)
        if resolved != self.root and self.root not in resolved.parents:
            raise SandboxViolation(f"path escapes sandbox: {path}")
        return resolved

    def display(self, p: Path) -> str:
        """Sandbox-absolute display path (what the model sees)."""
        try:
            return "/" + str(p.relative_to(self.root))
        except ValueError:
            return str(p)

    # -- file ops ----------------------------------------------------------
    def read_text(self, path: str) -> str:
        """Full, unpaginated file contents (for edits and in-file search —
        pagination is a presentation concern only; editing through a page
        window would silently truncate the file)."""
        p = self.resolve(path)
        if not p.is_file():
            raise FileNotFoundError(f"file does not exist: {path}")
        return p.read_text(errors="replace")

    def read_file(self, path: str, *, start_line: Optional[int] = None,
                  end_line: Optional[int] = None,
                  page_number: int = 1) -> Tuple[str, bool]:
        """Read file contents; returns (text, has_next_page). Line window
        then char pagination, mirroring read_file (toolsService.ts)."""
        p = self.resolve(path)
        if not p.is_file():
            raise FileNotFoundError(f"file does not exist: {path}")
        text = p.read_text(errors="replace")
        if start_line is not None or end_line is not None:
            lines = text.splitlines(keepends=True)
            s = (start_line or 1) - 1
            e = end_line if end_line is not None else len(lines)
            text = "".join(lines[s:e])
        start = (page_number - 1) * MAX_FILE_CHARS_PAGE
        page = text[start:start + MAX_FILE_CHARS_PAGE]
        return page, len(text) > start + MAX_FILE_CHARS_PAGE

    def write_file(self, path: str, content: str) -> Path:
        p = self.resolve(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(content)
        return p

    def create(self, path: str, *, is_folder: Optional[bool] = None) -> Path:
        # Trailing slash ⇒ folder (prompts.ts create_file_or_folder contract).
        if is_folder is None:
            is_folder = str(path).rstrip().endswith("/")
        p = self.resolve(path)
        if is_folder:
            p.mkdir(parents=True, exist_ok=True)
        else:
            p.parent.mkdir(parents=True, exist_ok=True)
            if not p.exists():
                p.write_text("")
        return p

    def delete(self, path: str, *, is_recursive: bool = False) -> None:
        p = self.resolve(path)
        if p == self.root:
            raise SandboxViolation("refusing to delete sandbox root")
        if p.is_dir():
            if is_recursive:
                shutil.rmtree(p)
            else:
                p.rmdir()
        elif p.exists():
            p.unlink()
        else:
            raise FileNotFoundError(f"path does not exist: {path}")

    # -- listing / tree ----------------------------------------------------
    def ls(self, path: str = "", *, page_number: int = 1
           ) -> Tuple[List[Tuple[str, bool]], bool]:
        """List (name, is_dir) children, paginated at
        MAX_CHILDREN_URIS_PAGE."""
        p = self.resolve(path) if path else self.root
        if not p.is_dir():
            raise NotADirectoryError(f"not a folder: {path}")
        entries = sorted(p.iterdir(),
                         key=lambda c: (not c.is_dir(), c.name.lower()))
        start = (page_number - 1) * MAX_CHILDREN_URIS_PAGE
        window = entries[start:start + MAX_CHILDREN_URIS_PAGE]
        return ([(c.name + ("/" if c.is_dir() else ""), c.is_dir())
                 for c in window],
                len(entries) > start + MAX_CHILDREN_URIS_PAGE)

    def dir_tree(self, path: str = "", *,
                 max_chars: int = DIRECTORY_OPTIMIZATION[
                     "MAX_DIRSTR_CHARS_TOTAL_TOOL"],
                 max_depth: int = DIRECTORY_OPTIMIZATION["MAX_DEPTH"]) -> str:
        """Bounded tree diagram (get_dir_tree / directoryStrService.ts)."""
        p = self.resolve(path) if path else self.root
        lines = [self.display(p) + "/"]
        total = len(lines[0])

        def walk(d: Path, prefix: str, depth: int) -> bool:
            nonlocal total
            if depth > max_depth:
                return True
            try:
                children = sorted(
                    (c for c in d.iterdir() if c.name not in _SKIP_DIRS),
                    key=lambda c: (not c.is_dir(), c.name.lower()))
            except PermissionError:
                return True
            for i, c in enumerate(children):
                connector = "└── " if i == len(children) - 1 else "├── "
                line = prefix + connector + c.name + ("/" if c.is_dir() else "")
                total += len(line) + 1
                if total > max_chars:
                    lines.append(prefix + "… (truncated)")
                    return False
                lines.append(line)
                if c.is_dir():
                    ext = "    " if i == len(children) - 1 else "│   "
                    if not walk(c, prefix + ext, depth + 1):
                        return False
            return True

        walk(p, "", 1)
        return "\n".join(lines)

    # -- search ------------------------------------------------------------
    def _walk_files(self, base: Optional[Path] = None) -> Iterator[Path]:
        base = base or self.root
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
            for f in filenames:
                yield Path(dirpath) / f

    def search_pathnames(self, query: str, *,
                         include_pattern: Optional[str] = None,
                         page_number: int = 1,
                         page_size: int = MAX_CHILDREN_URIS_PAGE
                         ) -> Tuple[List[str], bool]:
        """Filename substring/glob match (search_pathnames_only)."""
        q = query.lower()
        hits = []
        for f in self._walk_files():
            rel = self.display(f)
            if include_pattern and not fnmatch.fnmatch(rel, include_pattern):
                continue
            if q in rel.lower() or fnmatch.fnmatch(rel.lower(), q):
                hits.append(rel)
        hits.sort()
        start = (page_number - 1) * page_size
        return hits[start:start + page_size], len(hits) > start + page_size

    def search_files(self, query: str, *, is_regex: bool = False,
                     search_in_folder: Optional[str] = None,
                     page_number: int = 1, page_size: int = 50
                     ) -> Tuple[List[str], bool]:
        """Content search returning matching file paths (search_for_files)."""
        base = self.resolve(search_in_folder) if search_in_folder else None
        pat = re.compile(query) if is_regex else None
        hits = []
        for f in self._walk_files(base):
            try:
                text = f.read_text(errors="replace")
            except (OSError, UnicodeError):
                continue
            if (pat.search(text) if pat else query in text):
                hits.append(self.display(f))
        hits.sort()
        start = (page_number - 1) * page_size
        return hits[start:start + page_size], len(hits) > start + page_size

    def search_lines(self, pattern: str, *,
                     base: Optional[str] = None
                     ) -> Iterator[Tuple[str, int, str]]:
        """One-pass workspace grep: yields (display_path, 1-based line,
        line text) for every line matching the regex — each file read
        once, for callers that need all matches across the tree (edit
        prediction) without N separate walks."""
        pat = re.compile(pattern)
        root = self.resolve(base) if base else None
        for f in self._walk_files(root):
            try:
                text = f.read_text(errors="replace")
            except (OSError, UnicodeError):
                continue
            display = self.display(f)
            for i, line in enumerate(text.split("\n"), start=1):
                if pat.search(line):
                    yield display, i, line

    def search_in_file(self, path: str, query: str, *,
                       is_regex: bool = False) -> List[int]:
        """1-based start line numbers where the query matches
        (search_in_file, prompts.ts)."""
        text = self.read_text(path)
        pat = re.compile(query) if is_regex else None
        out = []
        for i, line in enumerate(text.splitlines(), start=1):
            if (pat.search(line) if pat else query in line):
                out.append(i)
        return out
