"""Document tool family: create/edit/convert/merge/extract + pdf ops.

In-process counterparts of the reference's document sidecar servers
(``browser/startDocumentReaderServer.cjs`` 3793 LoC and friends —
SURVEY.md §2.5/L8), which expose edit_document / create_document /
pdf_operation / document_convert / document_merge / document_extract /
open_browser / analyze_image / screenshot_to_code over localhost HTTP.
Here they are hermetic stdlib-only handlers on ToolsService:

- Office formats are handled at the zip+XML level (no binary deps):
  minimal-but-valid .docx/.xlsx/.pptx writers whose output round-trips
  through the matching extractors in ``sidecars.py``/this module.
- PDFs use an in-tree mini writer (uncompressed text objects) and an
  extractor that also inflates FlateDecode streams, so text extraction
  works for our own output and for many simple foreign PDFs.
- open_browser is a fetch-backed page session (no real browser in the
  sandbox); analyze_image parses image headers in-process and routes
  semantic analysis to a pluggable vision callable, which
  screenshot_to_code requires outright (reference: vision sidecar).
"""

from __future__ import annotations

import base64
import csv
import html as _html
import io
import json
import re
import struct
import time
import zipfile
import zlib
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

from .sandbox import Workspace
from .types import ToolUnavailableError

TEXT_SUFFIXES = (".txt", ".md", ".markdown", ".rst", ".log", ".html",
                 ".htm", ".csv", ".json", "")

# vision_fn(image_bytes, prompt) -> str
VisionFn = Callable[[bytes, str], str]


# ---------------------------------------------------------------------------
# Mini-PDF: writer + extractor
# ---------------------------------------------------------------------------

def _pdf_escape(s: str) -> str:
    s = s.replace("\\", r"\\").replace("(", r"\(").replace(")", r"\)")
    return s.encode("latin-1", errors="replace").decode("latin-1")


def minipdf_write(pages: List[List[str]]) -> bytes:
    """Serialize pages of text lines as a minimal valid PDF-1.4.

    One content stream per page: Helvetica 11pt, 14pt leading, US-Letter.
    Streams are uncompressed so the extractor (and any text tool) can
    read them back.
    """
    if not pages:
        pages = [[""]]
    objs: List[bytes] = []           # 1-indexed PDF objects, in order
    n_pages = len(pages)
    font_num = 3 + 2 * n_pages
    kids = " ".join(f"{3 + 2 * i} 0 R" for i in range(n_pages))
    objs.append(b"<< /Type /Catalog /Pages 2 0 R >>")
    objs.append(f"<< /Type /Pages /Kids [{kids}] /Count {n_pages} >>"
                .encode())
    for i, lines in enumerate(pages):
        page_num, content_num = 3 + 2 * i, 4 + 2 * i
        objs.append(
            f"<< /Type /Page /Parent 2 0 R /MediaBox [0 0 612 792] "
            f"/Contents {content_num} 0 R /Resources << /Font "
            f"<< /F1 {font_num} 0 R >> >> >>".encode())
        body = ["BT /F1 11 Tf 14 TL 72 720 Td"]
        for j, line in enumerate(lines):
            if j:
                body.append("T*")
            body.append(f"({_pdf_escape(line)}) Tj")
        body.append("ET")
        stream = "\n".join(body).encode("latin-1", errors="replace")
        objs.append(b"<< /Length " + str(len(stream)).encode()
                    + b" >>\nstream\n" + stream + b"\nendstream")
    objs.append(b"<< /Type /Font /Subtype /Type1 /BaseFont /Helvetica >>")

    out = io.BytesIO()
    out.write(b"%PDF-1.4\n")
    offsets = [0]
    for i, obj in enumerate(objs, start=1):
        offsets.append(out.tell())
        out.write(f"{i} 0 obj\n".encode() + obj + b"\nendobj\n")
    xref_at = out.tell()
    out.write(f"xref\n0 {len(objs) + 1}\n".encode())
    out.write(b"0000000000 65535 f \n")
    for off in offsets[1:]:
        out.write(f"{off:010d} 00000 n \n".encode())
    out.write(f"trailer\n<< /Size {len(objs) + 1} /Root 1 0 R >>\n"
              f"startxref\n{xref_at}\n%%EOF\n".encode())
    return out.getvalue()


def _pdf_unescape(s: str) -> str:
    return re.sub(r"\\([()\\])", r"\1", s)


def _stream_text(stream: bytes) -> str:
    """Text-show operators (Tj and TJ arrays) from one content stream."""
    try:
        text = stream.decode("latin-1")
    except UnicodeDecodeError:
        return ""
    parts: List[str] = []
    # Walk ops in order so Tj and T* interleave correctly.
    for m in re.finditer(
            r"\(((?:[^()\\]|\\.)*)\)\s*Tj"            # (..) Tj
            r"|\[((?:[^\]\\]|\\.)*)\]\s*TJ"           # [..] TJ
            r"|T\*|\bTd\b|\bTD\b", text):
        if m.group(0) in ("T*",) or m.group(0).endswith(("Td", "TD")):
            parts.append("\n")
        elif m.group(1) is not None:
            parts.append(_pdf_unescape(m.group(1)))
        elif m.group(2) is not None:
            parts.extend(_pdf_unescape(s)
                         for s in re.findall(r"\(((?:[^()\\]|\\.)*)\)",
                                             m.group(2)))
    joined = "".join(parts)
    return re.sub(r"\n{3,}", "\n\n", joined).strip("\n")


def minipdf_extract_pages(data: bytes) -> List[str]:
    """Per-content-stream text; inflates FlateDecode streams when found.

    Works on this module's own output and on simple foreign PDFs whose
    text sits in (possibly deflated) Tj/TJ operators. Raises ValueError
    when no text could be recovered from a real PDF.
    """
    if not data.startswith(b"%PDF"):
        raise ValueError("not a PDF file")
    pages: List[str] = []
    for m in re.finditer(rb"stream\r?\n(.*?)\r?\nendstream", data,
                         flags=re.S):
        raw = m.group(1)
        candidates = [raw]
        try:
            candidates.append(zlib.decompress(raw))
        except zlib.error:
            pass
        text = ""
        for c in candidates:
            text = _stream_text(c)
            if text:
                break
        if text:
            pages.append(text)
    if not pages:
        raise ValueError(
            "no extractable text streams in PDF (image-only or uses "
            "unsupported encodings; reference: documentReader sidecar)")
    return pages


# ---------------------------------------------------------------------------
# Office writers (zip+XML, matching the extractors in sidecars.py)
# ---------------------------------------------------------------------------

def _x(s: str) -> str:
    return _html.escape(str(s), quote=False)


def docx_write(paragraphs: List[str]) -> bytes:
    body = "".join(
        f"<w:p><w:r><w:t xml:space=\"preserve\">{_x(p)}</w:t></w:r></w:p>"
        for p in paragraphs)
    doc = ("<?xml version=\"1.0\" encoding=\"UTF-8\" standalone=\"yes\"?>"
           "<w:document xmlns:w=\"http://schemas.openxmlformats.org/"
           "wordprocessingml/2006/main\"><w:body>"
           f"{body}</w:body></w:document>")
    return _zip({
        "[Content_Types].xml":
            "<?xml version=\"1.0\"?><Types xmlns=\"http://schemas."
            "openxmlformats.org/package/2006/content-types\">"
            "<Default Extension=\"rels\" ContentType=\"application/vnd."
            "openxmlformats-package.relationships+xml\"/>"
            "<Default Extension=\"xml\" ContentType=\"application/xml\"/>"
            "<Override PartName=\"/word/document.xml\" ContentType="
            "\"application/vnd.openxmlformats-officedocument."
            "wordprocessingml.document.main+xml\"/></Types>",
        "_rels/.rels":
            "<?xml version=\"1.0\"?><Relationships xmlns=\"http://schemas."
            "openxmlformats.org/package/2006/relationships\">"
            "<Relationship Id=\"rId1\" Type=\"http://schemas."
            "openxmlformats.org/officeDocument/2006/relationships/"
            "officeDocument\" Target=\"word/document.xml\"/>"
            "</Relationships>",
        "word/document.xml": doc,
    })


def xlsx_write(rows: List[List[Any]]) -> bytes:
    """Shared-strings layout (t="s") so sidecars._xlsx_text reads it back."""
    shared: List[str] = []
    index: Dict[str, int] = {}
    cells_xml: List[str] = []
    for r, row in enumerate(rows, start=1):
        cs = []
        for c, val in enumerate(row):
            col = ""
            n = c
            while True:
                col = chr(ord("A") + n % 26) + col
                n = n // 26 - 1
                if n < 0:
                    break
            ref = f"{col}{r}"
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                cs.append(f"<c r=\"{ref}\"><v>{val}</v></c>")
            else:
                s = str(val)
                if s not in index:
                    index[s] = len(shared)
                    shared.append(s)
                cs.append(f"<c r=\"{ref}\" t=\"s\"><v>{index[s]}</v></c>")
        cells_xml.append(f"<row r=\"{r}\">{''.join(cs)}</row>")
    sheet = ("<?xml version=\"1.0\"?><worksheet xmlns=\"http://schemas."
             "openxmlformats.org/spreadsheetml/2006/main\"><sheetData>"
             f"{''.join(cells_xml)}</sheetData></worksheet>")
    sst = ("<?xml version=\"1.0\"?><sst xmlns=\"http://schemas."
           "openxmlformats.org/spreadsheetml/2006/main\" count="
           f"\"{len(shared)}\" uniqueCount=\"{len(shared)}\">"
           + "".join(f"<si><t xml:space=\"preserve\">{_x(s)}</t></si>"
                     for s in shared) + "</sst>")
    wb = ("<?xml version=\"1.0\"?><workbook xmlns=\"http://schemas."
          "openxmlformats.org/spreadsheetml/2006/main\" xmlns:r=\"http://"
          "schemas.openxmlformats.org/officeDocument/2006/relationships\">"
          "<sheets><sheet name=\"Sheet1\" sheetId=\"1\" r:id=\"rId1\"/>"
          "</sheets></workbook>")
    return _zip({
        "[Content_Types].xml":
            "<?xml version=\"1.0\"?><Types xmlns=\"http://schemas."
            "openxmlformats.org/package/2006/content-types\">"
            "<Default Extension=\"rels\" ContentType=\"application/vnd."
            "openxmlformats-package.relationships+xml\"/>"
            "<Default Extension=\"xml\" ContentType=\"application/xml\"/>"
            "<Override PartName=\"/xl/workbook.xml\" ContentType="
            "\"application/vnd.openxmlformats-officedocument."
            "spreadsheetml.sheet.main+xml\"/>"
            "<Override PartName=\"/xl/worksheets/sheet1.xml\" ContentType="
            "\"application/vnd.openxmlformats-officedocument."
            "spreadsheetml.worksheet+xml\"/>"
            "<Override PartName=\"/xl/sharedStrings.xml\" ContentType="
            "\"application/vnd.openxmlformats-officedocument."
            "spreadsheetml.sharedStrings+xml\"/></Types>",
        "_rels/.rels":
            "<?xml version=\"1.0\"?><Relationships xmlns=\"http://schemas."
            "openxmlformats.org/package/2006/relationships\">"
            "<Relationship Id=\"rId1\" Type=\"http://schemas."
            "openxmlformats.org/officeDocument/2006/relationships/"
            "officeDocument\" Target=\"xl/workbook.xml\"/></Relationships>",
        "xl/_rels/workbook.xml.rels":
            "<?xml version=\"1.0\"?><Relationships xmlns=\"http://schemas."
            "openxmlformats.org/package/2006/relationships\">"
            "<Relationship Id=\"rId1\" Type=\"http://schemas."
            "openxmlformats.org/officeDocument/2006/relationships/"
            "worksheet\" Target=\"worksheets/sheet1.xml\"/>"
            "<Relationship Id=\"rId2\" Type=\"http://schemas."
            "openxmlformats.org/officeDocument/2006/relationships/"
            "sharedStrings\" Target=\"sharedStrings.xml\"/>"
            "</Relationships>",
        "xl/workbook.xml": wb,
        "xl/sharedStrings.xml": sst,
        "xl/worksheets/sheet1.xml": sheet,
    })


def pptx_write(slides: List[Dict[str, Any]]) -> bytes:
    """Slides as {"title": str, "content": [str]}. Minimal single-master
    deck; text round-trips via :func:`pptx_text`."""
    files: Dict[str, str] = {}
    n = len(slides) or 1
    slide_overrides = "".join(
        f"<Override PartName=\"/ppt/slides/slide{i}.xml\" ContentType="
        "\"application/vnd.openxmlformats-officedocument.presentationml."
        "slide+xml\"/>" for i in range(1, n + 1))
    files["[Content_Types].xml"] = (
        "<?xml version=\"1.0\"?><Types xmlns=\"http://schemas."
        "openxmlformats.org/package/2006/content-types\">"
        "<Default Extension=\"rels\" ContentType=\"application/vnd."
        "openxmlformats-package.relationships+xml\"/>"
        "<Default Extension=\"xml\" ContentType=\"application/xml\"/>"
        "<Override PartName=\"/ppt/presentation.xml\" ContentType="
        "\"application/vnd.openxmlformats-officedocument.presentationml."
        "presentation.main+xml\"/>" + slide_overrides + "</Types>")
    files["_rels/.rels"] = (
        "<?xml version=\"1.0\"?><Relationships xmlns=\"http://schemas."
        "openxmlformats.org/package/2006/relationships\">"
        "<Relationship Id=\"rId1\" Type=\"http://schemas.openxmlformats."
        "org/officeDocument/2006/relationships/officeDocument\" "
        "Target=\"ppt/presentation.xml\"/></Relationships>")
    sld_ids = "".join(
        f"<p:sldId id=\"{255 + i}\" r:id=\"rId{i}\"/>"
        for i in range(1, n + 1))
    files["ppt/presentation.xml"] = (
        "<?xml version=\"1.0\"?><p:presentation xmlns:p=\"http://schemas."
        "openxmlformats.org/presentationml/2006/main\" xmlns:r=\"http://"
        "schemas.openxmlformats.org/officeDocument/2006/relationships\">"
        f"<p:sldIdLst>{sld_ids}</p:sldIdLst></p:presentation>")
    files["ppt/_rels/presentation.xml.rels"] = (
        "<?xml version=\"1.0\"?><Relationships xmlns=\"http://schemas."
        "openxmlformats.org/package/2006/relationships\">"
        + "".join(
            f"<Relationship Id=\"rId{i}\" Type=\"http://schemas."
            "openxmlformats.org/officeDocument/2006/relationships/slide\" "
            f"Target=\"slides/slide{i}.xml\"/>"
            for i in range(1, n + 1)) + "</Relationships>")
    for i, slide in enumerate(slides or [{}], start=1):
        paras = [slide.get("title", "")] + list(slide.get("content", []))
        body = "".join(
            "<a:p><a:r><a:t>" + _x(t) + "</a:t></a:r></a:p>"
            for t in paras if t != "")
        files[f"ppt/slides/slide{i}.xml"] = (
            "<?xml version=\"1.0\"?><p:sld xmlns:p=\"http://schemas."
            "openxmlformats.org/presentationml/2006/main\" xmlns:a="
            "\"http://schemas.openxmlformats.org/drawingml/2006/main\">"
            "<p:cSld><p:spTree><p:sp><p:txBody>" + body +
            "</p:txBody></p:sp></p:spTree></p:cSld></p:sld>")
    return _zip(files)


def pptx_text(path) -> str:
    """Slide text (a:t runs), one line per paragraph, slides separated by
    a blank line."""
    with zipfile.ZipFile(path) as z:
        names = sorted(
            (n for n in z.namelist()
             if re.match(r"ppt/slides/slide\d+\.xml$", n)),
            key=lambda n: int(re.search(r"(\d+)", n).group(1)))
        out: List[str] = []
        for name in names:
            xml = z.read(name).decode(errors="replace")
            paras = []
            for p in re.findall(r"(?s)<a:p[ >].*?</a:p>|<a:p/>", xml):
                runs = re.findall(r"<a:t[^>]*>(.*?)</a:t>", p, flags=re.S)
                if runs:
                    paras.append(_html.unescape("".join(runs)))
            out.append("\n".join(paras))
    return "\n\n".join(out)


def _zip(files: Dict[str, str]) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for name, content in files.items():
            z.writestr(name, content)
    return buf.getvalue()


# ---------------------------------------------------------------------------
# Image header parsing (analyze_image's in-process half)
# ---------------------------------------------------------------------------

def image_info(data: bytes) -> Dict[str, Any]:
    """Format + dimensions from magic bytes (PNG/JPEG/GIF/BMP/WEBP)."""
    if data[:8] == b"\x89PNG\r\n\x1a\n" and len(data) >= 24:
        w, h = struct.unpack(">II", data[16:24])
        return {"format": "png", "width": w, "height": h}
    if data[:2] == b"\xff\xd8":
        i = 2
        while i + 9 < len(data):
            if data[i] != 0xFF:
                i += 1
                continue
            marker = data[i + 1]
            if marker in (0xC0, 0xC1, 0xC2, 0xC3):   # SOFn
                h, w = struct.unpack(">HH", data[i + 5:i + 9])
                return {"format": "jpeg", "width": w, "height": h}
            seg_len = struct.unpack(">H", data[i + 2:i + 4])[0]
            i += 2 + seg_len
        return {"format": "jpeg", "width": None, "height": None}
    if data[:6] in (b"GIF87a", b"GIF89a") and len(data) >= 10:
        w, h = struct.unpack("<HH", data[6:10])
        return {"format": "gif", "width": w, "height": h}
    if data[:2] == b"BM" and len(data) >= 26:
        w, h = struct.unpack("<ii", data[18:26])
        return {"format": "bmp", "width": w, "height": abs(h)}
    if data[:4] == b"RIFF" and data[8:12] == b"WEBP":
        return {"format": "webp", "width": None, "height": None}
    raise ValueError("unrecognized image format")


# ---------------------------------------------------------------------------
# The tool service
# ---------------------------------------------------------------------------

class DocumentServices:
    """Handlers for the document/browser/vision tool families."""

    def __init__(self, workspace: Workspace, *,
                 vision_fn: Optional[VisionFn] = None,
                 fetch_fn: Optional[Callable[[str], Tuple[str, str]]] = None,
                 sidecars=None, max_content: int = 50_000):
        self.workspace = workspace
        self.vision_fn = vision_fn
        self._fetch_fn = fetch_fn
        # Network access rides the sidecar layer so SidecarConfig's
        # url_filter / timeout / byte caps govern open_browser too —
        # one fetch path, one policy (review finding: no second
        # unrestricted urllib path out of the rollout sandbox).
        from .sidecars import SidecarServices
        self.sidecars = sidecars or SidecarServices(workspace)
        self.max_content = max_content
        self._browser_sessions: Dict[str, Dict[str, Any]] = {}
        self._next_session = 1

    def install(self, tools) -> None:
        for name in ("edit_document", "create_document", "pdf_operation",
                     "document_convert", "document_merge",
                     "document_extract", "open_browser", "analyze_image",
                     "screenshot_to_code"):
            tools.register_handler(name, getattr(self, name))

    def mutation_targets(self, tool: str, p: Dict[str, Any]) -> List[str]:
        """Paths a document tool will (over)write, BEFORE execution —
        the before-edit snapshot hook's source of truth. Lives here so it
        can mirror each handler's real path arithmetic (split writes
        ``{stem}_page{i}.pdf``, convert honors the ``format`` override)
        instead of a second hand-rolled guess drifting in the session."""
        if tool in ("edit_document",):
            return [p["uri"]] if p.get("uri") else []
        if tool == "create_document":
            return [p["file_path"]] if p.get("file_path") else []
        if tool in ("document_merge",):
            return [p["output_path"]] if p.get("output_path") else []
        if tool == "document_convert":
            out = p.get("output_path")
            if not out:
                return []
            fmt = (p.get("format")
                   or Path(out).suffix.lstrip(".")).lower()
            dst = self.workspace.resolve(out)
            if fmt and dst.suffix.lstrip(".").lower() != fmt:
                dst = dst.with_suffix("." + fmt)
            return [str(dst.relative_to(self.workspace.root))]
        if tool == "pdf_operation":
            out = p.get("output_path")
            if not out:
                return []
            if str(p.get("operation", "")).lower() == "split":
                stem = self.workspace.resolve(out)
                return [str(f.relative_to(self.workspace.root))
                        for f in sorted(
                            stem.parent.glob(f"{stem.stem}_page*.pdf"))]
            return [out]
        return []

    # -- reading any supported format --------------------------------------
    def read_text_any(self, path: Path) -> str:
        """Plain-text view of any supported document format."""
        suffix = path.suffix.lower()
        if suffix == ".pdf":
            return "\n\n".join(minipdf_extract_pages(path.read_bytes()))
        if suffix == ".pptx":
            return pptx_text(path)
        if suffix == ".docx":
            from .sidecars import SidecarServices
            return SidecarServices._docx_text(path)
        if suffix == ".xlsx":
            from .sidecars import SidecarServices
            return SidecarServices._xlsx_text(path)
        if suffix in (".html", ".htm"):
            from .sidecars import html_to_text
            return html_to_text(path.read_text(errors="replace"))
        return path.read_text(errors="replace")

    def _write_as(self, path: Path, text: str) -> None:
        """Write plain text into the format implied by ``path``'s suffix."""
        suffix = path.suffix.lower()
        lines = text.split("\n")
        if suffix == ".docx":
            data: bytes = docx_write(lines)
        elif suffix == ".xlsx":
            rows = [self._split_row(ln) for ln in lines if ln.strip()]
            data = xlsx_write(rows)
        elif suffix == ".pptx":
            slides = [{"title": chunk[0] if chunk else "",
                       "content": chunk[1:]}
                      for chunk in _chunk_blank(lines)]
            data = pptx_write(slides)
        elif suffix == ".pdf":
            pages = [lines[i:i + 48] for i in range(0, len(lines), 48)]
            data = minipdf_write(pages or [[""]])
        elif suffix == ".csv":
            out = io.StringIO()
            w = csv.writer(out)
            for ln in lines:
                w.writerow(self._split_row(ln))
            path.write_text(out.getvalue())
            return
        elif suffix in (".html", ".htm"):
            body = "".join(f"<p>{_x(ln)}</p>\n" for ln in lines if ln)
            path.write_text("<!DOCTYPE html>\n<html><body>\n"
                            f"{body}</body></html>\n")
            return
        else:
            path.write_text(text)
            return
        path.write_bytes(data)

    @staticmethod
    def _structured(v: Any) -> Any:
        """Tool params travel as strings in the XML call grammar; decode
        JSON-shaped payloads (objects/arrays) back into structure."""
        if isinstance(v, str):
            s = v.strip()
            if s[:1] in ("{", "["):
                try:
                    return json.loads(s)
                except json.JSONDecodeError:
                    return v
        return v

    @staticmethod
    def _split_row(line: str) -> List[str]:
        if "\t" in line:
            return line.split("\t")
        if "," in line:
            return next(csv.reader(io.StringIO(line)))
        return [line]

    # -- edit_document -----------------------------------------------------
    def edit_document(self, p: Dict[str, Any]) -> Dict[str, Any]:
        path = self.workspace.resolve(p["uri"])
        if not path.is_file():
            raise FileNotFoundError(f"document does not exist: {p['uri']}")
        text = self.read_text_any(path)
        changes = 0
        if p.get("content") is not None:
            text = str(p["content"])
            changes = 1
        for rep in (self._structured(p.get("replacements")) or []):
            if isinstance(rep, dict):
                find, replace = rep.get("find", ""), rep.get("replace", "")
            else:
                find, replace = rep[0], rep[1]
            if find and find in text:
                text = text.replace(find, replace)
                changes += 1
        self._write_as(path, text)
        return {"uri": p["uri"], "format": path.suffix.lower() or "text",
                "changes": changes, "total_length": len(text)}

    # -- create_document ---------------------------------------------------
    def create_document(self, p: Dict[str, Any]) -> Dict[str, Any]:
        dtype = str(p["type"]).lower()
        path = self.workspace.resolve(p["file_path"])
        data = self._structured(p["document_data"])
        path.parent.mkdir(parents=True, exist_ok=True)

        def field(key: str) -> Any:
            # A dict payload must carry the type-specific key; anything
            # else is an actionable schema error, not a TypeError.
            v = data.get(key)
            if v is None:
                raise ValueError(
                    f"document_data for type '{dtype}' must contain "
                    f"'{key}' (got keys: {sorted(data.keys())})")
            return v

        if dtype in ("word", "docx"):
            paras = (field("paragraphs") if isinstance(data, dict)
                     else str(data).split("\n"))
            path.write_bytes(docx_write([str(x) for x in paras]))
        elif dtype in ("excel", "xlsx"):
            rows = (field("rows") if isinstance(data, dict)
                    else [self._split_row(ln)
                          for ln in str(data).split("\n") if ln.strip()])
            path.write_bytes(xlsx_write(rows))
        elif dtype in ("ppt", "pptx"):
            slides = (field("slides") if isinstance(data, dict)
                      else [{"title": s[0] if s else "", "content": s[1:]}
                            for s in _chunk_blank(str(data).split("\n"))])
            path.write_bytes(pptx_write(list(slides)))
        elif dtype == "pdf":
            lines = (field("lines") if isinstance(data, dict)
                     else str(data).split("\n"))
            pages = [lines[i:i + 48] for i in range(0, len(lines), 48)]
            path.write_bytes(minipdf_write(pages or [[""]]))
        else:
            raise ValueError(f"unsupported document type: {dtype}")
        return {"created": p["file_path"], "type": dtype,
                "bytes": path.stat().st_size}

    # -- pdf_operation -----------------------------------------------------
    def pdf_operation(self, p: Dict[str, Any]) -> Dict[str, Any]:
        op = str(p["operation"]).lower()
        inputs = self._structured(p.get("input_files")) or []
        if isinstance(inputs, str):
            inputs = [inputs]
        if not inputs:
            raise ValueError("pdf_operation needs input_files")
        paths = [self.workspace.resolve(u) for u in inputs]
        if op == "merge":
            pages: List[str] = []
            for path in paths:
                pages.extend(minipdf_extract_pages(path.read_bytes()))
            out = self.workspace.resolve(p["output_path"])
            out.parent.mkdir(parents=True, exist_ok=True)
            out.write_bytes(minipdf_write([pg.split("\n") for pg in pages]))
            return {"operation": op, "output": p["output_path"],
                    "pages": len(pages)}
        if op == "split":
            pages = minipdf_extract_pages(paths[0].read_bytes())
            stem = self.workspace.resolve(p["output_path"])
            stem.parent.mkdir(parents=True, exist_ok=True)
            created = []
            for i, pg in enumerate(pages, start=1):
                target = stem.parent / f"{stem.stem}_page{i}.pdf"
                target.write_bytes(minipdf_write([pg.split("\n")]))
                created.append(target.name)
            return {"operation": op, "created": created,
                    "pages": len(pages)}
        if op == "watermark":
            mark = str(p.get("watermark_text") or p.get("text") or "DRAFT")
            pages = minipdf_extract_pages(paths[0].read_bytes())
            out = self.workspace.resolve(p["output_path"])
            out.parent.mkdir(parents=True, exist_ok=True)
            out.write_bytes(minipdf_write(
                [[f"[{mark}]"] + pg.split("\n") for pg in pages]))
            return {"operation": op, "output": p["output_path"],
                    "watermark": mark, "pages": len(pages)}
        raise ValueError(f"unknown pdf operation: {op}")

    # -- document_convert / merge / extract --------------------------------
    def document_convert(self, p: Dict[str, Any]) -> Dict[str, Any]:
        src = self.workspace.resolve(p["input_file"])
        if not src.is_file():
            raise FileNotFoundError(f"no such document: {p['input_file']}")
        dst = self.workspace.resolve(p["output_path"])
        fmt = (p.get("format") or dst.suffix.lstrip(".")).lower()
        if fmt and dst.suffix.lstrip(".").lower() != fmt:
            dst = dst.with_suffix("." + fmt)
        text = self.read_text_any(src)
        dst.parent.mkdir(parents=True, exist_ok=True)
        self._write_as(dst, text)
        return {"input": p["input_file"], "output": dst.name,
                "format": fmt or "text", "chars": len(text)}

    def document_merge(self, p: Dict[str, Any]) -> Dict[str, Any]:
        inputs = self._structured(p["input_files"])
        if isinstance(inputs, str):
            inputs = [s for s in re.split(r"[,\n]", inputs) if s.strip()]
        texts = []
        for uri in inputs:
            path = self.workspace.resolve(uri.strip())
            texts.append(self.read_text_any(path))
        merged = "\n\n".join(texts)
        dst = self.workspace.resolve(p["output_path"])
        dst.parent.mkdir(parents=True, exist_ok=True)
        self._write_as(dst, merged)
        return {"output": p["output_path"], "inputs": len(texts),
                "chars": len(merged)}

    def document_extract(self, p: Dict[str, Any]) -> Dict[str, Any]:
        path = self.workspace.resolve(p["input_file"])
        if not path.is_file():
            raise FileNotFoundError(f"no such document: {p['input_file']}")
        kind = str(p.get("extract_type") or "text").lower()
        text = self.read_text_any(path)
        if kind == "text":
            return {"extract_type": kind,
                    "content": text[: self.max_content],
                    "truncated": len(text) > self.max_content}
        if kind == "links":
            links = re.findall(r"https?://[^\s)\"'<>\]]+", text)
            return {"extract_type": kind, "links": links[:500]}
        if kind == "emails":
            emails = sorted(set(re.findall(
                r"[A-Za-z0-9._%+-]+@[A-Za-z0-9.-]+\.[A-Za-z]{2,}", text)))
            return {"extract_type": kind, "emails": emails[:500]}
        if kind == "tables":
            rows = [ln for ln in text.split("\n")
                    if "\t" in ln or ln.lstrip().startswith("|")]
            return {"extract_type": kind,
                    "rows": [self._split_row(ln.strip().strip("|"))
                             if "\t" in ln else
                             [c.strip() for c in ln.strip().strip("|")
                              .split("|")]
                             for ln in rows[:500]]}
        if kind == "metadata":
            return {"extract_type": kind,
                    "format": path.suffix.lower() or "text",
                    "bytes": path.stat().st_size, "chars": len(text),
                    "lines": text.count("\n") + 1,
                    "words": len(text.split())}
        raise ValueError(f"unknown extract_type: {kind}")

    # -- open_browser ------------------------------------------------------
    def open_browser(self, p: Dict[str, Any]) -> Dict[str, Any]:
        """Fetch-backed page session: the sandbox has no display, so the
        'browser' is a readable-text view plus the page's links (the
        reference drives a real browser via startOpenBrowserServer.cjs)."""
        from .sidecars import html_to_text, _title_of
        url = p["url"]
        if self._fetch_fn is not None:
            markup, final_url = self._fetch_fn(url)
        else:
            self.sidecars._check_url(url)
            markup, _ctype, final_url = self.sidecars._get(url)
        links = re.findall(r"(?i)<a[^>]+href=[\"']([^\"'#][^\"']*)[\"']",
                           markup)[:100]
        session_id = f"browser-{self._next_session}"
        self._next_session += 1
        self._browser_sessions[session_id] = {
            "url": final_url, "opened_at": time.time()}
        return {"session_id": session_id, "url": final_url,
                "title": _title_of(markup),
                "content": html_to_text(markup)[: self.max_content],
                "links": links}

    # -- vision tools ------------------------------------------------------
    def analyze_image(self, p: Dict[str, Any]) -> Dict[str, Any]:
        data = base64.b64decode(p["image_data"], validate=False)
        info = image_info(data)
        info["bytes"] = len(data)
        prompt = str(p.get("prompt") or "Describe this image.")
        if self.vision_fn is not None:
            info["analysis"] = self.vision_fn(data, prompt)
        else:
            info["note"] = ("no vision model configured; returning image "
                            "metadata only")
        return info

    def screenshot_to_code(self, p: Dict[str, Any]) -> Dict[str, Any]:
        if self.vision_fn is None:
            raise ToolUnavailableError(
                "screenshot_to_code needs a vision-capable model "
                "(DocumentServices(vision_fn=...); reference: "
                "startScreenshotToCodeServer.cjs)")
        source = str(p["source"]).lower()
        stack = str(p.get("stack") or "html")
        if source == "image":
            data = base64.b64decode(p["image_data"], validate=False)
        elif source == "url":
            shot = self.open_browser({"url": p["url"]})
            data = shot["content"].encode()
        else:
            raise ValueError("source must be 'image' or 'url'")
        code = self.vision_fn(
            data, f"Generate {stack} code reproducing this UI. "
                  f"Return only code.")
        return {"stack": stack, "code": code}


def _chunk_blank(lines: List[str]) -> List[List[str]]:
    """Split lines into blank-line-separated chunks (≥1 chunk)."""
    chunks: List[List[str]] = [[]]
    for ln in lines:
        if ln.strip() == "":
            if chunks[-1]:
                chunks.append([])
        else:
            chunks[-1].append(ln)
    if not chunks[-1]:
        chunks.pop()
    return chunks or [[]]
