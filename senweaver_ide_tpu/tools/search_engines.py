"""Concrete web-search engine adapters for the fan-out merger.

The reference's webSearch sidecar rotates 8 engines (Baidu/Bing/DDG/
CSDN/Juejin/Weixin/GitHub/arXiv — ``startWebSearchServer.cjs:3``); the
TPU build's ``tools/sidecars.py web_search`` fans out over a pluggable
engine list and rank-merges. This module supplies the adapters: each is
a (query, limit) → results callable built over an injectable
``fetch(url) -> str`` so the PARSERS are hermetic-testable (zero-egress
environments test against canned fixtures; online deployments pass a
real fetcher, e.g. ``SidecarServices.text_fetcher()``).

Each result is ``{"title", "url", "snippet"}`` — the shape the RRF
merger dedups and scores.
"""

from __future__ import annotations

import html as _html
import json
import re
import urllib.parse
from typing import Callable, Dict, List

Fetch = Callable[[str], str]
Result = Dict[str, str]


def _clean(markup: str) -> str:
    return _html.unescape(re.sub(r"<[^>]+>", "", markup)).strip()


# -- DuckDuckGo (html.duckduckgo.com/html, no JS) -------------------------

def parse_ddg_html(page: str, limit: int) -> List[Result]:
    out: List[Result] = []
    anchor_re = re.compile(
        r'<a[^>]*class="[^"]*result__a[^"]*"[^>]*href="([^"]+)"[^>]*>'
        r'(.*?)</a>', re.S)
    matches = list(anchor_re.finditer(page))
    for i, m in enumerate(matches):
        # hrefs arrive HTML-entity-escaped ("&amp;uddg=..."): unescape
        # BEFORE query parsing or uddg is only found when first.
        url = _html.unescape(m.group(1))
        title = _clean(m.group(2))
        # DDG wraps targets in a redirect: uddg=<quoted real url>
        q = urllib.parse.urlparse(url).query
        real = urllib.parse.parse_qs(q).get("uddg", [url])[0]
        # Snippet search is bounded at the NEXT result's anchor — an
        # unbounded window would steal the following result's snippet
        # for any hit that has none of its own.
        end = (matches[i + 1].start() if i + 1 < len(matches)
               else len(page))
        snippet = ""
        sm = re.search(r'class="[^"]*result__snippet[^"]*"[^>]*>(.*?)</a>',
                       page[m.end():end], re.S)
        if sm:
            snippet = _clean(sm.group(1))[:300]
        out.append({"title": title, "url": real, "snippet": snippet})
        if len(out) >= limit:
            break
    return out


def duckduckgo_engine(fetch: Fetch):
    def duckduckgo(query: str, limit: int) -> List[Result]:
        page = fetch("https://html.duckduckgo.com/html/?q="
                     + urllib.parse.quote_plus(query))
        return parse_ddg_html(page, limit)
    return duckduckgo


# -- Bing (www.bing.com/search, classic HTML results) ---------------------

def parse_bing_html(page: str, limit: int) -> List[Result]:
    out: List[Result] = []
    for m in re.finditer(
            r'<li class="b_algo".*?<h2><a[^>]*href="([^"]+)"[^>]*>(.*?)'
            r"</a></h2>(.*?)</li>", page, re.S):
        # hrefs are HTML-attribute-escaped; an un-unescaped '&amp;'
        # breaks downstream fetches AND RRF dedup against other engines.
        url = _html.unescape(m.group(1))
        title, body = _clean(m.group(2)), m.group(3)
        sm = re.search(r"<p[^>]*>(.*?)</p>", body, re.S)
        out.append({"title": title, "url": url,
                    "snippet": _clean(sm.group(1))[:300] if sm else ""})
        if len(out) >= limit:
            break
    return out


def bing_engine(fetch: Fetch):
    def bing(query: str, limit: int) -> List[Result]:
        page = fetch("https://www.bing.com/search?q="
                     + urllib.parse.quote_plus(query))
        return parse_bing_html(page, limit)
    return bing


# -- GitHub repository search (REST JSON, no key for low volume) ----------

def parse_github_json(payload: str, limit: int) -> List[Result]:
    items = json.loads(payload).get("items", [])
    return [{"title": it.get("full_name", ""),
             "url": it.get("html_url", ""),
             "snippet": (it.get("description") or "")[:300]}
            for it in items[:limit]]


def github_engine(fetch: Fetch):
    def github(query: str, limit: int) -> List[Result]:
        payload = fetch("https://api.github.com/search/repositories?q="
                        + urllib.parse.quote_plus(query)
                        + f"&per_page={limit}")
        return parse_github_json(payload, limit)
    return github


# -- arXiv (Atom XML export API) ------------------------------------------

def parse_arxiv_atom(feed: str, limit: int) -> List[Result]:
    out: List[Result] = []
    for m in re.finditer(r"<entry>(.*?)</entry>", feed, re.S):
        entry = m.group(1)
        t = re.search(r"<title>(.*?)</title>", entry, re.S)
        i = re.search(r"<id>(.*?)</id>", entry, re.S)
        s = re.search(r"<summary>(.*?)</summary>", entry, re.S)
        out.append({
            "title": _clean(t.group(1)) if t else "",
            "url": (i.group(1).strip() if i else ""),
            "snippet": (_clean(s.group(1))[:300] if s else ""),
        })
        if len(out) >= limit:
            break
    return out


def arxiv_engine(fetch: Fetch):
    def arxiv(query: str, limit: int) -> List[Result]:
        feed = fetch("http://export.arxiv.org/api/query?search_query=all:"
                     + urllib.parse.quote_plus(query)
                     + f"&max_results={limit}")
        return parse_arxiv_atom(feed, limit)
    return arxiv


def default_engines(fetch: Fetch) -> tuple:
    """The standard fan-out set over one fetcher (order is merge-neutral:
    the RRF merger scores by rank agreement, not engine order)."""
    return (duckduckgo_engine(fetch), bing_engine(fetch),
            github_engine(fetch), arxiv_engine(fetch))
