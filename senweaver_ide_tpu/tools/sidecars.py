"""Sidecar tool backends: fetch_url / api_request / read_document / web_search.

The reference runs these as localhost Node HTTP servers spawned per tool
(``browser/start*.cjs``, 11.6k LoC: fetchUrl 2201, apiRequest 391,
documentReader 3793, webSearch 1482 — SURVEY.md §2.5/L8). The TPU build
has no Electron renderer to keep heavy work out of, so the equivalents are
in-process handlers plugged into ToolsService.register_handler — same tool
contract, no server lifecycle:

- ``fetch_url``: urllib GET with byte/char caps and an HTML→readable-text
  pass (the reference's cheerio/readability stage, startFetchUrlServer.cjs).
- ``api_request``: arbitrary-method HTTP with JSON header parsing and a
  capped response envelope (startApiRequestServer.cjs).
- ``read_document``: workspace-sandboxed text/markdown/CSV/JSON plus
  stdlib-only docx/xlsx extraction (zip+XML — no binary deps); the 3793-LoC
  reader's conversion matrix stays external (startDocumentReaderServer.cjs).
- ``web_search``: pluggable engine list (the reference rotates 8 engines,
  startWebSearchServer.cjs:3,:1025-1027); with no engines or no network it
  returns an OK-shaped empty result set instead of a failed tool call, so
  offline rollouts stop recording spurious failures in reward dims 3/4.
"""

from __future__ import annotations

import csv
import dataclasses
import html as _html
import io
import json
import re
import time
import urllib.error
import urllib.request
import zipfile
from typing import Any, Callable, Dict, List, Optional, Sequence

from .sandbox import Workspace

SearchEngine = Callable[[str, int], List[Dict[str, str]]]


@dataclasses.dataclass
class SidecarConfig:
    timeout_s: float = 15.0
    max_fetch_bytes: int = 2_000_000
    default_max_length: int = 50_000
    user_agent: str = "senweaver-ide-tpu/0.2"
    # Search engines: ALL are queried concurrently and rank-merged
    # (the reference's 8-engine rotation, startWebSearchServer.cjs).
    search_engines: Sequence[SearchEngine] = ()
    # Cap on concurrently-queried engines per search.
    fanout: int = 8
    # Optional URL predicate for fetch_url/api_request (e.g. allowlist).
    url_filter: Optional[Callable[[str], bool]] = None


def html_to_text(markup: str) -> str:
    """Readable-text extraction (the reference's readability stage,
    collapsed to stdlib): drop script/style/head, convert structural tags
    to line breaks, strip the rest, unescape entities."""
    s = re.sub(r"(?is)<(script|style|head|noscript|template)[^>]*>.*?</\1>",
               " ", markup)
    s = re.sub(r"(?i)<(br|/p|/div|/li|/tr|/h[1-6]|/section|/article)[^>]*>",
               "\n", s)
    s = re.sub(r"(?s)<[^>]+>", " ", s)
    s = _html.unescape(s)
    s = re.sub(r"[ \t\r\f\v]+", " ", s)
    s = re.sub(r" *\n *", "\n", s)
    s = re.sub(r"\n\n+", "\n\n", s)
    return s.strip()


def _title_of(markup: str) -> str:
    m = re.search(r"(?is)<title[^>]*>(.*?)</title>", markup)
    return _html.unescape(m.group(1)).strip() if m else ""


class SidecarServices:
    """In-process backends for the reference's sidecar-served tools."""

    def __init__(self, workspace: Workspace,
                 config: Optional[SidecarConfig] = None):
        self.workspace = workspace
        self.config = config or SidecarConfig()

    def install(self, tools) -> None:
        """Register every backend on a ToolsService."""
        tools.register_handler("fetch_url", self.fetch_url)
        tools.register_handler("api_request", self.api_request)
        tools.register_handler("read_document", self.read_document)
        tools.register_handler("web_search", self.web_search)

    # -- fetch_url (startFetchUrlServer.cjs) ------------------------------
    def fetch_url(self, p: Dict[str, Any]) -> Dict[str, Any]:
        url = p["url"]
        self._check_url(url)
        max_length = int(p.get("max_length") or
                         self.config.default_max_length)
        start_index = int(p.get("start_index") or 0)
        raw, content_type, final_url = self._get(url)
        if "html" in content_type:
            text = html_to_text(raw)
            title = _title_of(raw)
        else:
            text, title = raw, ""
        window = text[start_index:start_index + max_length]
        return {
            "url": final_url, "title": title, "content": window,
            "content_type": content_type, "total_length": len(text),
            "start_index": start_index,
            "truncated": start_index + len(window) < len(text),
        }

    # -- api_request (startApiRequestServer.cjs) --------------------------
    def api_request(self, p: Dict[str, Any]) -> Dict[str, Any]:
        url = p["url"]
        self._check_url(url)
        method = str(p.get("method") or "GET").upper()
        headers = {"User-Agent": self.config.user_agent}
        raw_headers = p.get("headers")
        if raw_headers:
            parsed = (json.loads(raw_headers)
                      if isinstance(raw_headers, str) else raw_headers)
            if not isinstance(parsed, dict):
                raise ValueError("headers must be a JSON object")
            headers.update({str(k): str(v) for k, v in parsed.items()})
        body = p.get("body")
        data = body.encode() if isinstance(body, str) else body
        req = urllib.request.Request(url, data=data, headers=headers,
                                     method=method)
        t0 = time.monotonic()
        try:
            with urllib.request.urlopen(
                    req, timeout=self.config.timeout_s) as resp:
                payload = resp.read(self.config.max_fetch_bytes)
                status = resp.status
                resp_headers = dict(resp.headers)
        except urllib.error.HTTPError as e:
            payload = e.read()[: self.config.max_fetch_bytes]
            status = e.code
            resp_headers = dict(e.headers or {})
        text = payload.decode(errors="replace")
        return {"status": status, "headers": resp_headers,
                "body": text[: self.config.default_max_length],
                "truncated": len(text) > self.config.default_max_length,
                "duration_ms": round((time.monotonic() - t0) * 1000, 1)}

    # -- read_document (startDocumentReaderServer.cjs) --------------------
    def read_document(self, p: Dict[str, Any]) -> Dict[str, Any]:
        uri = p["uri"]
        path = self.workspace.resolve(uri)
        if not path.is_file():
            raise FileNotFoundError(f"document does not exist: {uri}")
        suffix = path.suffix.lower()
        if suffix == ".docx":
            text = self._docx_text(path)
        elif suffix == ".xlsx":
            text = self._xlsx_text(path)
        elif suffix == ".csv":
            text = self._csv_text(path)
        elif suffix == ".json":
            text = json.dumps(json.loads(path.read_text(errors="replace")),
                              indent=2, ensure_ascii=False)
        elif suffix in (".txt", ".md", ".markdown", ".rst", ".log", ""):
            text = path.read_text(errors="replace")
        elif suffix == ".pdf":
            from .documents import minipdf_extract_pages
            text = "\n\n".join(minipdf_extract_pages(path.read_bytes()))
        elif suffix == ".pptx":
            from .documents import pptx_text
            text = pptx_text(path)
        elif suffix in (".doc", ".xls", ".ppt"):
            raise ValueError(
                f"legacy {suffix} extraction needs an external converter "
                f"in this hermetic build (reference: documentReader "
                f"sidecar)")
        else:
            text = path.read_text(errors="replace")
        start = int(p.get("start_index") or 0)
        cap = int(p.get("max_length") or self.config.default_max_length)
        window = text[start:start + cap]
        return {"uri": uri, "format": suffix or "text", "content": window,
                "total_length": len(text), "start_index": start,
                "truncated": start + len(window) < len(text)}

    # -- web_search (startWebSearchServer.cjs) ----------------------------
    def web_search(self, p: Dict[str, Any]) -> Dict[str, Any]:
        """Multi-engine fan-out → rank-merge (startWebSearchServer.cjs
        :1025-1027 rotates 8 engines; here ALL configured engines are
        queried CONCURRENTLY and their result lists fuse by reciprocal
        rank, so one slow/flaky engine neither blocks nor biases the
        answer). Dedup is by URL; an engine that throws only drops its
        own votes. With zero engines (the hermetic default) this stays
        an OK-shaped empty result, not a failed tool call."""
        import concurrent.futures as _fut

        query = p["query"]
        limit = int(p.get("max_results") or 10)
        engines = list(self.config.search_engines)[:self.config.fanout]
        errors: List[str] = []
        per_engine: List[tuple] = []     # (engine_name, results)
        if engines:
            # No context manager: its exit JOINS workers, so one wedged
            # engine would stall every search. Bounded wait + abandon.
            pool = _fut.ThreadPoolExecutor(max_workers=len(engines))
            futs = {pool.submit(e, query, limit):
                    getattr(e, "__name__", f"engine{i}")
                    for i, e in enumerate(engines)}
            pending = set(futs)
            try:
                for f in _fut.as_completed(futs,
                                           timeout=self.config.timeout_s):
                    pending.discard(f)
                    name = futs[f]
                    try:
                        per_engine.append((name, list(f.result())[:limit]))
                    except Exception as e:   # engine down → skip its votes
                        errors.append(f"{name}: {type(e).__name__}")
            except _fut.TimeoutError:        # stragglers forfeit their votes
                for f in pending:
                    errors.append(f"{futs[f]}: timeout")
            finally:
                pool.shutdown(wait=False, cancel_futures=True)
        # Reciprocal-rank fusion over URL identity: score(url) =
        # Σ_engines 1/(K + rank); K=60 is the standard RRF constant.
        fused: Dict[str, Dict[str, Any]] = {}
        K = 60.0
        # Deterministic fold order; key on the NAME only (two engines may
        # share a __name__, and result dicts don't compare).
        for name, results in sorted(per_engine, key=lambda t: t[0]):
            for rank, r in enumerate(results):
                url = r.get("url") or r.get("link") or r.get("title", "")
                entry = fused.setdefault(
                    url, {"result": dict(r), "score": 0.0, "engines": []})
                entry["score"] += 1.0 / (K + rank)
                entry["engines"].append(name)
        ranked = sorted(fused.values(), key=lambda e: -e["score"])[:limit]
        if ranked:
            return {"query": query,
                    "results": [{**e["result"],
                                 "engines": e["engines"]} for e in ranked],
                    "engines_queried": len(engines),
                    "engines_failed": len(errors)}
        # Graceful offline degradation: an OK result with zero hits (the
        # model sees "no results", not a failed tool call).
        return {"query": query, "results": [],
                "note": "no search engine available"
                        + (f" ({'; '.join(errors)})" if errors else "")}

    def text_fetcher(self) -> Callable[[str], str]:
        """``fetch(url) -> body text`` over this sidecar's HTTP stack
        (UA, timeout, byte cap, url_filter) — the injection point for
        the concrete search-engine adapters (tools/search_engines.py):

            cfg.search_engines = default_engines(svc.text_fetcher())
        """
        def fetch(url: str) -> str:
            self._check_url(url)
            raw, _ctype, _final = self._get(url)
            return raw
        return fetch

    # -- internals --------------------------------------------------------
    def _check_url(self, url: str) -> None:
        if self.config.url_filter is not None \
                and not self.config.url_filter(url):
            raise PermissionError(f"URL not allowed by policy: {url}")

    def _get(self, url: str) -> tuple[str, str, str]:
        req = urllib.request.Request(
            url, headers={"User-Agent": self.config.user_agent})
        with urllib.request.urlopen(
                req, timeout=self.config.timeout_s) as resp:
            raw = resp.read(self.config.max_fetch_bytes)
            ctype = (resp.headers.get("Content-Type") or "").lower()
            return raw.decode(errors="replace"), ctype, resp.url

    @staticmethod
    def _docx_text(path) -> str:
        with zipfile.ZipFile(path) as z:
            xml = z.read("word/document.xml").decode(errors="replace")
        paras = re.split(r"</w:p>", xml)
        lines = []
        for para in paras:
            runs = re.findall(r"<w:t[^>]*>(.*?)</w:t>", para, flags=re.S)
            if runs:
                lines.append(_html.unescape("".join(runs)))
        return "\n".join(lines)

    @staticmethod
    def _xlsx_text(path) -> str:
        with zipfile.ZipFile(path) as z:
            shared: List[str] = []
            if "xl/sharedStrings.xml" in z.namelist():
                sxml = z.read("xl/sharedStrings.xml").decode(errors="replace")
                shared = [_html.unescape(re.sub(r"(?s)<[^>]+>", "", si))
                          for si in re.findall(r"(?s)<si>(.*?)</si>", sxml)]
            sheets = sorted(n for n in z.namelist()
                            if re.match(r"xl/worksheets/sheet\d+\.xml$", n))
            out: List[str] = []
            for name in sheets:
                xml = z.read(name).decode(errors="replace")
                for row in re.findall(r"(?s)<row[^>]*>(.*?)</row>", xml):
                    cells = []
                    for attrs, val in re.findall(
                            r"(?s)<c\b([^>]*)>.*?<v>(.*?)</v>", row):
                        if re.search(r'\bt="s"', attrs):
                            idx = int(val)
                            cells.append(shared[idx]
                                         if idx < len(shared) else val)
                        else:
                            cells.append(val)
                    if cells:
                        out.append("\t".join(cells))
            return "\n".join(out)

    def _csv_text(self, path) -> str:
        text = path.read_text(errors="replace")
        rows = list(csv.reader(io.StringIO(text)))
        return "\n".join("\t".join(row) for row in rows)
