"""Builtin tool schema registry — rendered into the system prompt.

The analogue of `prompt/prompts.ts:225-718` (builtinTools): one entry per
active tool with a description and named params. The agent loop renders
these as the XML tool-call grammar the local policy emits (the reference
renders them for providers without native tool APIs via
extractXMLToolsWrapper, extractGrammar.ts:324 — the local-policy path here
always uses that grammar).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Mapping, Sequence

from .types import BUILTIN_TOOL_NAMES


@dataclasses.dataclass(frozen=True)
class ToolSchema:
    name: str
    description: str
    params: Mapping[str, str]          # param name → description
    required: Sequence[str] = ()


_URI = "Full sandbox path to the target."
_PAGE = "Optional 1-based page number for paginated results."

TOOL_SCHEMAS: Dict[str, ToolSchema] = {s.name: s for s in [
    # --- context gathering ---
    ToolSchema("read_file", "Read the contents of a file.",
               {"uri": _URI,
                "start_line": "Optional first line (1-based).",
                "end_line": "Optional last line (inclusive).",
                "page_number": _PAGE}, ("uri",)),
    ToolSchema("ls_dir", "List the files and folders in a directory.",
               {"uri": "Optional folder path; empty for workspace root.",
                "page_number": _PAGE}),
    ToolSchema("get_dir_tree",
               "Print a bounded tree diagram of a folder — an efficient "
               "way to learn the layout of the workspace.",
               {"uri": _URI}, ("uri",)),
    ToolSchema("search_pathnames_only",
               "Find files whose NAME or path matches the query.",
               {"query": "Substring or glob to match against pathnames.",
                "include_pattern": "Optional glob filter over results.",
                "page_number": _PAGE}, ("query",)),
    ToolSchema("search_for_files",
               "Find files whose CONTENT matches the query.",
               {"query": "Substring or regex to search for.",
                "is_regex": "Optional bool; default false.",
                "search_in_folder": "Optional folder to restrict the search.",
                "page_number": _PAGE}, ("query",)),
    ToolSchema("search_in_file",
               "Return the 1-based line numbers where the query matches "
               "inside one file.",
               {"uri": _URI,
                "query": "Substring or regex.",
                "is_regex": "Optional bool; default false."},
               ("uri", "query")),
    ToolSchema("read_lint_errors", "Read lint diagnostics for a file.",
               {"uri": _URI}, ("uri",)),
    # --- edits ---
    ToolSchema("create_file_or_folder",
               "Create a file or folder (missing parents are created). A "
               "trailing slash means folder; no trailing slash means file.",
               {"uri": _URI}, ("uri",)),
    ToolSchema("delete_file_or_folder", "Delete a file or folder.",
               {"uri": _URI,
                "is_recursive": "Optional bool; delete folders recursively."},
               ("uri",)),
    ToolSchema("edit_file",
               "Apply SEARCH/REPLACE block edits to a file. Provide one "
               "string containing <<<<<<< ORIGINAL / ======= / "
               ">>>>>>> UPDATED blocks whose ORIGINAL text is copied "
               "exactly from read_file output.",
               {"uri": _URI,
                "search_replace_blocks": "The SEARCH/REPLACE block string."},
               ("uri", "search_replace_blocks")),
    ToolSchema("rewrite_file", "Replace the entire contents of a file.",
               {"uri": _URI, "new_content": "The complete new file text."},
               ("uri", "new_content")),
    # --- terminal ---
    ToolSchema("run_command",
               "Run a shell command and wait for it (times out after 8s of "
               "output inactivity).",
               {"command": "The shell command.",
                "cwd": "Optional working directory."}, ("command",)),
    ToolSchema("open_persistent_terminal",
               "Open a long-lived background shell; returns its ID.",
               {"cwd": "Optional working directory."}),
    ToolSchema("run_persistent_command",
               "Run a command in a persistent terminal; returns output "
               "after 5s while the command keeps running.",
               {"command": "The shell command.",
                "persistent_terminal_id": "ID from "
                                          "open_persistent_terminal."},
               ("command", "persistent_terminal_id")),
    ToolSchema("kill_persistent_terminal",
               "Kill a persistent terminal by ID.",
               {"persistent_terminal_id": "The terminal ID."},
               ("persistent_terminal_id",)),
    # --- network (gated in the hermetic sandbox) ---
    ToolSchema("open_browser", "Open a URL in a browser session.",
               {"url": "http(s) URL.", "headless": "Optional bool."},
               ("url",)),
    ToolSchema("fetch_url", "Fetch a URL and return readable content.",
               {"url": "http(s) URL.", "max_length": "Optional char cap.",
                "start_index": "Optional offset into the content."},
               ("url",)),
    ToolSchema("web_search", "Search the web.",
               {"query": "The search query.",
                "max_results": "Optional, 1-50."}, ("query",)),
    ToolSchema("analyze_image", "Analyze an image with a vision model.",
               {"image_data": "Base64 image.",
                "prompt": "Optional instruction."}, ("image_data",)),
    ToolSchema("screenshot_to_code",
               "Generate UI code from a screenshot or URL.",
               {"source": "'image' or 'url'.", "image_data": "Base64 image.",
                "url": "Source URL.", "stack": "Target framework."},
               ("source",)),
    ToolSchema("api_request", "Make an HTTP API request.",
               {"url": "http(s) URL.", "method": "GET/POST/…",
                "headers": "Optional JSON object.",
                "body": "Optional request body."}, ("url",)),
    # --- documents (gated) ---
    ToolSchema("read_document",
               "Read text from a document (docx/xlsx/pptx/pdf).",
               {"uri": _URI, "start_index": "Optional offset.",
                "max_length": "Optional char cap."}, ("uri",)),
    ToolSchema("edit_document", "Edit a document's text content.",
               {"uri": _URI, "content": "New content.",
                "replacements": "Optional find/replace list."}, ("uri",)),
    ToolSchema("create_document", "Create a new document.",
               {"type": "'word' | 'excel' | 'ppt'.",
                "file_path": "Target path.",
                "document_data": "Structured content."},
               ("type", "file_path", "document_data")),
    ToolSchema("pdf_operation", "Merge/split/watermark PDFs.",
               {"operation": "'merge' | 'split' | 'watermark'.",
                "input_files": "Inputs.", "output_path": "Output."},
               ("operation",)),
    ToolSchema("document_convert", "Convert a document between formats.",
               {"input_file": "Source.", "output_path": "Target.",
                "format": "Optional target format."},
               ("input_file", "output_path")),
    ToolSchema("document_merge", "Merge multiple documents into one.",
               {"input_files": "Inputs.", "output_path": "Output."},
               ("input_files", "output_path")),
    ToolSchema("document_extract", "Extract structured data from documents.",
               {"input_file": "Source.", "extract_type": "What to extract."},
               ("input_file",)),
    # --- agents ---
    ToolSchema("spawn_subagent",
               "Spawn a specialized subagent to work on a subtask in "
               "parallel; returns its final report.",
               {"agent_type": "One of the registered subagent types.",
                "task": "The subtask description.",
                "context": "Optional extra context."},
               ("agent_type", "task")),
    ToolSchema("edit_agent",
               "Delegate a code edit to the dedicated edit agent.",
               {"uri": _URI, "instructions": "What to change.",
                "mode": "'edit' | 'create' | 'overwrite'."},
               ("uri", "instructions")),
    ToolSchema("skill",
               "Load a named skill's full instructions on demand.",
               {"name": "The skill name."}, ("name",)),
]}

assert set(TOOL_SCHEMAS) == set(BUILTIN_TOOL_NAMES), (
    set(TOOL_SCHEMAS) ^ set(BUILTIN_TOOL_NAMES))
