"""Tool-system types: names, approval classes, results, errors.

Mirrors the reference's `common/toolsServiceTypes.ts`: the 31 active builtin
tool names (BuiltinToolCallParams :51-162), the approval-type map
(approvalTypeOfBuiltinToolName :28-37 — edits / terminal / MCP tools), and the
result envelope the agent loop consumes. The TPU build's rollout sandbox keeps
the same names and approval classes so traces produced here feed the same
reward dimensions (tool_success_rate etc., traceCollectorService.ts:697-729).
"""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import Any, Dict, Optional

# The 31 active builtin tools (toolsServiceTypes.ts:51-162; registry rendered
# into the system prompt from prompt/prompts.ts:225-718 `builtinTools`).
CONTEXT_TOOLS = (
    "read_file", "ls_dir", "get_dir_tree", "search_pathnames_only",
    "search_for_files", "search_in_file", "read_lint_errors",
)
EDIT_TOOLS = (
    "create_file_or_folder", "delete_file_or_folder", "edit_file",
    "rewrite_file",
)
TERMINAL_TOOLS = (
    "run_command", "run_persistent_command", "open_persistent_terminal",
    "kill_persistent_terminal",
)
NETWORK_TOOLS = (
    "open_browser", "fetch_url", "web_search", "analyze_image",
    "screenshot_to_code", "api_request",
)
DOCUMENT_TOOLS = (
    "read_document", "edit_document", "create_document", "pdf_operation",
    "document_convert", "document_merge", "document_extract",
)
AGENT_TOOLS = ("spawn_subagent", "edit_agent", "skill")

BUILTIN_TOOL_NAMES = (CONTEXT_TOOLS + EDIT_TOOLS + TERMINAL_TOOLS
                      + NETWORK_TOOLS + DOCUMENT_TOOLS + AGENT_TOOLS)


class ApprovalType(str, enum.Enum):
    """Approval classes gating tool execution
    (toolsServiceTypes.ts:28-44)."""
    EDITS = "edits"
    TERMINAL = "terminal"
    MCP = "MCP tools"


# approvalTypeOfBuiltinToolName (toolsServiceTypes.ts:28-37): only edit and
# terminal tools require approval; everything else auto-runs.
APPROVAL_TYPE_OF_TOOL: Dict[str, ApprovalType] = {
    "create_file_or_folder": ApprovalType.EDITS,
    "delete_file_or_folder": ApprovalType.EDITS,
    "rewrite_file": ApprovalType.EDITS,
    "edit_file": ApprovalType.EDITS,
    "edit_document": ApprovalType.EDITS,
    "create_document": ApprovalType.EDITS,
    "run_command": ApprovalType.TERMINAL,
    "run_persistent_command": ApprovalType.TERMINAL,
    "open_persistent_terminal": ApprovalType.TERMINAL,
    "kill_persistent_terminal": ApprovalType.TERMINAL,
}


class ToolValidationError(ValueError):
    """Raised by validate_params — maps to the reference's throw-in-validate
    pattern (toolsService.ts:860-934); the agent loop feeds the message back
    to the model as a tool error (chatThreadService.ts:963-982)."""


class ToolDeniedError(PermissionError):
    """Tool required approval and the rollout policy denied it
    (approval gate, chatThreadService.ts:984-992)."""


class ToolUnavailableError(RuntimeError):
    """Tool exists in the registry but its backend is not available in the
    hermetic sandbox (network/document sidecars, start*.cjs — absent here
    unless an external handler is registered)."""


@dataclasses.dataclass
class ToolResult:
    """Envelope returned by ToolsService.call_tool — the analogue of the
    {result, interrupted} shape _runToolCall builds
    (chatThreadService.ts:1089-1167)."""
    tool: str
    params: Dict[str, Any]
    result: Any = None
    error: Optional[str] = None
    duration_ms: float = 0.0
    started_at: float = dataclasses.field(default_factory=time.time)

    @property
    def ok(self) -> bool:
        return self.error is None
