"""SEARCH/REPLACE block extraction + application.

Implements the reference's fast-apply edit format: blocks delimited by
``<<<<<<< ORIGINAL`` / ``=======`` / ``>>>>>>> UPDATED`` markers
(prompt/prompts.ts:38-40), extracted as in
`browser/helpers/extractCodeFromResult.ts` and applied as in
`editCodeService.ts:1296` (instantlyApplySearchReplaceBlocks). Matching is
exact-first with a whitespace-tolerant fallback so minor indentation drift in
model output still applies — malformed blocks raise, and the agent loop's
retry policy (editCodeService.ts:1997 retry-on-malformed) regenerates.
"""

from __future__ import annotations

import dataclasses
import re
from typing import List

ORIGINAL = "<<<<<<< ORIGINAL"
DIVIDER = "======="
FINAL = ">>>>>>> UPDATED"


class MalformedBlocksError(ValueError):
    pass


class SearchNotFoundError(ValueError):
    pass


@dataclasses.dataclass
class SearchReplaceBlock:
    original: str
    updated: str


def extract_blocks(text: str) -> List[SearchReplaceBlock]:
    """Parse all SEARCH/REPLACE blocks out of model output.

    Tolerates surrounding prose and code fences; raises MalformedBlocksError
    when markers are absent or unbalanced (the validate-time error of
    toolsService.ts:1257-1283)."""
    if ORIGINAL not in text:
        preview = text[:100]
        raise MalformedBlocksError(
            f'search/replace blocks must contain "{ORIGINAL}" markers. '
            f'Received: "{preview}...". To replace an entire file use '
            f"rewrite_file; otherwise use the {ORIGINAL} / {DIVIDER} / "
            f"{FINAL} format.")
    blocks: List[SearchReplaceBlock] = []
    # Scan line-wise so ======= inside code doesn't split a block.
    lines = text.split("\n")
    i = 0
    while i < len(lines):
        if lines[i].strip() != ORIGINAL:
            i += 1
            continue
        orig: List[str] = []
        upd: List[str] = []
        i += 1
        while i < len(lines) and lines[i].strip() != DIVIDER:
            if lines[i].strip() == ORIGINAL or lines[i].strip() == FINAL:
                raise MalformedBlocksError(
                    f"unbalanced block: expected {DIVIDER} before "
                    f"{lines[i].strip()}")
            orig.append(lines[i])
            i += 1
        if i >= len(lines):
            raise MalformedBlocksError(f"missing {DIVIDER} divider")
        i += 1
        while i < len(lines) and lines[i].strip() != FINAL:
            if lines[i].strip() in (ORIGINAL, DIVIDER):
                raise MalformedBlocksError(
                    f"unbalanced block: expected {FINAL} before "
                    f"{lines[i].strip()}")
            upd.append(lines[i])
            i += 1
        if i >= len(lines):
            raise MalformedBlocksError(f"missing {FINAL} terminator")
        i += 1
        blocks.append(SearchReplaceBlock("\n".join(orig), "\n".join(upd)))
    if not blocks:
        raise MalformedBlocksError("no complete SEARCH/REPLACE blocks found")
    return blocks


def _find_whitespace_tolerant(content: str, needle: str) -> tuple[int, int]:
    """Locate needle ignoring per-line leading/trailing whitespace; returns
    (start, end) char offsets in content, or (-1, -1)."""
    c_lines = content.split("\n")
    n_lines = [ln.strip() for ln in needle.split("\n")]
    # Drop leading/trailing blank needle lines for matching purposes.
    while n_lines and not n_lines[0]:
        n_lines.pop(0)
    while n_lines and not n_lines[-1]:
        n_lines.pop()
    if not n_lines:
        return -1, -1
    stripped = [ln.strip() for ln in c_lines]
    for start in range(len(c_lines) - len(n_lines) + 1):
        if stripped[start:start + len(n_lines)] == n_lines:
            off = sum(len(ln) + 1 for ln in c_lines[:start])
            end = off + sum(len(ln) + 1
                            for ln in c_lines[start:start + len(n_lines)]) - 1
            return off, end
    return -1, -1


def apply_blocks(content: str, blocks: List[SearchReplaceBlock]) -> str:
    """Apply blocks in order; each ORIGINAL must match exactly once (first
    occurrence wins, as in the reference's sequential apply)."""
    for b in blocks:
        if b.original == "" or b.original.strip() == "":
            # Empty ORIGINAL ⇒ append (create-into-empty-file semantics).
            content = content + b.updated if content else b.updated
            continue
        idx = content.find(b.original)
        if idx >= 0:
            content = content[:idx] + b.updated + content[idx +
                                                          len(b.original):]
            continue
        s, e = _find_whitespace_tolerant(content, b.original)
        if s < 0:
            snippet = b.original.strip().split("\n")[0][:80]
            raise SearchNotFoundError(
                f"ORIGINAL text not found in file (starts with: "
                f'"{snippet}"). Re-read the file and use exact text.')
        content = content[:s] + b.updated + content[e:]
    return content


def apply_search_replace(content: str, blocks_text: str) -> str:
    """extract + apply in one step (the edit_file tool path)."""
    return apply_blocks(content, extract_blocks(blocks_text))


def surrounding_blocks_format_doc() -> str:
    """The format documentation injected into edit prompts
    (searchReplaceBlockTemplate, prompts.ts:44-57)."""
    return (f"{ORIGINAL}\n<exact text from read_file output>\n{DIVIDER}\n"
            f"<modified version of the text>\n{FINAL}")
