"""Fleet-side learner gateway: lease authority + fenced publish over rpc.

The serving fleet is the single authority over its replicas, so it is
also the natural home for the learner lease: colocating the
:class:`~..resilience.lease.LeaseStore` with the fleet gives
single-writer semantics without a coordination service. The handler
exposes exactly the surface a disaggregated learner needs:

====================  ========================================================
method                semantics
====================  ========================================================
acquire_lease         grant the publish lease at a fresh (higher) fencing
                      epoch; a restarted learner fences out its zombie twin
renew_lease           heartbeat; raises ``LeaseLost`` when superseded/expired
release_lease         voluntary release (the epoch is retired, never reused)
publish               STAGE a fenced ``(epoch, version)`` publish; the
                      fleet's own pump rolls it replica by replica. Validated
                      twice: live-lease check here, monotonic high-water
                      check in ``WeightPublisher.begin``. Idempotent under
                      retried request ids — a publish whose response was
                      lost replays instead of staging twice.
publish_status        roll progress + convergence; in manual-pump fleets each
                      poll also advances the fleet one step, so a learner
                      polling over loopback drives the roll it is waiting on
signals / fleet_stats the autoscaler-ish load surface (queue depth, sheds,
                      versions) a learner or operator reads over the wire
====================  ========================================================

Publishes are a resumable saga: stage (durable fleet-side) → roll
(advanced by the fleet pump, partition-tolerant via quarantine) →
confirm (the learner polls ``publish_status``). A learner killed after
stage loses nothing — the roll still lands; its successor re-acquires
the lease at a higher epoch and republishes its last durable version,
which supersedes any torn roll.

:func:`serve_fleet_http` puts the handler on a real socket (same JSON
frame as the engine shim); tests run it behind ``LoopbackTransport``
with a ``NetworkFaultPlan`` for deterministic partition chaos.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..obs.federation import MetricsScrapeMixin
from ..resilience.lease import LeaseStore
from .remote_server import RpcHandlerBase, serve_rpc_http
from .replica import DEAD

# Publish staging consults the idempotency cache (a staged publish
# whose response was lost must REPLAY, never double-stage), as does
# federation ``scrape`` (a retried scrape must replay the same delta,
# its cursor already advanced).
LEARNER_MUTATING_METHODS = frozenset({"publish", "publish_adapter",
                                      "scrape"})
# Lease mutations are deliberately NOT cached — re-executing them on a
# retry is safe (acquire grants a fresh higher epoch, renew/release
# are idempotent on live state), whereas caching them lets a restarted
# client whose request ids collide with a previous incarnation replay
# that incarnation's lease grant and run at a zombie epoch, defeating
# the fencing (rpc_lint RPC103 keeps them OUT of the cached set).
# publish_status rides along: each manual-pump poll advances the fleet
# one step, so it mutates, but an extra step on a retry is harmless.
LEARNER_REEXECUTE_SAFE_METHODS = frozenset({
    "acquire_lease", "renew_lease", "release_lease", "publish_status"})
# Reads; never cached, must see fresh state.
LEARNER_READONLY_METHODS = frozenset({"signals", "fleet_stats",
                                      "health"})


class FleetRpcHandler(MetricsScrapeMixin, RpcHandlerBase):
    """Lease + fenced-publish dispatch table over one ServingFleet."""

    mutating_methods = LEARNER_MUTATING_METHODS
    readonly_methods = LEARNER_READONLY_METHODS
    reexecute_safe_methods = LEARNER_REEXECUTE_SAFE_METHODS
    # Stitched-trace role: spans from this handler belong to the
    # fleet/learner gateway process (see obs/propagation.py).
    span_service = "fleet"

    def __init__(self, fleet, *, lease_store: Optional[LeaseStore] = None,
                 lease_ttl_s: float = 30.0, clock=None,
                 idempotency_cache_size: int = 1024, registry=None):
        super().__init__(idempotency_cache_size=idempotency_cache_size)
        self.fleet = fleet
        self.clock = clock if clock is not None else fleet.clock
        if registry is None:
            registry = fleet.registry
        self.lease_store = lease_store or LeaseStore(
            ttl_s=lease_ttl_s, registry=registry)

    # -- lease ---------------------------------------------------------------
    def _m_acquire_lease(self, holder, steal=False) -> Dict[str, Any]:
        """Reexecute-safe, never cached: re-execution grants a fresh
        HIGHER epoch, while a cached replay could hand a restarted
        client a previous incarnation's (zombie) epoch."""
        lease = self.lease_store.acquire(str(holder), now=self.clock(),
                                         steal=bool(steal))
        return {"epoch": lease.epoch, "expires_at": lease.expires_at,
                "ttl_s": self.lease_store.ttl_s}

    def _m_renew_lease(self, holder, epoch) -> Dict[str, Any]:
        """Reexecute-safe: renewal is idempotent on LIVE state; a
        cached replay could acknowledge an epoch that has since been
        superseded."""
        lease = self.lease_store.renew(str(holder), int(epoch),
                                       now=self.clock())
        return {"epoch": lease.epoch, "expires_at": lease.expires_at}

    def _m_release_lease(self, holder, epoch) -> Dict[str, Any]:
        """Reexecute-safe: releasing an already-released epoch is a
        no-op on live state, so retries need no cache."""
        return {"released": self.lease_store.release(str(holder),
                                                     int(epoch))}

    # -- publish saga --------------------------------------------------------
    def _m_publish(self, params, epoch, version,
                   eager=False) -> Dict[str, Any]:
        """Cached-mutating: a staged publish whose response was lost
        must REPLAY on retry, never double-stage."""
        # Fencing check 1: the epoch must be the LIVE lease (raises
        # LeaseLost across the wire). Check 2 is the publisher's own
        # monotonic high-water mark — both must pass. ``eager``
        # requests the no-drain roll (the streaming learner's default).
        self.lease_store.validate(int(epoch), now=self.clock())
        v = self.fleet.begin_publish(params, epoch=int(epoch),
                                     version=int(version),
                                     eager=bool(eager))
        return {"version": v, "epoch": int(epoch), "staged": True,
                "eager": bool(eager)}

    def _m_publish_adapter(self, tenant_id, lora, epoch,
                           version=None) -> Dict[str, Any]:
        """Cached-mutating: a lost-response retry replays the first
        apply instead of re-installing the adapter."""
        # Same double fencing as _m_publish (live lease here, per-
        # tenant monotonic watermark in WeightPublisher), but the
        # apply is immediate and no-drain: there is no roll to poll.
        self.lease_store.validate(int(epoch), now=self.clock())
        v = self.fleet.publish_adapter(
            str(tenant_id), lora, epoch=int(epoch),
            version=None if version is None else int(version))
        return {"tenant_id": str(tenant_id), "version": v,
                "epoch": int(epoch), "applied": True}

    def _m_publish_status(self) -> Dict[str, Any]:
        """Reexecute-safe: each poll may pump the fleet one step, so it
        mutates, but a retry's extra step is harmless and the caller
        needs FRESH roll progress, never a cached replay."""
        # Manual-pump fleets advance one step per poll so a loopback
        # learner's status loop drives the roll it waits on; threaded
        # fleets are already pumped by their dispatcher.
        if not self.fleet.threaded:
            self.fleet.step()
        pub = self.fleet.publisher
        versions = [r.weight_version for r in self.fleet.replicas
                    if r.state != DEAD]
        return {
            "in_progress": pub.in_progress,
            "version": pub.version,
            "epoch": pub.epoch,
            "skew": pub.skew(),
            "replicas_live": len(versions),
            "min_version": min(versions) if versions else 0,
            "max_version": max(versions) if versions else 0,
            "converged": (not pub.in_progress and versions != []
                          and min(versions) == max(versions)
                          == pub.version),
        }

    # -- load / health surface -----------------------------------------------
    def _m_signals(self) -> Dict[str, Any]:
        pub = self.fleet.publisher
        return {
            "queue_depth": self.fleet.admission.depth(),
            "replicas_live": sum(r.state != DEAD
                                 for r in self.fleet.replicas),
            "weight_version": pub.version,
            "publish_epoch": pub.epoch,
            "publish_in_progress": pub.in_progress,
        }

    def _m_fleet_stats(self) -> Dict[str, Any]:
        s = self.fleet.stats()
        return {k: s[k] for k in (
            "replicas_live", "queue_depth", "pending", "completed",
            "rejected", "weight_version", "publish_epoch",
            "weight_version_skew", "publish_in_progress") if k in s}

    def _m_health(self) -> Dict[str, Any]:
        return {"state": "ok",
                "replicas_live": sum(r.state != DEAD
                                     for r in self.fleet.replicas)}


def serve_fleet_http(fleet_or_handler, *, host: str = "127.0.0.1",
                     port: int = 0):
    """Serve a fleet's learner gateway over real HTTP; returns
    ``(server, port)`` (started daemon ``ThreadingHTTPServer``)."""
    handler = (fleet_or_handler
               if isinstance(fleet_or_handler, FleetRpcHandler)
               else FleetRpcHandler(fleet_or_handler))
    return serve_rpc_http(handler, host=host, port=port,
                          thread_name="serve-learner-http")


# -- standalone lease authority (satellite: shared across fleets) ------------

LEASE_MUTATING_METHODS = frozenset({"scrape"})
# No LEASE op is cached, on purpose — the PR-7 zombie-grant rule in
# its new topology:
# idempotency-caching a lease grant would let a restarted client whose
# request ids collide with a previous incarnation REPLAY that
# incarnation's epoch and write as a zombie. Re-EXECUTING lease ops on
# a retried request id is always safe (acquire grants a fresh higher
# epoch; renew/release act on live state), so the mutating lease ops
# live in the reexecute-safe set (rpc_lint RPC103 fails the gate if
# one ever migrates into the cached set). ``scrape`` (federation delta
# shipping) is the one exception: its per-scraper cursor makes replays
# the only safe retry.
LEASE_REEXECUTE_SAFE_METHODS = frozenset({
    "acquire_lease", "renew_lease", "release_lease"})
# validate_lease only READS (it raises when the epoch isn't live);
# lease_info/health are plain reads.
LEASE_READONLY_METHODS = frozenset({"validate_lease", "lease_info",
                                    "health"})


class LeaseRpcHandler(MetricsScrapeMixin, RpcHandlerBase):
    """The learner lease as its OWN process: one
    :class:`~..resilience.lease.LeaseStore` behind an rpc endpoint, so
    several fleets can share a single learner (each fleet's
    :class:`FleetRpcHandler` delegates through a
    :class:`RemoteLeaseStore`) without any fleet being the authority.
    Time is always THIS process's clock — lease validity must not
    depend on N fleet clocks agreeing."""

    mutating_methods = LEASE_MUTATING_METHODS
    readonly_methods = LEASE_READONLY_METHODS
    reexecute_safe_methods = LEASE_REEXECUTE_SAFE_METHODS
    span_service = "lease"

    def __init__(self, store: Optional[LeaseStore] = None, *,
                 ttl_s: float = 30.0, clock=None,
                 idempotency_cache_size: int = 256, registry=None):
        super().__init__(idempotency_cache_size=idempotency_cache_size)
        import time as _time
        self.store = store or LeaseStore(ttl_s=ttl_s, registry=registry)
        self.clock = clock if clock is not None else _time.monotonic

    def _m_acquire_lease(self, holder, steal=False) -> Dict[str, Any]:
        """Reexecute-safe, never cached: re-execution grants a fresh
        HIGHER epoch; a cached replay would resurrect a zombie one."""
        lease = self.store.acquire(str(holder), now=self.clock(),
                                   steal=bool(steal))
        return {"epoch": lease.epoch, "expires_at": lease.expires_at,
                "ttl_s": self.store.ttl_s}

    def _m_renew_lease(self, holder, epoch) -> Dict[str, Any]:
        """Reexecute-safe: idempotent on live state; replay could
        acknowledge a superseded epoch."""
        lease = self.store.renew(str(holder), int(epoch),
                                 now=self.clock())
        return {"epoch": lease.epoch, "expires_at": lease.expires_at}

    def _m_release_lease(self, holder, epoch) -> Dict[str, Any]:
        """Reexecute-safe: double-release is a no-op on live state."""
        return {"released": self.store.release(str(holder), int(epoch))}

    def _m_validate_lease(self, epoch) -> Dict[str, Any]:
        # Raises LeaseLost across the wire when ``epoch`` isn't live —
        # the fencing check a fleet runs before staging a publish.
        self.store.validate(int(epoch), now=self.clock())
        return {"valid": True, "epoch": int(epoch)}

    def _m_lease_info(self) -> Dict[str, Any]:
        cur = self.store.current()
        return {"ttl_s": self.store.ttl_s,
                "epoch": self.store.current_epoch,
                "holder": cur.holder if cur is not None else None}

    def _m_health(self) -> Dict[str, Any]:
        return {"state": "ok", "epoch": self.store.current_epoch}


class RemoteLeaseStore:
    """Client-side duck of :class:`~..resilience.lease.LeaseStore` over
    rpc — what a fleet injects as ``FleetRpcHandler(lease_store=...)``
    when the lease authority runs in its own process. The surface
    matches the in-memory store (acquire/renew/release/validate +
    ``ttl_s``); callers' ``now=`` kwargs are accepted for signature
    compatibility but IGNORED — the authority's clock is the only one
    that counts. Typed lease errors (``LeaseLost``,
    ``LeaseUnavailable``) rehydrate across the wire as themselves."""

    def __init__(self, transport, *, name: Optional[str] = None,
                 policy=None, clock=None, sleep=None, rng=None,
                 registry=None):
        from ..resilience.retry import RetryPolicy
        from .learner import FleetPublishClient
        import time as _time
        self._rpc = FleetPublishClient(
            transport, name=name,
            policy=policy or RetryPolicy(max_retries=3,
                                         base_delay_s=0.05,
                                         max_delay_s=2.0),
            clock=clock if clock is not None else _time.monotonic,
            sleep=sleep, rng=rng, registry=registry)
        self.name = self._rpc.name
        self._ttl_s: Optional[float] = None

    @property
    def ttl_s(self) -> float:
        if self._ttl_s is None:
            self._ttl_s = float(self._rpc._call("lease_info")["ttl_s"])
        return self._ttl_s

    def acquire(self, holder: str, *, now: Optional[float] = None,
                steal: bool = False):
        from ..resilience.lease import Lease
        out = self._rpc._call("acquire_lease",
                              {"holder": str(holder),
                               "steal": bool(steal)})
        self._ttl_s = float(out.get("ttl_s", self._ttl_s or 30.0))
        return Lease(holder=str(holder), epoch=int(out["epoch"]),
                     expires_at=float(out["expires_at"]))

    def renew(self, holder: str, epoch: int, *,
              now: Optional[float] = None):
        from ..resilience.lease import Lease
        out = self._rpc._call("renew_lease",
                              {"holder": str(holder),
                               "epoch": int(epoch)})
        return Lease(holder=str(holder), epoch=int(out["epoch"]),
                     expires_at=float(out["expires_at"]))

    def release(self, holder: str, epoch: int) -> bool:
        out = self._rpc._call("release_lease",
                              {"holder": str(holder),
                               "epoch": int(epoch)})
        return bool(out.get("released"))

    def validate(self, epoch: int, *,
                 now: Optional[float] = None) -> None:
        self._rpc._call("validate_lease", {"epoch": int(epoch)})


def serve_lease_http(store_or_handler=None, *, host: str = "127.0.0.1",
                     port: int = 0, ttl_s: float = 30.0):
    """Serve a standalone lease authority over real HTTP; returns
    ``(server, port)``."""
    handler = (store_or_handler
               if isinstance(store_or_handler, LeaseRpcHandler)
               else LeaseRpcHandler(store_or_handler, ttl_s=ttl_s))
    return serve_rpc_http(handler, host=host, port=port,
                          thread_name="serve-lease-http")


# -- streaming experience intake (learner-side endpoint) ---------------------

EXPERIENCE_MUTATING_METHODS = frozenset({"submit_episodes", "scrape"})
# submit_episodes IS idempotency-cached: a batch whose ack frame was
# lost (drop_response chaos) must REPLAY the recorded acks, not
# re-offer — the queue's seen-set would ack "duplicate" anyway, but
# replaying keeps the collector's view of each episode's FIRST outcome
# stable (an episode accepted then evicted must not flap to "stale" on
# the retry of the same request).
EXPERIENCE_READONLY_METHODS = frozenset({"stream_stats", "health"})


class ExperienceRpcHandler(MetricsScrapeMixin, RpcHandlerBase):
    """Collector→learner episode intake over rpc. Wraps a
    :class:`~.learner.StreamingLearnerService` (or any object with
    ``intake(episodes)`` / ``stream_stats()``)."""

    mutating_methods = EXPERIENCE_MUTATING_METHODS
    readonly_methods = EXPERIENCE_READONLY_METHODS
    span_service = "learner"

    def __init__(self, learner, *, idempotency_cache_size: int = 1024):
        super().__init__(idempotency_cache_size=idempotency_cache_size)
        self.learner = learner

    def _m_submit_episodes(self, episodes) -> Dict[str, Any]:
        """Cached-mutating: a batch whose ack frame was lost must
        REPLAY the recorded acks on retry — re-offering would flap an
        accepted-then-evicted episode's outcome to "stale"."""
        from ..training.experience import StreamedEpisode
        eps = [e if isinstance(e, StreamedEpisode)
               else StreamedEpisode.from_wire(dict(e))
               for e in episodes]
        return self.learner.intake(eps)

    def _m_stream_stats(self) -> Dict[str, Any]:
        return self.learner.stream_stats()

    def _m_health(self) -> Dict[str, Any]:
        return {"state": "ok"}


def serve_experience_http(learner_or_handler, *,
                          host: str = "127.0.0.1", port: int = 0):
    """Serve a streaming learner's episode intake over real HTTP;
    returns ``(server, port)``."""
    handler = (learner_or_handler
               if isinstance(learner_or_handler, ExperienceRpcHandler)
               else ExperienceRpcHandler(learner_or_handler))
    return serve_rpc_http(handler, host=host, port=port,
                          thread_name="serve-experience-http")
