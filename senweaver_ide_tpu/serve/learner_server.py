"""Fleet-side learner gateway: lease authority + fenced publish over rpc.

The serving fleet is the single authority over its replicas, so it is
also the natural home for the learner lease: colocating the
:class:`~..resilience.lease.LeaseStore` with the fleet gives
single-writer semantics without a coordination service. The handler
exposes exactly the surface a disaggregated learner needs:

====================  ========================================================
method                semantics
====================  ========================================================
acquire_lease         grant the publish lease at a fresh (higher) fencing
                      epoch; a restarted learner fences out its zombie twin
renew_lease           heartbeat; raises ``LeaseLost`` when superseded/expired
release_lease         voluntary release (the epoch is retired, never reused)
publish               STAGE a fenced ``(epoch, version)`` publish; the
                      fleet's own pump rolls it replica by replica. Validated
                      twice: live-lease check here, monotonic high-water
                      check in ``WeightPublisher.begin``. Idempotent under
                      retried request ids — a publish whose response was
                      lost replays instead of staging twice.
publish_status        roll progress + convergence; in manual-pump fleets each
                      poll also advances the fleet one step, so a learner
                      polling over loopback drives the roll it is waiting on
signals / fleet_stats the autoscaler-ish load surface (queue depth, sheds,
                      versions) a learner or operator reads over the wire
====================  ========================================================

Publishes are a resumable saga: stage (durable fleet-side) → roll
(advanced by the fleet pump, partition-tolerant via quarantine) →
confirm (the learner polls ``publish_status``). A learner killed after
stage loses nothing — the roll still lands; its successor re-acquires
the lease at a higher epoch and republishes its last durable version,
which supersedes any torn roll.

:func:`serve_fleet_http` puts the handler on a real socket (same JSON
frame as the engine shim); tests run it behind ``LoopbackTransport``
with a ``NetworkFaultPlan`` for deterministic partition chaos.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..resilience.lease import LeaseStore
from .remote_server import RpcHandlerBase, serve_rpc_http
from .replica import DEAD

# Only publish staging consults the idempotency cache: a staged
# publish whose response was lost must REPLAY, never double-stage.
# Lease mutations are deliberately NOT cached — re-executing them on a
# retry is safe (acquire grants a fresh higher epoch, renew/release
# are idempotent on live state), whereas caching them lets a restarted
# client whose request ids collide with a previous incarnation replay
# that incarnation's lease grant and run at a zombie epoch, defeating
# the fencing. Status/signals are reads and must see fresh state.
LEARNER_MUTATING_METHODS = frozenset({"publish", "publish_adapter"})


class FleetRpcHandler(RpcHandlerBase):
    """Lease + fenced-publish dispatch table over one ServingFleet."""

    mutating_methods = LEARNER_MUTATING_METHODS
    # Stitched-trace role: spans from this handler belong to the
    # fleet/learner gateway process (see obs/propagation.py).
    span_service = "fleet"

    def __init__(self, fleet, *, lease_store: Optional[LeaseStore] = None,
                 lease_ttl_s: float = 30.0, clock=None,
                 idempotency_cache_size: int = 1024, registry=None):
        super().__init__(idempotency_cache_size=idempotency_cache_size)
        self.fleet = fleet
        self.clock = clock if clock is not None else fleet.clock
        if registry is None:
            registry = fleet.registry
        self.lease_store = lease_store or LeaseStore(
            ttl_s=lease_ttl_s, registry=registry)

    # -- lease ---------------------------------------------------------------
    def _m_acquire_lease(self, holder, steal=False) -> Dict[str, Any]:
        lease = self.lease_store.acquire(str(holder), now=self.clock(),
                                         steal=bool(steal))
        return {"epoch": lease.epoch, "expires_at": lease.expires_at,
                "ttl_s": self.lease_store.ttl_s}

    def _m_renew_lease(self, holder, epoch) -> Dict[str, Any]:
        lease = self.lease_store.renew(str(holder), int(epoch),
                                       now=self.clock())
        return {"epoch": lease.epoch, "expires_at": lease.expires_at}

    def _m_release_lease(self, holder, epoch) -> Dict[str, Any]:
        return {"released": self.lease_store.release(str(holder),
                                                     int(epoch))}

    # -- publish saga --------------------------------------------------------
    def _m_publish(self, params, epoch, version) -> Dict[str, Any]:
        # Fencing check 1: the epoch must be the LIVE lease (raises
        # LeaseLost across the wire). Check 2 is the publisher's own
        # monotonic high-water mark — both must pass.
        self.lease_store.validate(int(epoch), now=self.clock())
        v = self.fleet.begin_publish(params, epoch=int(epoch),
                                     version=int(version))
        return {"version": v, "epoch": int(epoch), "staged": True}

    def _m_publish_adapter(self, tenant_id, lora, epoch,
                           version=None) -> Dict[str, Any]:
        # Same double fencing as _m_publish (live lease here, per-
        # tenant monotonic watermark in WeightPublisher), but the
        # apply is immediate and no-drain: there is no roll to poll.
        self.lease_store.validate(int(epoch), now=self.clock())
        v = self.fleet.publish_adapter(
            str(tenant_id), lora, epoch=int(epoch),
            version=None if version is None else int(version))
        return {"tenant_id": str(tenant_id), "version": v,
                "epoch": int(epoch), "applied": True}

    def _m_publish_status(self) -> Dict[str, Any]:
        # Manual-pump fleets advance one step per poll so a loopback
        # learner's status loop drives the roll it waits on; threaded
        # fleets are already pumped by their dispatcher.
        if not self.fleet.threaded:
            self.fleet.step()
        pub = self.fleet.publisher
        versions = [r.weight_version for r in self.fleet.replicas
                    if r.state != DEAD]
        return {
            "in_progress": pub.in_progress,
            "version": pub.version,
            "epoch": pub.epoch,
            "skew": pub.skew(),
            "replicas_live": len(versions),
            "min_version": min(versions) if versions else 0,
            "max_version": max(versions) if versions else 0,
            "converged": (not pub.in_progress and versions != []
                          and min(versions) == max(versions)
                          == pub.version),
        }

    # -- load / health surface -----------------------------------------------
    def _m_signals(self) -> Dict[str, Any]:
        pub = self.fleet.publisher
        return {
            "queue_depth": self.fleet.admission.depth(),
            "replicas_live": sum(r.state != DEAD
                                 for r in self.fleet.replicas),
            "weight_version": pub.version,
            "publish_epoch": pub.epoch,
            "publish_in_progress": pub.in_progress,
        }

    def _m_fleet_stats(self) -> Dict[str, Any]:
        s = self.fleet.stats()
        return {k: s[k] for k in (
            "replicas_live", "queue_depth", "pending", "completed",
            "rejected", "weight_version", "publish_epoch",
            "weight_version_skew", "publish_in_progress") if k in s}

    def _m_health(self) -> Dict[str, Any]:
        return {"state": "ok",
                "replicas_live": sum(r.state != DEAD
                                     for r in self.fleet.replicas)}


def serve_fleet_http(fleet_or_handler, *, host: str = "127.0.0.1",
                     port: int = 0):
    """Serve a fleet's learner gateway over real HTTP; returns
    ``(server, port)`` (started daemon ``ThreadingHTTPServer``)."""
    handler = (fleet_or_handler
               if isinstance(fleet_or_handler, FleetRpcHandler)
               else FleetRpcHandler(fleet_or_handler))
    return serve_rpc_http(handler, host=host, port=port,
                          thread_name="serve-learner-http")
