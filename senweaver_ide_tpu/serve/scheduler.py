"""Global fleet scheduler + live-migration coordinator.

Before this module the fleet had three sanctioned ways to hurt a
request: the KV pressure ladder truncate-finishes a decode at the
preempt cap, an eager (no-drain) weight publish degrades to classic
draining when its patience runs out, and autoscale scale-down is
drain-then-kill. All three are the same problem — work is pinned to
the replica it started on — and all three get the same fix: checkpoint
the in-flight decode (``rollout/migration.py``), graft it onto a
replica with headroom, resume token-exactly.

Two objects:

* :class:`GlobalScheduler` — placement. Consumes the same per-replica
  signals the router balances on (KV pressure, remaining decode
  tokens, adapter residency) plus the federation store's staleness
  verdicts, and answers one question: *where should this decode go?*
  A replica whose gauges the fleet can no longer trust (stale peer) is
  never a migration target.

* :class:`MigrationCoordinator` — the two-phase handoff, run over the
  existing idempotency-keyed RPC layer:

  ::

      freeze (pause on source)
        → snapshot (checkpoint_request; ONE host gather)
          → fence check (same weight version on both ends, publisher
            quiescent — a publish landing mid-handoff forces a local
            finish on the source, NEVER a cross-version splice)
            → install on target (idempotency-keyed restore: at-least-
              once on the wire, exactly-once on the engine)
              → re-point fleet bookkeeping (router departure hook,
                source detach, target adopt)
                → ack on the target's FIRST post-migration token
                  → release on source

  The source keeps its frozen copy (blocks and all) until the ack: a
  target that dies mid-install or pre-first-token costs nothing — the
  coordinator resumes the source copy and the decode continues as if
  the handoff never happened (outcome ``rescued``). Completion is
  exactly-once because only ONE side is ever unpaused: the source
  until re-point, the target after, and the rescue path flips it back
  atomically under the fleet pump.

Failure outcomes (the ``outcome`` label on
``senweaver_serve_migrations_total``):

=================  ======================================================
``completed``      target acked its first post-migration token; source
                   copy released.
``rescued``        target died (or was partitioned into death) before
                   the ack; source copy resumed, decode finishes there.
``snapshot_abort`` checkpoint_request failed (source fault); request
                   simply resumes on the source.
``fence_abort``    a weight publish landed between snapshot and
                   install (version skew source↔target, or the
                   checkpoint's fence no longer matches) — local
                   finish on the source.
``install_abort``  restore RPC failed through its retry budget; source
                   copy resumed.
=================  ======================================================
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..obs.incidents import emit_event
from .admission import FleetRequest
from .replica import DEAD, LIVE, EngineReplica

# A migration target must have at least this much free KV pool
# (1 - kv_pressure) before we graft a decode onto it — grafting onto a
# replica that is itself about to preempt just moves the problem.
DEFAULT_MIN_HEADROOM = 0.15


@dataclasses.dataclass
class PendingMigration:
    """One handoff between install-on-target and first-token ack."""
    ticket: int
    source: Optional[EngineReplica]   # None once the source died
    source_rid: int
    target: EngineReplica
    target_rid: int
    reason: str
    started_at: float


class GlobalScheduler:
    """Fleet-wide placement for migrating decodes.

    Reads the replicas' own gauges directly (they are authoritative for
    local replicas and RPC-backed for remote ones) and uses the
    federation store only as a VETO: a peer whose scrapes have gone
    stale may be partitioned, and grafting a decode onto a replica we
    cannot observe trades a known-good copy for an unobservable one."""

    def __init__(self, replicas: Sequence[EngineReplica], *,
                 fleet_store=None,
                 min_headroom: float = DEFAULT_MIN_HEADROOM):
        self.replicas = list(replicas)
        self.fleet_store = fleet_store
        self.min_headroom = float(min_headroom)

    def pick_target(self, source: Optional[EngineReplica], *,
                    tenant_id: Optional[str] = None,
                    require_version: Optional[int] = None,
                    need_headroom: bool = True,
                    exclude: Sequence[str] = ()) -> Optional[EngineReplica]:
        """The best replica to receive a migrating decode, or None
        when nowhere qualifies (the caller falls back to the legacy
        degrade path — truncate / drain — which is exactly what this
        module exists to make rare, not impossible)."""
        excluded = set(exclude)
        cands: List[EngineReplica] = []
        for r in self.replicas:
            if r is source or r.replica_id in excluded:
                continue
            if not r.accepting:                 # LIVE + free slot
                continue
            if require_version is not None \
                    and r.weight_version != require_version:
                continue
            if need_headroom \
                    and (1.0 - r.kv_pressure) < self.min_headroom:
                continue
            if self.fleet_store is not None \
                    and self.fleet_store.is_stale(r.replica_id):
                continue
            cands.append(r)
        if not cands:
            return None
        if tenant_id is not None:
            resident = [r for r in cands
                        if r.has_adapter_resident(tenant_id)]
            if resident:
                cands = resident
        return min(cands, key=lambda r: (r.kv_pressure,
                                         r.outstanding_decode_tokens,
                                         r.outstanding))


class MigrationCoordinator:
    """Runs live handoffs and owns their metrics + pending-ack ledger.

    Wired by ``ServingFleet.attach_migration()``; the fleet pump calls
    :meth:`pump` each tick, ``_ingest`` feeds :meth:`note_progress`,
    ``_complete`` feeds :meth:`note_complete`, and ``_handle_death``
    calls :meth:`on_replica_death` BEFORE the router triages orphans
    (rescue must pull the migrated copy out of the dead target's
    in-flight map so it is not double-requeued)."""

    def __init__(self, router, publisher=None, *,
                 scheduler: Optional[GlobalScheduler] = None,
                 fleet_store=None, registry=None):
        self.router = router
        self.publisher = publisher
        self.scheduler = scheduler or GlobalScheduler(
            router.replicas, fleet_store=fleet_store)
        # ticket -> PendingMigration (install done, first token pending)
        self.pending: Dict[int, PendingMigration] = {}
        # monotonically counts handoffs for idempotency-key uniqueness
        self._seq = 0
        if registry is None:
            from ..obs import get_registry
            registry = get_registry()
        self._migrations_total = registry.counter(
            "senweaver_serve_migrations_total",
            "Live decode migrations by trigger and outcome.",
            labelnames=("reason", "outcome"))
        self._migration_ms = registry.histogram(
            "senweaver_serve_migration_ms",
            "Wall ms from freeze to install-acked re-point (the ack "
            "itself lands with the target's next emitted token).")

    # -- the handoff ---------------------------------------------------------
    def migrate(self, req: FleetRequest, source: EngineReplica,
                target: EngineReplica, *, reason: str,
                now: float) -> bool:
        """Two-phase handoff of ``req`` from ``source`` to ``target``.
        Returns True when the install landed and fleet bookkeeping was
        re-pointed (the request now decodes on ``target``); False on
        any abort — in which case the request is resumed on ``source``
        and nothing was lost."""
        rid = req.engine_rid
        if rid is None or req.ticket in self.pending:
            return False
        t0 = time.perf_counter()

        # Phase 1: freeze + snapshot. checkpoint_request pauses the
        # row, so the source engine stops emitting for this request
        # the moment the snapshot is cut.
        try:
            ckpt = source.engine.checkpoint_request(rid)
        except Exception as e:
            self._abort(req, source, rid, reason, "snapshot_abort", e)
            return False
        ckpt = ckpt.with_fence(epoch=self._publisher_epoch(),
                               version=source.weight_version,
                               deadline=req.deadline)

        # Fence: a weight publish between snapshot and install means
        # the checkpoint's KV was produced by weights the target no
        # longer runs. Never splice across versions — finish locally
        # instead. The check is against the TARGET's resident version
        # (re-read here, after the snapshot), not the publisher's roll
        # target: mid-roll, migrating an old-version decode onto a
        # not-yet-swapped peer is legal — it is exactly the eager-
        # publish relief path — and the publisher cannot swap the
        # target concurrently because swaps and migrations both run
        # under the fleet's pump.
        if target.weight_version != ckpt.weight_version:
            self._abort(req, source, rid, reason, "fence_abort", None)
            return False

        # Phase 2: install on target. At-least-once on the wire (the
        # RPC client retries under the SAME idempotency key), exactly-
        # once on the engine (the server's idempotency cache replays
        # the first outcome).
        self._seq += 1
        key = f"mig-{req.ticket}-s{self._seq}"
        try:
            if getattr(target.engine, "supports_idempotency", False):
                new_rid = target.engine.restore_checkpoint(
                    ckpt.to_wire(), idempotency_key=key)
            else:
                new_rid = target.engine.restore_request(ckpt)
        except Exception as e:
            self._abort(req, source, rid, reason, "install_abort", e)
            return False

        # Re-point fleet bookkeeping. tokens_survive: the emitted
        # count and first-token timestamp moved WITH the checkpoint —
        # a migration is progress relocation, not a retry.
        self.router.on_request_departure(req, tokens_survive=True)
        source.detach(rid)
        target.adopt(new_rid, req)
        self.pending[req.ticket] = PendingMigration(
            ticket=req.ticket, source=source, source_rid=rid,
            target=target, target_rid=new_rid, reason=reason,
            started_at=now)
        self._migration_ms.observe((time.perf_counter() - t0) * 1000.0)
        emit_event("migration_start", t=now, ticket=req.ticket,
                   reason=reason, source=source.replica_id,
                   target=target.replica_id)
        return True

    def _abort(self, req: FleetRequest, source: EngineReplica,
               rid: int, reason: str, outcome: str,
               err: Optional[Exception]) -> None:
        """Any failure before re-point: unfreeze the source copy and
        count the outcome. The request never left the source, so there
        is nothing to undo on the target — a half-installed restore
        there is an unreferenced engine request the server's release
        path (or its own completion) cleans up."""
        try:
            source.engine.resume_request(rid)
        except Exception:
            pass    # source died too — death triage owns the request now
        self._migrations_total.inc(reason=reason, outcome=outcome)
        emit_event("migration_abort", ticket=req.ticket, reason=reason,
                   outcome=outcome,
                   error=(type(err).__name__ if err else ""))

    def _publisher_epoch(self) -> int:
        return int(getattr(self.publisher, "epoch", 0) or 0)

    # -- evacuation (scale-down + eager-publish relief) ----------------------
    def evacuate(self, source: EngineReplica, *, reason: str,
                 now: float, limit: Optional[int] = None,
                 exclude=()) -> int:
        """Migrate as many of ``source``'s in-flight decodes as the
        fleet has room for. Returns the number moved; whatever could
        not be placed keeps decoding on the source (the caller's
        legacy drain path still applies to the remainder)."""
        moved = 0
        with source._lock:
            work = list(source.inflight.items())
        for rid, req in work:
            if limit is not None and moved >= limit:
                break
            if req.hold_slot or req.ticket in self.pending:
                continue    # held slots pin multi-turn state; skip
            target = self.scheduler.pick_target(
                source, tenant_id=req.tenant_id,
                require_version=source.weight_version,
                exclude=exclude)
            if target is None:
                continue
            if self.migrate(req, source, target, reason=reason, now=now):
                moved += 1
        return moved

    # -- pump (KV pressure + eager publish call sites) -----------------------
    def pump(self, now: float) -> int:
        """One coordinator tick, called from the fleet pump:

        1. Drain each local engine's pressure-migration offers (rows
           the KV ladder would otherwise truncate-finish at the
           preempt cap) and move them to a replica with headroom —
           or resume them in place when nowhere qualifies, in which
           case the next cap trip truncates exactly as before.
        2. When an eager publish has been blocked long enough to risk
           degrading, migrate decodes off the blocked replicas toward
           same-version peers so the roll can advance before its
           patience runs out."""
        moved = 0
        for rep in self.router.replicas:
            if rep.state == DEAD:
                continue
            take = getattr(rep.engine, "take_pressure_migrations", None)
            if take is None:
                continue
            for rid in take():
                req = rep.inflight.get(rid)
                if req is None or req.ticket in self.pending:
                    continue
                target = self.scheduler.pick_target(
                    rep, tenant_id=req.tenant_id,
                    require_version=rep.weight_version)
                if target is not None and self.migrate(
                        req, rep, target, reason="kv_pressure", now=now):
                    moved += 1
                else:
                    # No headroom anywhere: unfreeze; the engine's
                    # _migration_offered set guarantees the NEXT cap
                    # trip truncate-finishes instead of re-offering
                    # (no livelock).
                    try:
                        rep.engine.resume_request(rid)
                    except Exception:
                        pass
        moved += self._pump_eager_relief(now)
        return moved

    def _pump_eager_relief(self, now: float) -> int:
        """Eager-publish call site: the publisher names the replicas
        whose outstanding work is blocking the no-drain roll; move
        their longest-remaining decodes to peers still on the same
        version so the blocked replicas drain without degrading."""
        if self.publisher is None:
            return 0
        pending_fn = getattr(self.publisher, "eager_pending", None)
        if pending_fn is None:
            return 0
        blocked_ids = set(pending_fn())
        if not blocked_ids:
            return 0
        moved = 0
        blocked = [r for r in self.router.replicas
                   if r.replica_id in blocked_ids and r.state != DEAD]
        if len(blocked) < 2:
            return 0    # one blocker: nowhere same-version to put it —
                        # every idle peer already swapped to the new
                        # version, and a cross-version splice is banned
        # Consolidate: the blocker with the MOST remaining decode work
        # drains last no matter what, so it becomes the receiver; every
        # other blocker evacuates into it, swaps on the next pump, and
        # the roll stops burning patience. (Receiver-directed, so two
        # blocked peers can never ping-pong work between each other.)
        receiver = max(blocked, key=lambda r: r.outstanding_decode_tokens)
        others = [r for r in self.router.replicas if r is not receiver]
        for rep in blocked:
            if rep is receiver:
                continue
            moved += self.evacuate(
                rep, reason="eager_publish", now=now,
                exclude=[r for r in others if r is not rep])
        return moved

    # -- ack / rescue --------------------------------------------------------
    def note_progress(self, req: FleetRequest, now: float) -> None:
        """First post-migration token observed (fleet ``_ingest``):
        the target owns the decode for real now — release the frozen
        source copy and count the handoff completed."""
        pend = self.pending.get(req.ticket)
        if pend is None:
            return
        if req.replica_id != pend.target.replica_id \
                or req.engine_rid != pend.target_rid:
            return          # token from a life the ledger already left
        self._finish_pending(pend, now)

    def note_complete(self, req: FleetRequest, now: float) -> None:
        """Defensive ack on completion — a decode that finishes on the
        target in the same step it was installed may never pass
        through ``_ingest`` with its pending entry still open."""
        pend = self.pending.get(req.ticket)
        if pend is None:
            return
        self._finish_pending(pend, now)

    def _finish_pending(self, pend: PendingMigration, now: float) -> None:
        self.pending.pop(pend.ticket, None)
        if pend.source is not None and pend.source.state != DEAD:
            try:
                pend.source.engine.release_request(pend.source_rid)
            except Exception:
                pass    # best-effort: a dead/partitioned source leaks
                        # nothing the fleet owns — its janitor reclaims
        self._migrations_total.inc(reason=pend.reason,
                                   outcome="completed")
        emit_event("migration_ack", t=now, ticket=pend.ticket,
                   reason=pend.reason,
                   target=pend.target.replica_id)

    def rescue_request(self, req: FleetRequest, now: float) -> bool:
        """Result-lost triage hook: a pre-ack migration TARGET failed
        to hand over its result (partition mid-handoff). The frozen
        source copy is still intact — resume it and re-point the fleet
        there. True = rescued (token-exact continuation on the source);
        False = no pending entry or the source is gone too, and the
        caller falls back to retry-from-prompt triage."""
        pend = self.pending.get(req.ticket)
        if pend is None:
            return False
        del self.pending[req.ticket]
        pend.target.detach(pend.target_rid)
        src = pend.source
        if src is None or src.state == DEAD:
            return False
        try:
            src.engine.resume_request(pend.source_rid)
        except Exception:
            return False
        self.router.on_request_departure(req, tokens_survive=True)
        src.adopt(pend.source_rid, req)
        self._migrations_total.inc(reason=pend.reason,
                                   outcome="rescued")
        emit_event("migration_rescue", t=now, ticket=pend.ticket,
                   source=src.replica_id,
                   target=pend.target.replica_id)
        return True

    def on_replica_death(self, replica: EngineReplica,
                         now: float) -> List[FleetRequest]:
        """Death intersects the pending ledger two ways:

        * the TARGET died pre-ack — the frozen source copy is the
          request: detach it from the dying target (so the router's
          orphan triage doesn't double-requeue it), resume the source
          row, re-adopt there. Token-exact, zero lost work, outcome
          ``rescued``.
        * the SOURCE died pre-ack — the target's copy is the request;
          the ledger just forgets the source so the eventual ack skips
          the release.

        Returns the requests rescued back onto their sources."""
        rescued: List[FleetRequest] = []
        for ticket, pend in list(self.pending.items()):
            if pend.target is replica:
                req = replica.detach(pend.target_rid)
                del self.pending[ticket]
                src = pend.source
                if src is None or src.state == DEAD:
                    # both ends gone — re-adopt on the dying target so
                    # normal orphan triage (retry-from-prompt) finds
                    # it; nothing to rescue
                    if req is not None:
                        replica.adopt(pend.target_rid, req)
                    continue
                try:
                    src.engine.resume_request(pend.source_rid)
                except Exception:
                    if req is not None:
                        replica.adopt(pend.target_rid, req)
                    continue
                if req is not None:
                    self.router.on_request_departure(
                        req, tokens_survive=True)
                    src.adopt(pend.source_rid, req)
                    rescued.append(req)
                self._migrations_total.inc(reason=pend.reason,
                                           outcome="rescued")
                emit_event("migration_rescue", t=now, ticket=ticket,
                           source=src.replica_id,
                           target=replica.replica_id)
            elif pend.source is replica:
                pend.source = None      # ack will skip the release
        return rescued

    def has_pending_on(self, replica: EngineReplica) -> bool:
        """True while ``replica`` is either end of an un-acked handoff
        — autoscale must not retire a frozen source out from under the
        exactly-once guarantee."""
        return any(p.source is replica or p.target is replica
                   for p in self.pending.values())

    def stats(self) -> Dict[str, object]:
        return {"pending": len(self.pending),
                "handoffs": self._seq}
