"""Bounded admission control for the serving fleet.

The single-engine ``RolloutEngine.submit`` enqueues without judgment; a
fleet serving mixed traffic cannot — the ROADMAP's "heavy traffic from
millions of users" premise means the steady state is OVERLOAD, and the
only question is who waits, who runs, and who is told no. This module is
that decision, made explicit:

- two priority classes: ``INTERACTIVE`` (a human is watching — editor
  autocomplete, sidebar chat) and ``TRAIN_ROLLOUT`` (GRPO collection —
  throughput matters, latency doesn't), with interactive strictly first
  in dispatch order;
- per-class bounded queues — past the bound the request is shed with a
  typed :class:`Rejected` outcome, never silently dropped (the
  acceptance invariant: every submitted request completes or is
  explicitly rejected);
- per-class token-bucket rate limits (admission-time shed, so a
  misbehaving client can't starve the other class by queue pressure);
- per-request deadlines: a request whose deadline passes while QUEUED is
  shed at the next dispatch scan — deadlines bound queue wait, they do
  not kill in-flight decodes (a dispatched request's tokens are already
  paid for).

Everything takes an injectable monotonic ``now`` so the priority /
deadline tests run on a deterministic fake clock (seeded like
``resilience/chaos.py`` — no sleeps, no wall-clock flakes).
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

# Priority classes, in strict dispatch order (first = served first).
INTERACTIVE = "interactive"
TRAIN_ROLLOUT = "train_rollout"
PRIORITY_CLASSES: Tuple[str, ...] = (INTERACTIVE, TRAIN_ROLLOUT)

# Rejection reasons carried on the typed outcome.
REJECT_QUEUE_FULL = "queue_full"
REJECT_RATE_LIMITED = "rate_limited"
REJECT_DEADLINE = "deadline"
REJECT_REPLICA_FAILURE = "replica_failure"
REJECT_NO_REPLICAS = "no_replicas"
REJECT_KV_PRESSURE = "kv_pressure"
REJECT_TENANT_RATE = "tenant_rate_limited"


@dataclasses.dataclass(frozen=True)
class Rejected:
    """Typed load-shed outcome — the explicit "no" admission promises.

    ``reason`` is one of the REJECT_* constants; ``detail`` is a human
    string for logs; ``priority`` the class the request was submitted
    under."""

    ticket: int
    priority: str
    reason: str
    detail: str = ""


class RequestRejected(RuntimeError):
    """Raised when a result is demanded for a shed request.

    Carries the :class:`Rejected` outcome so callers that only speak the
    single-engine API (``result()`` returning tokens) still surface the
    shed as a typed error instead of an empty generation."""

    def __init__(self, rejected: Rejected):
        super().__init__(
            f"request {rejected.ticket} rejected: {rejected.reason}"
            + (f" ({rejected.detail})" if rejected.detail else ""))
        self.rejected = rejected


@dataclasses.dataclass
class FleetRequest:
    """One fleet submission, from admission through dispatch to outcome.

    ``deadline`` is ABSOLUTE (clock domain of the fleet's injected
    clock); ``not_before`` is the retry backoff floor the router sets
    after a replica death. Dispatch state (``replica_id``,
    ``engine_rid``, ``version_at_dispatch``) is rewritten on every
    (re)dispatch — a retried request must not carry its dead replica's
    weight version into the mixing assertion."""

    ticket: int
    prompt: List[int]
    max_new_tokens: int
    priority: str = TRAIN_ROLLOUT
    eos_id: Optional[int] = None
    prefix_tokens: Optional[List[int]] = None
    hold_slot: bool = False
    # Multi-tenant serving: the tenant this request decodes for. Drives
    # per-tenant admission fairness, router adapter affinity, and —
    # when the tenant has a published LoRA adapter — which adapter the
    # engine binds at submit. None = anonymous/base traffic.
    tenant_id: Optional[str] = None
    deadline: Optional[float] = None
    submitted_at: float = 0.0
    # -- dispatch state (owned by the fleet) --------------------------------
    attempts: int = 0
    not_before: float = 0.0
    # tokens decoded so far on the current replica — max_new_tokens
    # minus this is the request's REMAINING decode work, the unit the
    # router balances in (reset to 0 on re-dispatch after a death: the
    # partial tokens died with the replica).
    emitted: int = 0
    replica_id: Optional[str] = None
    engine_rid: Optional[int] = None
    version_at_dispatch: Optional[int] = None
    # Stamped by the replica UNDER ITS LOCK at the instant the request
    # is popped from ``inflight`` — the fleet must not re-read
    # ``replica.weight_version`` at completion time, because the
    # publisher may legally swap weights between the pop (zero
    # in-flight) and the fleet's bookkeeping.
    version_at_finish: Optional[int] = None
    first_token_at: Optional[float] = None
    dispatched_at: Optional[float] = None
    # -- timeline provenance (read by the fleet's TimelineRecorder) ----------
    # Stamped by AdmissionQueue.pop_ready at the instant the queue
    # hands the request over — the queue-ownership boundary, measured
    # where it happens rather than inferred at dispatch.
    queue_exit_at: Optional[float] = None
    # Router.pick's reason for its choice ("affinity" | "load").
    routed_by: Optional[str] = None
    # Wall time replica.submit spent inside engine.submit — for a
    # remote replica this is the RPC + remote prefill cost.
    submit_ms: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class ClassPolicy:
    """Admission knobs for one priority class.

    ``rate``/``burst`` parameterize a token bucket (None = unlimited);
    ``default_deadline_s`` applies when the caller passes no deadline
    (None = no deadline)."""

    max_queue: int = 256
    rate: Optional[float] = None          # requests/sec refill
    burst: Optional[float] = None         # bucket capacity (defaults rate)
    default_deadline_s: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class AdmissionConfig:
    interactive: ClassPolicy = ClassPolicy(max_queue=64)
    train_rollout: ClassPolicy = ClassPolicy(max_queue=512)
    # KV-pool pressure watermarks (rollout/kv_pressure.WatermarkGate):
    # the fleet feeds note_kv_pressure() each pump; at >= high the
    # queue gates — new offers shed with REJECT_KV_PRESSURE and
    # dispatch defers — until pressure drains to <= low. Backpressure
    # thus arrives BEFORE BlocksExhausted, and in-flight decodes (whose
    # blocks are already granted) always run to completion.
    kv_pressure_high: float = 0.92
    kv_pressure_low: float = 0.75
    # Per-tenant fairness: every distinct ``tenant_id`` gets its own
    # token bucket at these knobs (None = no per-tenant limiting), so
    # one hot tenant is shed at the door instead of starving the fleet.
    # Checked BEFORE the class bucket — a tenant-shed request must not
    # burn a class token other tenants could have used.
    tenant_rate: Optional[float] = None    # requests/sec per tenant
    tenant_burst: Optional[float] = None   # bucket size (defaults rate)

    def policy(self, priority: str) -> ClassPolicy:
        if priority == INTERACTIVE:
            return self.interactive
        if priority == TRAIN_ROLLOUT:
            return self.train_rollout
        raise ValueError(f"unknown priority class {priority!r} "
                         f"(want one of {PRIORITY_CLASSES})")


class TokenBucket:
    """Standard token bucket on an injectable clock. ``try_take`` is the
    only mutation; refill is computed lazily from elapsed time, so a
    fake clock that jumps forward refills exactly rate×dt."""

    def __init__(self, rate: float, burst: float, *, now: float = 0.0):
        self.rate = float(rate)
        self.burst = float(burst)
        self._tokens = float(burst)
        self._last = now

    def try_take(self, now: float) -> bool:
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.rate)
        self._last = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False


class AdmissionQueue:
    """Per-class bounded queues (EDF within a class) with rate limits
    and deadline shed.

    Not a thread in sight: the fleet serializes access under its own
    lock and supplies ``now`` — this object is pure policy, which is
    what makes the semantics testable on a fake clock."""

    def __init__(self, config: AdmissionConfig = AdmissionConfig(), *,
                 registry=None, now: float = 0.0):
        self.config = config
        self._queues: Dict[str, Deque[FleetRequest]] = {
            p: deque() for p in PRIORITY_CLASSES}
        self._buckets: Dict[str, Optional[TokenBucket]] = {}
        for p in PRIORITY_CLASSES:
            pol = config.policy(p)
            self._buckets[p] = (
                TokenBucket(pol.rate, pol.burst or pol.rate, now=now)
                if pol.rate is not None else None)
        # Per-tenant buckets, created lazily at first offer. Bounded in
        # practice by the tenant population; a bucket is just two
        # floats, so no eviction machinery.
        self._tenant_buckets: Dict[str, TokenBucket] = {}
        if registry is None:
            from ..obs import get_registry
            registry = get_registry()
        self._depth_gauge = registry.gauge(
            "senweaver_serve_queue_depth",
            "Requests admitted but not yet dispatched to a replica.",
            labelnames=("priority",))
        self._shed_total = registry.counter(
            "senweaver_serve_shed_total",
            "Requests shed by admission control (typed Rejected).",
            labelnames=("priority", "reason"))
        self._admitted_total = registry.counter(
            "senweaver_serve_admitted_total",
            "Requests admitted past the queue/rate gates.",
            labelnames=("priority",))
        from ..rollout.kv_pressure import WatermarkGate
        self._kv_gate = WatermarkGate(config.kv_pressure_high,
                                      config.kv_pressure_low)
        self._kv_pressure = 0.0
        self._kv_gated_gauge = registry.gauge(
            "senweaver_serve_kv_gated",
            "1 while admission is gated on KV-pool pressure "
            "(watermark hysteresis), else 0.")
        self._kv_gated_gauge.set(0)
        for p in PRIORITY_CLASSES:      # pre-touch so gauges render at 0
            self._depth_gauge.set(0, priority=p)

    # -- pressure ------------------------------------------------------------
    def note_kv_pressure(self, pressure: float) -> bool:
        """Feed the fleet's KV pool-pressure sample (0..1, worst
        placeable replica). Returns the resulting gate state: True =
        new offers shed and dispatch deferred until pressure drains
        below the low watermark."""
        self._kv_pressure = float(pressure)
        gated = self._kv_gate.update(self._kv_pressure)
        self._kv_gated_gauge.set(1 if gated else 0)
        return gated

    @property
    def kv_gated(self) -> bool:
        return self._kv_gate.gated

    # -- intake --------------------------------------------------------------
    def offer(self, req: FleetRequest, now: float) -> Optional[Rejected]:
        """Admit or shed ``req``. Returns the Rejected outcome on shed
        (queue full / rate limited), None on admission. Applies the
        class default deadline when the request carries none."""
        pol = self.config.policy(req.priority)
        if self._kv_gate.gated:
            # proactive backpressure: the pool is near exhaustion
            # fleet-wide — shed at the door (typed, before any blocks
            # are at stake) rather than let BlocksExhausted preempt
            # someone already decoding
            return self._shed(req, REJECT_KV_PRESSURE,
                              f"kv pool pressure "
                              f"{self._kv_pressure:.2f} >= "
                              f"{self.config.kv_pressure_high:g}")
        # Tenant fairness gate FIRST: a tenant over its budget must be
        # shed before the class bucket is touched, or one hot tenant's
        # rejections would still drain tokens from everyone else.
        if req.tenant_id is not None and self.config.tenant_rate is not None:
            tb = self._tenant_buckets.get(req.tenant_id)
            if tb is None:
                tb = TokenBucket(
                    self.config.tenant_rate,
                    self.config.tenant_burst or self.config.tenant_rate,
                    now=now)
                self._tenant_buckets[req.tenant_id] = tb
            if not tb.try_take(now):
                return self._shed(req, REJECT_TENANT_RATE,
                                  f"tenant {req.tenant_id} over "
                                  f"{self.config.tenant_rate:g} req/s")
        bucket = self._buckets[req.priority]
        if bucket is not None and not bucket.try_take(now):
            return self._shed(req, REJECT_RATE_LIMITED,
                              f"class {req.priority} over "
                              f"{pol.rate:g} req/s")
        q = self._queues[req.priority]
        if len(q) >= pol.max_queue:
            return self._shed(req, REJECT_QUEUE_FULL,
                              f"class {req.priority} queue at "
                              f"{pol.max_queue}")
        if req.deadline is None and pol.default_deadline_s is not None:
            req.deadline = now + pol.default_deadline_s
        q.append(req)
        self._admitted_total.inc(priority=req.priority)
        self._depth_gauge.set(len(q), priority=req.priority)
        return None

    def requeue(self, req: FleetRequest) -> None:
        """Put a retried request back at the FRONT of its class queue —
        it already waited once; backoff is enforced by ``not_before``,
        not by queue position."""
        q = self._queues[req.priority]
        q.appendleft(req)
        self._depth_gauge.set(len(q), priority=req.priority)

    # -- dispatch ------------------------------------------------------------
    def pop_ready(self, now: float) -> Tuple[Optional[FleetRequest],
                                             List[Rejected]]:
        """Next dispatchable request plus any shed because their
        deadline passed while queued.

        Order: strict priority class first; WITHIN a class, earliest
        deadline first (EDF — the queue-wait deadline is the SLO, so
        the request closest to blowing it runs next), deadline-less
        requests after all deadline-bearing ones in FIFO order.
        ``not_before`` backoff is honored: a request inside its retry
        floor is skipped without losing its queue position.

        While the KV-pressure gate is engaged, dispatch DEFERS: already
        -queued requests keep their positions (the deadline sweep still
        runs) and drain once in-flight completions release blocks and
        the gate opens at the low watermark."""
        if self._kv_gate.gated:
            return None, self.shed_expired(now)
        sheds: List[Rejected] = []
        picked: Optional[FleetRequest] = None
        for p in PRIORITY_CLASSES:
            q = self._queues[p]
            keep: List[FleetRequest] = []
            best_key = None
            best_i = -1
            for req in q:
                if req.deadline is not None and now >= req.deadline:
                    sheds.append(self._shed(
                        req, REJECT_DEADLINE,
                        f"queued past deadline "
                        f"(+{now - req.deadline:.3f}s)"))
                    continue
                keep.append(req)
                if req.not_before > now:
                    continue
                key = (req.deadline is None,
                       req.deadline if req.deadline is not None else 0.0,
                       len(keep) - 1)
                if best_key is None or key < best_key:
                    best_key = key
                    best_i = len(keep) - 1
            if best_i >= 0:
                picked = keep.pop(best_i)
                picked.queue_exit_at = now
            if len(keep) != len(q):
                q.clear()
                q.extend(keep)
            self._depth_gauge.set(len(q), priority=p)
            if picked is not None:
                break
        return picked, sheds

    def shed_expired(self, now: float) -> List[Rejected]:
        """Deadline sweep without dispatching (used between pumps while
        every replica is busy — expired requests must not wait for a
        free slot to learn they're dead)."""
        sheds: List[Rejected] = []
        for p in PRIORITY_CLASSES:
            q = self._queues[p]
            keep: List[FleetRequest] = []
            for req in q:
                if req.deadline is not None and now >= req.deadline:
                    sheds.append(self._shed(
                        req, REJECT_DEADLINE,
                        f"queued past deadline "
                        f"(+{now - req.deadline:.3f}s)"))
                else:
                    keep.append(req)
            if len(keep) != len(q):
                q.clear()
                q.extend(keep)
                self._depth_gauge.set(len(q), priority=p)
        return sheds

    def shed_all(self, reason: str, detail: str = "") -> List[Rejected]:
        """Drain every queue into Rejected outcomes (fleet shutdown or
        last-replica death — the none-lost invariant still holds)."""
        sheds: List[Rejected] = []
        for p in PRIORITY_CLASSES:
            q = self._queues[p]
            while q:
                sheds.append(self._shed(q.popleft(), reason, detail))
            self._depth_gauge.set(0, priority=p)
        return sheds

    # -- introspection -------------------------------------------------------
    def depth(self, priority: Optional[str] = None) -> int:
        if priority is not None:
            return len(self._queues[priority])
        return sum(len(q) for q in self._queues.values())

    def stats(self) -> Dict[str, Any]:
        out = {f"queue_depth_{p}": len(self._queues[p])
               for p in PRIORITY_CLASSES}
        out["kv_pressure"] = self._kv_pressure
        out["kv_gated"] = int(self._kv_gate.gated)
        return out

    # -- internals -----------------------------------------------------------
    def _shed(self, req: FleetRequest, reason: str,
              detail: str) -> Rejected:
        self._shed_total.inc(priority=req.priority, reason=reason)
        return Rejected(ticket=req.ticket, priority=req.priority,
                        reason=reason, detail=detail)
