"""SLO-aware request routing across the replica fleet.

Two signals, in order:

1. **Prefix affinity** — a request carrying ``prefix_tokens`` (the
   shared agent system prompt) prefers a replica that already holds that
   prefix's KV: installing it there is one HBM copy
   (``engine._install_prefix``) instead of a full prefill on a cold
   replica. Ties break by least outstanding work.
2. **Least outstanding work, in decode TOKENS** — otherwise the live
   replica with the fewest REMAINING decode tokens wins
   (Σ ``max_new_tokens − emitted`` over its in-flight requests, which
   replicas already track per request). In-flight count treats a
   replica two steps from draining the same as one holding fresh
   512-token generations; remaining tokens is the actual queue-time
   signal. Count is kept as the tiebreaker.

Replica death is the router's second job: orphaned in-flight requests
come back through :meth:`on_replica_death`, which either schedules a
retry on the surviving fleet — backoff via the SAME exponential shape
the episode fault boundary uses (``resilience.episode_retry_delay_s``)
— or sheds the request with a typed ``Rejected`` once its retry budget
is spent. A retried request restarts from its prompt: partial tokens
from the dead replica are discarded (they may belong to a different
weight version than the surviving replicas serve).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..resilience.retry import RetryPolicy
from .admission import (REJECT_NO_REPLICAS, REJECT_REPLICA_FAILURE,
                        FleetRequest, Rejected)
from .replica import EngineReplica


class Router:
    def __init__(self, replicas: Sequence[EngineReplica], *,
                 max_retries: int = 2,
                 retry_base_delay_s: float = 0.05,
                 retry_max_delay_s: float = 2.0,
                 registry=None):
        self.replicas = list(replicas)
        # The shared resilience retry shape; UNJITTERED — requeue
        # backoff is enforced by fake-clock-friendly `not_before`
        # timestamps, and deterministic delays keep the SLO tests exact.
        self.retry = RetryPolicy(max_retries=int(max_retries),
                                 base_delay_s=retry_base_delay_s,
                                 max_delay_s=retry_max_delay_s,
                                 jitter=False)
        if registry is None:
            from ..obs import get_registry
            registry = get_registry()
        self._affinity_hits = registry.counter(
            "senweaver_serve_prefix_affinity_hits_total",
            "Requests routed to a replica already holding their prefix.")
        self._adapter_affinity_hits = registry.counter(
            "senweaver_serve_adapter_affinity_hits_total",
            "Tenant requests routed to a replica whose pool already "
            "holds their current adapter version (no upload at submit).")
        self._retries_total = registry.counter(
            "senweaver_serve_retries_total",
            "Requests resubmitted after a replica death/fault.")
        self._deaths_total = registry.counter(
            "senweaver_serve_replica_deaths_total",
            "Replicas declared dead.")

    # -- selection -----------------------------------------------------------
    def live_replicas(self) -> List[EngineReplica]:
        from .replica import DEAD
        return [r for r in self.replicas if r.state != DEAD]

    def pick(self, req: FleetRequest) -> Optional[EngineReplica]:
        """Choose a replica for ``req`` (None = nothing accepting; the
        request stays queued)."""
        accepting = [r for r in self.replicas if r.accepting]
        if not accepting:
            return None
        def load(r: EngineReplica):
            return (r.outstanding_decode_tokens, r.outstanding)

        if req.prefix_tokens:
            key = tuple(req.prefix_tokens)
            warm = [r for r in accepting if r.holds_prefix(key)]
            if warm:
                self._affinity_hits.inc()
                req.routed_by = "affinity"
                return min(warm, key=load)
        if req.tenant_id is not None:
            # Tenant→adapter-slot affinity, below prefix affinity
            # (prefix KV is the bigger transfer) but above raw load: a
            # replica whose pool already holds the tenant's CURRENT
            # adapter version skips the submit-time upload.
            resident = [
                r for r in accepting
                if getattr(r, "has_adapter_resident", None) is not None
                and r.has_adapter_resident(req.tenant_id)]
            if resident:
                self._adapter_affinity_hits.inc()
                req.routed_by = "adapter_affinity"
                return min(resident, key=load)
        req.routed_by = "load"
        return min(accepting, key=load)

    def load_snapshot(self) -> dict:
        """Per-replica remaining-decode-token snapshot — the signal
        :meth:`pick` balances on, exposed for the speculation depth
        controller's report surface (scripts/spec_report.py) and the
        dashboard. Keys are replica ids; DEAD replicas are omitted."""
        return {r.replica_id: r.outstanding_decode_tokens
                for r in self.live_replicas()}

    # -- failure handling ----------------------------------------------------
    def on_request_departure(self, req: FleetRequest, *,
                             tokens_survive: bool = False) -> None:
        """THE hook for a request leaving a replica without completing
        — replica death, result-lost triage, or migration-out. Clears
        the dispatch state in one place so the load accounting
        (remaining decode tokens = ``max_new_tokens − emitted``) can
        never go stale-high on a replica the request no longer
        occupies.

        ``tokens_survive=False`` (death / lost result): the partial
        tokens died with the replica — the retry restarts from the
        prompt, so ``emitted`` resets and the attempt is spent.
        ``tokens_survive=True`` (live migration): the tokens moved
        WITH the request — ``emitted`` and ``first_token_at`` are
        real progress and a migration is not a retry."""
        req.replica_id = None
        req.engine_rid = None
        req.version_at_dispatch = None
        req.version_at_finish = None
        if not tokens_survive:
            req.attempts += 1
            req.first_token_at = None
            req.emitted = 0     # partial tokens died with the replica

    def on_replica_death(self, replica: EngineReplica, now: float
                         ) -> Tuple[List[FleetRequest], List[Rejected]]:
        """Kill ``replica`` and triage its orphans: (requeue, shed).

        Requeued requests carry a ``not_before`` backoff floor — the
        dispatcher won't touch them until it passes — and cleared
        dispatch state (their partial tokens died with the replica)."""
        orphans = replica.kill()
        self._deaths_total.inc()
        requeue: List[FleetRequest] = []
        shed: List[Rejected] = []
        have_survivors = bool(self.live_replicas())
        for req in orphans:
            self.on_request_departure(req, tokens_survive=False)
            if not have_survivors:
                shed.append(Rejected(
                    ticket=req.ticket, priority=req.priority,
                    reason=REJECT_NO_REPLICAS,
                    detail="last replica died"))
            elif req.attempts > self.max_retries:
                shed.append(Rejected(
                    ticket=req.ticket, priority=req.priority,
                    reason=REJECT_REPLICA_FAILURE,
                    detail=f"retry budget spent "
                           f"({req.attempts - 1} retries)"))
            else:
                req.not_before = now + self.retry.backoff_s(req.attempts)
                self._retries_total.inc()
                requeue.append(req)
        return requeue, shed

    # -- policy accessors (fleet + legacy callers) ---------------------------
    @property
    def max_retries(self) -> int:
        return self.retry.max_retries

    @property
    def retry_base_delay_s(self) -> float:
        return self.retry.base_delay_s

    @property
    def retry_max_delay_s(self) -> float:
        return self.retry.max_delay_s
