"""Admission-driven autoscaling with hysteresis.

``fleet.add_replica`` has been the spawn half of autoscaling since the
fleet landed; this module is the long-promised trigger. The signals are
the admission queue's own: **queue depth** (work waiting that no replica
accepts) and **shed rate** (the ``senweaver_serve_shed_total`` counter's
derivative — admitted demand the fleet is actively refusing). Overload
that only sheds is a policy failure when capacity is one
``add_replica`` away.

Hysteresis is the whole design: naive threshold controllers flap — one
burst adds a replica, the queue drains, the controller immediately
drains the replica, the next burst sheds again. Three guards prevent
that:

- **sustain**: a signal must hold continuously for ``sustain_s``
  (overload) / ``idle_sustain_s`` (idle) before any action;
- **cooldown**: after ANY action, no further action for ``cooldown_s``;
- **bounds**: never below ``min_replicas`` or above ``max_replicas``,
  and never a drain while a weight publish is rolling (a retiring
  replica mid-roll would re-resume under the publisher).

Scale-down is two-phase: pick the least-loaded live replica, ``drain()``
it (stops accepting, keeps decoding its in-flight work), and only when
its outstanding count hits zero retire it through the fleet's normal
death path — zero orphans, zero sheds, by construction.

The controller is evaluated inside the fleet's pump (under the fleet
lock, manual ``step()`` and the dispatcher thread both), so it needs no
thread of its own and every test runs it on a fake clock.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from ..obs.incidents import emit_event
from .replica import DEAD, LIVE

ACTION_ADD = "add"
ACTION_DRAIN = "drain"


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """Hysteresis knobs. Defaults are conservative for real clocks;
    tests tighten them against a fake clock."""

    min_replicas: int = 1
    max_replicas: int = 8
    # Overload when queue depth >= this OR shed rate (sheds/sec over the
    # evaluation window) >= shed_rate_high OR fleet KV pool pressure
    # (senweaver_kv_pressure, published by the fleet pump) >=
    # kv_pressure_high — the memory-pressure ladder's "scale" rung,
    # fired by the same gauge admission gates on, so capacity arrives
    # BEFORE BlocksExhausted starts preempting.
    queue_depth_high: int = 8
    shed_rate_high: float = 1.0
    kv_pressure_high: float = 0.9
    sustain_s: float = 2.0          # overload must hold this long
    idle_sustain_s: float = 10.0    # idleness must hold this long
    cooldown_s: float = 5.0         # min gap between ANY two actions
    evaluate_interval_s: float = 0.25


class AutoscaleController:
    """Queue-depth / shed-rate hysteresis driving add_replica + drain."""

    def __init__(self, fleet, spawn_engine, *,
                 config: AutoscaleConfig = AutoscaleConfig(),
                 registry=None, fleet_store=None):
        self.fleet = fleet
        self.spawn_engine = spawn_engine
        self.config = config
        # Optional FleetMetricsStore: when the fleet is federated the
        # controller reads the FLEET-WIDE rollups (sum of sheds, max KV
        # pressure across peers) instead of this process's local view —
        # capacity decisions see remote replicas' pressure too.
        self.fleet_store = fleet_store
        # Optional MigrationCoordinator (serve/scheduler.py), wired by
        # fleet.attach_migration(): when present, scale-down EVACUATES
        # the retiring replica's in-flight decodes to live peers
        # instead of waiting out a drain — retirement completes in one
        # pump tick and no request ever runs on borrowed time.
        self.migrator = None
        # All mutable state below is guarded-by: fleet._lock — evaluate()
        # only ever runs inside the fleet's pump, which holds it.
        self._last_eval_at: Optional[float] = None   # guarded-by: fleet._lock
        self._overload_since: Optional[float] = None  # guarded-by: fleet._lock
        self._idle_since: Optional[float] = None      # guarded-by: fleet._lock
        self._last_action_at: Optional[float] = None  # guarded-by: fleet._lock
        self._last_shed_total = 0.0                   # guarded-by: fleet._lock
        self._last_shed_at: Optional[float] = None    # guarded-by: fleet._lock
        self._retiring: Optional[str] = None          # guarded-by: fleet._lock
        self._spawned = 0                             # guarded-by: fleet._lock
        # (now, action) audit trail — what the flapping tests assert on.
        self.actions: List[Tuple[float, str]] = []    # guarded-by: fleet._lock
        if registry is None:
            from ..obs import get_registry
            registry = get_registry()
        self._registry = registry
        self._actions_total = registry.counter(
            "senweaver_serve_autoscale_actions_total",
            "Autoscaler actions taken (add = replica spawned, drain = "
            "replica retired).", labelnames=("action",))
        self._shed_rate_gauge = registry.gauge(
            "senweaver_serve_autoscale_shed_rate",
            "Shed rate (sheds/sec) the autoscaler last observed.")
        self._shed_rate_gauge.set(0.0)

    # -- signal plumbing -----------------------------------------------------
    def _shed_total(self) -> float:
        if self.fleet_store is not None:
            v = self.fleet_store.rollup_value(
                "senweaver_serve_shed_total", "sum")
            if v is not None:
                return float(v)
        m = self._registry.get("senweaver_serve_shed_total")
        if m is None:
            return 0.0
        return sum(float(v) for v in m.samples().values())

    def _live(self):
        return [r for r in self.fleet.replicas if r.state != DEAD]

    def _kv_pressure(self) -> float:
        if self.fleet_store is not None:
            v = self.fleet_store.rollup_value(
                "senweaver_kv_pressure", "max")
            if v is not None:
                return float(v)
        m = self._registry.get("senweaver_kv_pressure")
        if m is None:
            return 0.0
        vals = m.samples().values()
        return max((float(v) for v in vals), default=0.0)

    # -- the controller ------------------------------------------------------
    def evaluate(self, now: float) -> Optional[str]:
        """One hysteresis tick; returns the action taken (if any).
        Called from the fleet pump — the caller holds the lock
        (``fleet._lock``)."""
        cfg = self.config
        if (self._last_eval_at is not None
                and now - self._last_eval_at < cfg.evaluate_interval_s):
            return None
        # Shed rate over the window since the previous evaluation.
        shed_total = self._shed_total()
        if self._last_shed_at is None or now <= self._last_shed_at:
            shed_rate = 0.0
        else:
            shed_rate = ((shed_total - self._last_shed_total)
                         / (now - self._last_shed_at))
        self._last_shed_total = shed_total
        self._last_shed_at = now
        self._last_eval_at = now
        self._shed_rate_gauge.set(shed_rate)

        # Finish an in-progress retirement before considering anything
        # else: a drained replica at zero outstanding retires cleanly.
        action = self._pump_retirement(now)
        if action is not None:
            return action

        depth = self.fleet.admission.depth()
        live = self._live()
        kv_pressure = self._kv_pressure()
        overloaded = (depth >= cfg.queue_depth_high
                      or shed_rate >= cfg.shed_rate_high
                      or kv_pressure >= cfg.kv_pressure_high)
        idle = (depth == 0 and shed_rate == 0.0
                and kv_pressure < cfg.kv_pressure_high
                and all(r.outstanding == 0 for r in live))

        self._overload_since = (
            (self._overload_since if self._overload_since is not None
             else now) if overloaded else None)
        self._idle_since = (
            (self._idle_since if self._idle_since is not None else now)
            if idle else None)

        if (self._last_action_at is not None
                and now - self._last_action_at < cfg.cooldown_s):
            return None
        if (self._overload_since is not None
                and now - self._overload_since >= cfg.sustain_s
                and len(live) < cfg.max_replicas):
            return self._scale_up(now)
        if (self._idle_since is not None
                and now - self._idle_since >= cfg.idle_sustain_s
                and len(live) > cfg.min_replicas
                and self._retiring is None
                and not self.fleet.publisher.in_progress):
            return self._begin_retirement(now)
        return None

    def _scale_up(self, now: float) -> str:
        # guarded-by: caller
        self._spawned += 1
        replica_id = f"replica-as{self._spawned}"
        self.fleet.add_replica(self.spawn_engine(),
                               replica_id=replica_id)
        self._record(now, ACTION_ADD)
        return ACTION_ADD

    def _begin_retirement(self, now: float) -> Optional[str]:
        # guarded-by: caller
        live = [r for r in self._live() if r.state != DEAD]
        if len(live) <= self.config.min_replicas:
            return None
        victim = min(live, key=lambda r: r.outstanding)
        victim.drain()
        self._retiring = victim.replica_id
        self._record(now, ACTION_DRAIN)
        return ACTION_DRAIN

    def _pump_retirement(self, now: float) -> Optional[str]:
        # guarded-by: caller
        if self._retiring is None:
            return None
        rep = next((r for r in self.fleet.replicas
                    if r.replica_id == self._retiring), None)
        if rep is None or rep.state == DEAD:
            self._retiring = None
            return None
        if rep.outstanding > 0 and self.migrator is not None:
            # Live-migrate the stragglers off instead of draining them
            # out: whatever the fleet can place moves now; any
            # remainder keeps decoding here and the next tick retries.
            self.migrator.evacuate(rep, reason="scale_down", now=now)
        if (self.migrator is not None
                and self.migrator.has_pending_on(rep)):
            # Still the frozen SOURCE of an un-acked handoff: killing
            # it now would strand the fallback copy the exactly-once
            # guarantee depends on. Wait for the ack.
            return None
        if rep.state != DEAD and rep.outstanding == 0:
            # Drained dry — retire through the fleet's death path (no
            # orphans by construction). A publish roll may have resumed
            # it meanwhile; re-drain and wait in that case.
            if rep.state == LIVE:
                rep.drain()
                return None
            self.fleet.kill_replica(rep.replica_id)
            self._retiring = None
        return None

    def _record(self, now: float, action: str) -> None:
        # guarded-by: caller
        self._last_action_at = now
        self._overload_since = None
        self._idle_since = None
        self.actions.append((now, action))
        self._actions_total.inc(action=action)
        emit_event("autoscale_action", t=now, action=action)
