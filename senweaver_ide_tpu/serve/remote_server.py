"""Server shim: one local RolloutEngine behind the rpc protocol.

:class:`EngineRpcHandler` is the whole remote side of the cross-host
fleet — a method-dispatch table over one engine plus the one piece of
state that makes retries SAFE: a bounded **idempotency cache** keyed by
the client's ``request_id``. A retried mutating call (the client saw a
timeout; the server may or may not have executed) replays the cached
outcome instead of executing twice — that is the exactly-once half of
the fleet's no-loss/no-double-execution guarantee (the retry policy is
the no-loss half). Cached outcomes include application ERRORS: a submit
that raised ValueError raises the same ValueError on replay rather than
accidentally succeeding the second time.

:func:`serve_engine_http` wraps the handler in a stdlib
``ThreadingHTTPServer`` speaking the :data:`~.rpc.RPC_PATH` JSON frame —
the real-socket deployment path. Tests mostly skip it and hand the
handler to a ``LoopbackTransport``; one end-to-end test drives the HTTP
stack on 127.0.0.1 to keep the wire honest.
"""

from __future__ import annotations

import collections
import json
import threading
from typing import Any, Dict, Optional, Tuple

from ..obs.federation import MetricsScrapeMixin
from .rpc import RPC_PATH, RpcApplicationError, RpcProtocolError, decode, \
    encode

# Methods that change engine state; only these consult/populate the
# idempotency cache (reads are naturally idempotent and must see fresh
# state — a cached ``step`` replay is correct, a cached ``health`` lie).
# ``scrape`` is mutating on purpose: delta shipping advances a
# per-scraper cursor, so a retried scrape must REPLAY the cached delta
# (exactly-once) rather than compute a second one and skip a window.
MUTATING_METHODS = frozenset({
    "submit", "step", "release_slot", "register_prefix", "import_prefix",
    "release_prefix", "update_params", "scrape",
    # Live migration (serve/scheduler.py). checkpoint_request mutates
    # (it freezes the row) and a lost-response retry must replay the
    # SAME snapshot; restore_checkpoint is the at-least-once install
    # whose cache hit makes it exactly-once on the engine.
    "checkpoint_request", "restore_checkpoint", "resume_request",
    "release_request"})

# Reads are never cached and must see fresh state — a cached ``step``
# replay is correct, a cached ``health`` a lie. Every dispatchable
# method must appear in exactly one classification set (rpc_lint
# RPC101 enforces it).
READONLY_METHODS = frozenset({
    "health", "meta", "is_done", "result", "result_logps",
    "export_prefix", "stats"})


class RpcHandlerBase:
    """Dispatch table + idempotency cache; subclasses provide ``_m_*``
    methods and classify each one into exactly one of three sets:

    ``mutating_methods``        consult/populate the idempotency cache —
                                a retried call REPLAYS its first outcome
    ``readonly_methods``        never cached; must see fresh state
    ``reexecute_safe_methods``  mutating but deliberately UNCACHED —
                                re-execution on a retry is safe, replay
                                is dangerous (the lease family: a cached
                                grant replayed by a restarted client
                                would resurrect a zombie epoch)

    The cache is the exactly-once half of the fleet's retry contract: a
    retried mutating call (the client saw a timeout; the server may or
    may not have executed) replays the cached outcome — including cached
    application ERRORS — instead of executing twice."""

    mutating_methods: frozenset = frozenset()
    readonly_methods: frozenset = frozenset()
    reexecute_safe_methods: frozenset = frozenset()
    # Span attribute naming the process role in a stitched trace
    # ("engine" host, "fleet" learner gateway, ...).
    span_service: str = "rpc"

    def __init__(self, *, idempotency_cache_size: int = 4096):
        self._cache_size = max(1, int(idempotency_cache_size))
        # request_id -> ("ok" | "err", payload) — replayed on duplicates
        self._cache: "collections.OrderedDict[str, Tuple[str, Any]]" = \
            collections.OrderedDict()       # guarded-by: _lock
        self.executed: Dict[str, int] = {}  # method -> count, guarded-by: _lock
        self.replays = 0                    # guarded-by: _lock
        self._lock = threading.Lock()

    # -- dispatch ------------------------------------------------------------
    def handle(self, method: str, params: Dict[str, Any], *,
               request_id: Optional[str] = None,
               trace: Optional[Dict[str, Any]] = None) -> Any:
        """Dispatch one rpc. ``trace`` is the frame's propagated span
        context (see ``obs/propagation.py``): when tracing is enabled
        the call runs under a ``rpc.server.<method>`` span stitched
        into the caller's trace. An idempotency-cache hit ANNOTATES
        that span (``replay=True``) — the replayed work itself recorded
        its span on first execution, so retried RPCs never duplicate
        spans, they just show up as annotated replays."""
        fn = getattr(self, f"_m_{method}", None)
        if fn is None:
            raise RpcProtocolError(f"unknown rpc method {method!r}")
        tracer = _maybe_tracer()
        if tracer is None or not tracer.enabled:
            outcome, _ = self._dispatch(fn, method, params, request_id)
            return self._replay(outcome)
        from ..obs.propagation import server_span
        with server_span(tracer, trace, f"rpc.server.{method}",
                         service=self.span_service,
                         method=method) as span:
            outcome, replayed = self._dispatch(fn, method, params,
                                               request_id)
            if span is not None:
                if request_id is not None:
                    span.set_attr("request_id", request_id)
                if replayed:
                    span.set_attr("replay", True)
                if outcome[0] == "err":
                    span.set_attr("app_error", outcome[1][0])
            return self._replay(outcome)

    def _dispatch(self, fn, method: str, params: Dict[str, Any],
                  request_id: Optional[str]
                  ) -> Tuple[Tuple[str, Any], bool]:
        """(outcome, replayed): the cache-or-execute core of handle."""
        cacheable = (request_id is not None
                     and method in self.mutating_methods)
        if cacheable:
            with self._lock:
                hit = self._cache.get(request_id)
                if hit is not None:
                    self._cache.move_to_end(request_id)
                    self.replays += 1
                    return hit, True
        try:
            result = fn(**params)
            outcome = ("ok", result)
        except RpcProtocolError:
            raise
        except Exception as e:
            outcome = ("err", (type(e).__name__, str(e)))
        if cacheable:
            with self._lock:
                self._cache[request_id] = outcome
                while len(self._cache) > self._cache_size:
                    self._cache.popitem(last=False)
        with self._lock:
            self.executed[method] = self.executed.get(method, 0) + 1
        return outcome, False

    @staticmethod
    def _replay(outcome: Tuple[str, Any]) -> Any:
        status, payload = outcome
        if status == "ok":
            return payload
        raise RpcApplicationError(payload[0], payload[1])


def _maybe_tracer():
    """The global tracer, or None if obs is unimportable — the server
    must handle rpcs even when observability is broken."""
    try:
        from ..obs import get_tracer
        return get_tracer()
    except Exception:
        return None


class EngineRpcHandler(MetricsScrapeMixin, RpcHandlerBase):
    """The whole remote side of the cross-host fleet: a dispatch table
    over one local engine (plus the idempotency cache from the base,
    plus the federation ``scrape`` endpoint from the mixin)."""

    mutating_methods = MUTATING_METHODS
    readonly_methods = READONLY_METHODS
    span_service = "engine"

    def __init__(self, engine, *, idempotency_cache_size: int = 4096,
                 registry=None):
        super().__init__(idempotency_cache_size=idempotency_cache_size)
        self.engine = engine
        # Host-side fencing high-water mark for versioned publishes —
        # the last line of defense against a stale writer reaching this
        # replica directly (same rule as WeightPublisher.begin, except
        # an EQUAL version at a >= epoch is an idempotent reinstall).
        self._hw_epoch = 0                  # guarded-by: _lock
        self._hw_version = 0                # guarded-by: _lock
        if registry is None:
            from ..obs import get_registry
            registry = get_registry()
        self._stale_total = registry.counter(
            "senweaver_serve_stale_publish_total",
            "Publishes rejected by (epoch, version) fencing — a stale "
            "or duplicate writer was denied.")

    # -- methods -------------------------------------------------------------
    def _m_health(self) -> Dict[str, Any]:
        return {"state": "ok",
                "has_work": bool(getattr(self.engine, "has_work", False)),
                "queue_depth": int(
                    self.engine.stats().get("queue_depth", 0))}

    def _m_meta(self) -> Dict[str, Any]:
        return {"num_slots": int(getattr(self.engine, "num_slots", 8)),
                "context_bound": int(
                    getattr(self.engine, "context_bound", 1 << 30))}

    def _m_submit(self, prompt, max_new_tokens=128, prefix_id=None,
                  eos_id=None, hold_slot=False, continue_from=None) -> int:
        """Cached-mutating: a retried submit must replay the SAME
        request id — re-executing would enqueue the prompt twice."""
        return self.engine.submit(
            list(prompt), max_new_tokens=max_new_tokens,
            prefix_id=prefix_id, eos_id=eos_id, hold_slot=hold_slot,
            continue_from=continue_from)

    def _m_step(self) -> Dict[str, Any]:
        """Cached-mutating: each step advances decode state, so a
        lost-response retry must replay that step's tokens — executing
        a second step would silently drop a token window."""
        # JSON object keys are strings; the client int()s them back.
        return {str(rid): toks
                for rid, toks in self.engine.step().items()}

    def _m_is_done(self, rid) -> bool:
        return bool(self.engine.is_done(int(rid)))

    def _m_result(self, rid):
        return list(self.engine.result(int(rid)))

    def _m_result_logps(self, rid):
        return [float(x) for x in self.engine.result_logps(int(rid))]

    def _m_release_slot(self, rid) -> None:
        """Cached-mutating: replay keeps a retried release from
        freeing a slot that was already reassigned to a new request."""
        self.engine.release_slot(int(rid))

    def _m_register_prefix(self, tokens) -> int:
        """Cached-mutating: replay returns the SAME prefix id — a
        second registration would pin a duplicate KV prefix."""
        return int(self.engine.register_prefix(list(tokens)))

    def _m_export_prefix(self, prefix_id):
        return self.engine.export_prefix(int(prefix_id))

    def _m_import_prefix(self, tokens, kv, last_logits=None) -> int:
        """Cached-mutating: replay returns the first install's prefix
        id instead of allocating the KV blocks a second time."""
        return int(self.engine.import_prefix(list(tokens), kv,
                                             last_logits))

    def _m_release_prefix(self, prefix_id) -> None:
        """Cached-mutating: replay keeps a retried release from
        double-decrementing the prefix refcount."""
        self.engine.release_prefix(int(prefix_id))

    def _m_update_params(self, params, version=None, epoch=None) -> None:
        """Cached-mutating: a retried install replays the first
        outcome; fresh re-execution would trip the (epoch, version)
        fence below and misreport a stale publish."""
        if version is not None:
            from .weights import StalePublishError
            v, e = int(version), int(epoch or 0)
            with self._lock:
                if e < self._hw_epoch or (e == self._hw_epoch
                                          and v < self._hw_version):
                    self._stale_total.inc()
                    raise StalePublishError(
                        f"update_params (epoch={e}, version={v}) behind "
                        f"this host's high-water mark (epoch="
                        f"{self._hw_epoch}, version={self._hw_version})")
                self._hw_epoch, self._hw_version = e, v
        self.engine.update_params(params)

    # -- live migration (serve/scheduler.py) ---------------------------------
    def _m_checkpoint_request(self, rid, pause=True) -> Dict[str, Any]:
        """Cached-mutating: freezes the row, so a lost-response retry
        must replay the SAME snapshot, not cut a second one."""
        ckpt = self.engine.checkpoint_request(int(rid),
                                              pause=bool(pause))
        return ckpt.to_wire()

    def _m_restore_checkpoint(self, ckpt) -> int:
        """Cached-mutating: the at-least-once install whose cache hit
        makes it exactly-once — replay returns the first restore's rid
        instead of materializing the decode twice."""
        from ..rollout.migration import DecodeCheckpoint
        return int(self.engine.restore_request(
            DecodeCheckpoint.from_wire(ckpt)))

    def _m_resume_request(self, rid) -> None:
        """Cached-mutating: replay keeps a retried resume from
        double-unpausing a row the scheduler re-froze since."""
        self.engine.resume_request(int(rid))

    def _m_release_request(self, rid) -> bool:
        """Cached-mutating: replay preserves the first release's
        verdict — re-executing would report False for a row that THIS
        call already released."""
        return bool(self.engine.release_request(int(rid)))

    def _m_stats(self) -> Dict[str, Any]:
        return dict(self.engine.stats())


def serve_engine_http(engine_or_handler, *, host: str = "127.0.0.1",
                      port: int = 0):
    """Serve one engine over real HTTP; returns ``(server, port)``.

    ``server`` is a started ``ThreadingHTTPServer`` (daemon thread);
    call ``server.shutdown()`` when done. Port 0 picks a free port —
    the test-friendly default.
    """
    handler = (engine_or_handler
               if isinstance(engine_or_handler, RpcHandlerBase)
               else EngineRpcHandler(engine_or_handler))
    return serve_rpc_http(handler, host=host, port=port)


def serve_rpc_http(handler: RpcHandlerBase, *, host: str = "127.0.0.1",
                   port: int = 0, thread_name: str = "serve-rpc-http"):
    """Serve any :class:`RpcHandlerBase` behind the :data:`~.rpc.RPC_PATH`
    JSON frame over a stdlib ``ThreadingHTTPServer``; returns
    ``(server, port)``. Shared by the engine shim above and the
    learner gateway (``learner_server.serve_fleet_http``)."""
    import http.server

    class _Rpc(http.server.BaseHTTPRequestHandler):
        def do_POST(self):     # noqa: N802 (stdlib naming)
            if self.path != RPC_PATH:
                self.send_error(404)
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                frame = json.loads(self.rfile.read(length))
                method = frame["method"]
                params = decode(frame.get("params") or {})
                request_id = frame.get("request_id")
                trace = frame.get("trace")
            except (ValueError, KeyError, TypeError):
                self.send_error(400, "malformed rpc frame")
                return
            try:
                result = handler.handle(method, params,
                                        request_id=request_id,
                                        trace=trace)
                body = {"ok": True, "result": encode(result)}
            except RpcApplicationError as e:
                body = {"ok": False, "error_type": e.error_type,
                        "message": e.message}
            except RpcProtocolError as e:
                self.send_error(400, str(e))
                return
            except Exception as e:      # crash mid-call → 5xx
                self.send_error(500, str(e))
                return
            payload = json.dumps(body).encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *args):   # keep test output quiet
            pass

    server = http.server.ThreadingHTTPServer((host, port), _Rpc)
    server.daemon_threads = True
    thread = threading.Thread(target=server.serve_forever,
                              name=thread_name, daemon=True)
    thread.start()
    return server, server.server_address[1]
