"""serve/ — multi-replica rollout serving fleet.

Composes N :class:`~senweaver_ide_tpu.rollout.engine.RolloutEngine`
instances behind one facade with admission control (priority classes,
rate limits, deadlines, typed :class:`Rejected` sheds), SLO-aware
routing (prefix affinity + least outstanding work + retry-on-death), and
versioned rolling weight publication. See ``docs/serving.md``.
"""

from .admission import (AdmissionConfig, AdmissionQueue, ClassPolicy,
                        FleetRequest, INTERACTIVE, PRIORITY_CLASSES,
                        REJECT_DEADLINE, REJECT_NO_REPLICAS,
                        REJECT_QUEUE_FULL, REJECT_RATE_LIMITED,
                        REJECT_REPLICA_FAILURE, Rejected,
                        RequestRejected, TRAIN_ROLLOUT, TokenBucket)
from .autoscale import (ACTION_ADD, ACTION_DRAIN, AutoscaleConfig,
                        AutoscaleController)
from .frontend import Completed, ServingFleet
from .learner import (EpisodeStreamer, ExperienceClient,
                      FleetPublishClient, LearnerConfig,
                      LearnerPublishError, LearnerService,
                      StreamingLearnerConfig, StreamingLearnerService)
from .learner_server import (ExperienceRpcHandler, FleetRpcHandler,
                             LeaseRpcHandler, RemoteLeaseStore,
                             serve_experience_http, serve_fleet_http,
                             serve_lease_http)
from .prefix_store import SharedPrefixStore
from .remote import (PROBE_DEAD, PROBE_OK, PROBE_SLOW,
                     RemoteEngineClient, RemoteReplica)
from .remote_server import (EngineRpcHandler, RpcHandlerBase,
                            serve_engine_http, serve_rpc_http)
from .replica import (DEAD, DRAINING, EngineReplica, LIVE, ReplicaDead)
from .router import Router
from .rpc import (HttpTransport, LoopbackTransport, RpcApplicationError,
                  RpcCircuitOpen, RpcError, RpcProtocolError,
                  RpcServerError, RpcTimeout, RpcTransportError)
from .weights import StalePublishError, WeightPublisher

__all__ = [
    "ACTION_ADD", "ACTION_DRAIN",
    "AdmissionConfig", "AdmissionQueue", "AutoscaleConfig",
    "AutoscaleController", "ClassPolicy", "Completed",
    "DEAD", "DRAINING", "EngineReplica", "EngineRpcHandler",
    "EpisodeStreamer", "ExperienceClient", "ExperienceRpcHandler",
    "FleetPublishClient", "FleetRequest", "FleetRpcHandler",
    "HttpTransport", "INTERACTIVE",
    "LIVE", "LearnerConfig", "LearnerPublishError", "LearnerService",
    "LeaseRpcHandler", "LoopbackTransport", "PRIORITY_CLASSES",
    "PROBE_DEAD", "PROBE_OK", "PROBE_SLOW",
    "REJECT_DEADLINE", "REJECT_NO_REPLICAS",
    "REJECT_QUEUE_FULL", "REJECT_RATE_LIMITED", "REJECT_REPLICA_FAILURE",
    "Rejected", "RemoteEngineClient", "RemoteLeaseStore", "RemoteReplica",
    "ReplicaDead",
    "RequestRejected", "Router", "RpcApplicationError", "RpcCircuitOpen",
    "RpcError", "RpcHandlerBase", "RpcProtocolError", "RpcServerError",
    "RpcTimeout", "RpcTransportError", "ServingFleet",
    "SharedPrefixStore", "StalePublishError",
    "StreamingLearnerConfig", "StreamingLearnerService",
    "TRAIN_ROLLOUT", "TokenBucket", "WeightPublisher",
    "serve_engine_http", "serve_experience_http", "serve_fleet_http",
    "serve_lease_http", "serve_rpc_http",
]
