"""serve/ — multi-replica rollout serving fleet.

Composes N :class:`~senweaver_ide_tpu.rollout.engine.RolloutEngine`
instances behind one facade with admission control (priority classes,
rate limits, deadlines, typed :class:`Rejected` sheds), SLO-aware
routing (prefix affinity + least outstanding work + retry-on-death), and
versioned rolling weight publication. See ``docs/serving.md``.
"""

from .admission import (AdmissionConfig, AdmissionQueue, ClassPolicy,
                        FleetRequest, INTERACTIVE, PRIORITY_CLASSES,
                        REJECT_DEADLINE, REJECT_NO_REPLICAS,
                        REJECT_QUEUE_FULL, REJECT_RATE_LIMITED,
                        REJECT_REPLICA_FAILURE, Rejected,
                        RequestRejected, TRAIN_ROLLOUT, TokenBucket)
from .frontend import Completed, ServingFleet
from .prefix_store import SharedPrefixStore
from .replica import (DEAD, DRAINING, EngineReplica, LIVE, ReplicaDead)
from .router import Router
from .weights import WeightPublisher

__all__ = [
    "AdmissionConfig", "AdmissionQueue", "ClassPolicy", "Completed",
    "DEAD", "DRAINING", "EngineReplica", "FleetRequest", "INTERACTIVE",
    "LIVE", "PRIORITY_CLASSES", "REJECT_DEADLINE", "REJECT_NO_REPLICAS",
    "REJECT_QUEUE_FULL", "REJECT_RATE_LIMITED", "REJECT_REPLICA_FAILURE",
    "Rejected", "ReplicaDead", "RequestRejected", "Router",
    "ServingFleet", "SharedPrefixStore", "TRAIN_ROLLOUT", "TokenBucket",
    "WeightPublisher",
]
