"""Disaggregated learner: crash-tolerant training-side weight publication.

PR 6 built the actor half of the Podracer "Sebulba" split — replicas
that serve across a transport. This module is the learner half: a
process that trains (an :class:`~..training.online.OnlineImprovementLoop`
or any round-running trainer) and publishes versioned weights to a
:class:`~.frontend.ServingFleet` over the same rpc transport, surviving
every failure a separate process invites:

- **zombie/duplicate learners** — every publish carries the
  ``(lease_epoch, weight_version)`` fencing token from the fleet-side
  :class:`~..resilience.lease.LeaseStore`; a superseded learner's
  publishes raise :class:`~.weights.StalePublishError` /
  :class:`~..resilience.lease.LeaseLost` fleet-wide instead of applying.
- **crash/resume** — :meth:`LearnerService.start` re-acquires the lease
  (strictly higher epoch) and, when the durable state file records a
  prior publish, REPUBLISHES that version. A publish torn by the crash
  is superseded by the republish (higher epoch), so the fleet converges
  on the learner's last durable weights — serving never runs a policy
  the trainer cannot resume from.
- **partitions mid-publish** — publish is a resumable saga: stage
  (idempotent under retried request ids, bounded by a learner-side
  :class:`~..resilience.retry.RetryBudget`) → the fleet pump rolls →
  the learner polls convergence. A replica unreachable mid-roll is
  quarantined fleet-side and backfills through ``add_replica``; the
  learner's poll still converges on the reachable set.

The transport is injected: ``LoopbackTransport`` for hermetic CPU tests
(with ``NetworkFaultPlan`` chaos), ``HttpTransport`` against
:func:`~.learner_server.serve_fleet_http` for real deployment.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import threading
import time
import uuid
from typing import Any, Callable, Dict, Optional

from ..resilience.lease import LeaseLost
from ..resilience.retry import RetryBudget, RetryPolicy
from .rpc import RpcApplicationError, RpcError
from .weights import StalePublishError

_client_counter = itertools.count()


class LearnerPublishError(RuntimeError):
    """A staged publish failed to converge within the deadline (the
    fleet is unreachable or wedged — NOT a fencing rejection)."""


class FleetPublishClient:
    """Learner-side rpc proxy to a :class:`~.learner_server.FleetRpcHandler`.

    The retry story mirrors ``RemoteEngineClient._call``: transient wire
    errors retry under a shared :class:`RetryPolicy` (the learner-side
    RetryBudget that bounds retry storms), publishes carry stable
    ``(epoch, version)``-keyed request ids so a retried stage REPLAYS
    server-side, and remote application errors re-raise locally as
    their original types (``LeaseLost`` stays ``LeaseLost`` across the
    wire). Lease calls are NOT idempotency-cached server-side —
    re-executing them on retry is safe — so request ids never need to
    survive a client restart; the per-instance nonce in the default
    ``name`` keeps incarnations from sharing an id space regardless."""

    def __init__(self, transport, *, name: Optional[str] = None,
                 policy: RetryPolicy = RetryPolicy(max_retries=3,
                                                   base_delay_s=0.05,
                                                   max_delay_s=2.0),
                 clock=time.monotonic, sleep=None, rng=None,
                 registry=None):
        self.transport = transport
        if name is None:
            # Unique per INSTANCE, not per target: request ids prefixed
            # by a shared target would collide across restarts (seq
            # restarts at 0), and a colliding id must never be able to
            # replay a previous incarnation's cached response.
            target = getattr(transport, "target",
                             f"learner-{next(_client_counter)}")
            name = f"{target}#{uuid.uuid4().hex[:8]}"
        self.name = name
        self.policy = policy
        self.clock = clock
        self.sleep = sleep or time.sleep
        self._rng = rng
        self._seq = itertools.count()
        if registry is None:
            from ..obs import get_registry
            registry = get_registry()
        self._rpcs_total = registry.counter(
            "senweaver_learner_rpcs_total",
            "Learner→fleet RPCs attempted (per attempt, not per call).",
            labelnames=("method",))
        self._retries_total = registry.counter(
            "senweaver_learner_rpc_retries_total",
            "Learner→fleet RPC retries (transient error, budget left).")

    def _call(self, method: str,
              params: Optional[Dict[str, Any]] = None, *,
              idempotency_key: Optional[str] = None,
              timeout_s: Optional[float] = None) -> Any:
        request_id = idempotency_key or f"{self.name}:{next(self._seq)}"
        budget = RetryBudget(self.policy, now=self.clock(), rng=self._rng)
        while True:
            self._rpcs_total.inc(method=method)
            try:
                return self.transport.call(
                    method, params, request_id=request_id,
                    timeout_s=timeout_s)
            except RpcApplicationError as e:
                e.raise_local()     # LeaseLost / StalePublishError / …
            except RpcError as e:
                if not e.retriable:
                    raise
                delay = budget.next_delay(
                    now=self.clock(),
                    retry_after_s=getattr(e, "retry_after_s", None))
                if delay is None:
                    raise
                self._retries_total.inc()
                if delay > 0:
                    self.sleep(delay)

    # -- gateway surface -----------------------------------------------------
    def acquire_lease(self, holder: str, *,
                      steal: bool = False) -> Dict[str, Any]:
        return self._call("acquire_lease",
                          {"holder": holder, "steal": steal})

    def renew_lease(self, holder: str, epoch: int) -> Dict[str, Any]:
        return self._call("renew_lease",
                          {"holder": holder, "epoch": epoch})

    def release_lease(self, holder: str, epoch: int) -> Dict[str, Any]:
        return self._call("release_lease",
                          {"holder": holder, "epoch": epoch})

    def publish(self, params, *, epoch: int, version: int,
                eager: bool = False,
                timeout_s: Optional[float] = None) -> Dict[str, Any]:
        # The idempotency key is the fencing token itself: a retried
        # stage of (epoch, version) must replay, never double-stage.
        # eager=True requests the fleet's no-drain roll (streaming
        # learner: collection never pauses for the publish).
        return self._call(
            "publish",
            {"params": params, "epoch": epoch, "version": version,
             "eager": eager},
            idempotency_key=f"{self.name}:publish:e{epoch}:v{version}",
            timeout_s=timeout_s)

    def publish_adapter(self, tenant_id: str, lora, *, epoch: int,
                        version: Optional[int] = None,
                        timeout_s: Optional[float] = None) -> Dict[str, Any]:
        # Adapter publishes are fenced by (epoch, per-tenant version);
        # the key mirrors publish: a lost response replays the apply
        # (idempotent — the per-tenant watermark rejects the re-stage).
        return self._call(
            "publish_adapter",
            {"tenant_id": tenant_id, "lora": lora, "epoch": epoch,
             "version": version},
            idempotency_key=(f"{self.name}:publish_adapter:{tenant_id}"
                             f":e{epoch}:v{version}"),
            timeout_s=timeout_s)

    def publish_status(self) -> Dict[str, Any]:
        return self._call("publish_status")

    def signals(self) -> Dict[str, Any]:
        return self._call("signals")

    def fleet_stats(self) -> Dict[str, Any]:
        return self._call("fleet_stats")


@dataclasses.dataclass(frozen=True)
class LearnerConfig:
    """Knobs for one learner process."""

    holder: str = "learner-0"
    # Durable (version, rounds) JSON beside the trainer's checkpoints;
    # None = in-memory only (no crash/resume republish).
    state_path: Optional[str] = None
    publish_timeout_s: float = 30.0
    # Sleep between convergence polls; 0 = poll hot (loopback tests —
    # each poll pumps the fleet one step anyway).
    publish_poll_interval_s: float = 0.0
    steal_lease: bool = False


class LearnerService:
    """One GRPO learner: train a round, publish fenced weights, repeat.

    ``trainer`` is either an object with ``run_round()`` + a
    ``state.params`` attribute (the :class:`OnlineImprovementLoop`
    contract) or a bare callable returning fresh params. The service
    owns no training logic — only leadership, versioning, and the
    publish saga."""

    def __init__(self, trainer, client: FleetPublishClient, *,
                 config: LearnerConfig = LearnerConfig(),
                 clock=time.monotonic, sleep=None, registry=None):
        self.trainer = trainer
        self.client = client
        self.config = config
        self.clock = clock
        self.sleep = sleep or time.sleep
        self.epoch = 0              # guarded-by: _lock
        self.version = 0            # guarded-by: _lock
        self.rounds = 0             # guarded-by: _lock
        self._lease_expires_at: Optional[float] = None  # guarded-by: _lock
        self._lock = threading.Lock()
        if registry is None:
            from ..obs import get_registry
            registry = get_registry()
        self._rounds_total = registry.counter(
            "senweaver_learner_rounds_total",
            "Training rounds the learner completed.")
        self._publishes_total = registry.counter(
            "senweaver_learner_publishes_total",
            "Fenced weight publishes that converged fleet-wide.")
        self._publish_failures_total = registry.counter(
            "senweaver_learner_publish_failures_total",
            "Publishes that failed to stage or converge.")
        self._resumes_total = registry.counter(
            "senweaver_learner_resume_republishes_total",
            "Crash/resume republishes of the last durable version.")
        self._lease_lost_total = registry.counter(
            "senweaver_learner_lease_lost_total",
            "Lease losses observed (superseded by another learner).")
        self._epoch_gauge = registry.gauge(
            "senweaver_learner_lease_epoch",
            "This learner's fencing epoch (0 = no lease).")
        self._version_gauge = registry.gauge(
            "senweaver_learner_weight_version",
            "Last weight version this learner published durably.")
        self._epoch_gauge.set(0)
        self._version_gauge.set(0)

    # -- durable state -------------------------------------------------------
    def _load_state(self) -> Dict[str, Any]:
        path = self.config.state_path
        if path is None or not os.path.exists(path):
            return {}
        try:
            with open(path, "r", encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            # Torn write from a crash — treat as no durable state; the
            # save path is atomic, so this only covers external damage.
            return {}

    def _save_state(self) -> None:
        path = self.config.state_path
        if path is None:
            return
        with self._lock:
            payload = {"weight_version": self.version,
                       "rounds": self.rounds}
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        os.replace(tmp, path)

    # -- leadership ----------------------------------------------------------
    def start(self) -> int:
        """Acquire the lease (a strictly higher epoch — fencing out any
        previous incarnation) and reconverge the fleet: republish the
        last durable version if one exists, else adopt the fleet's
        current version so the next publish lands above it. Returns
        the lease epoch."""
        saved = self._load_state()
        lease = self.client.acquire_lease(self.config.holder,
                                          steal=self.config.steal_lease)
        with self._lock:
            self.epoch = int(lease["epoch"])
            self._lease_expires_at = float(lease["expires_at"])
            self.rounds = int(saved.get("rounds", 0))
        self._epoch_gauge.set(self.epoch)
        durable_version = int(saved.get("weight_version", 0))
        if durable_version > 0:
            with self._lock:
                self.version = durable_version
            self._resumes_total.inc()
            self._publish(self._params(), durable_version)
        else:
            fleet_version = int(
                self.client.signals().get("weight_version", 0))
            with self._lock:
                self.version = max(self.version, fleet_version)
        self._version_gauge.set(self.version)
        return self.epoch

    def stop(self) -> None:
        """Voluntary leadership release (best-effort — a crash skips
        this and the TTL/fencing path covers it)."""
        with self._lock:
            epoch = self.epoch
        if epoch > 0:
            try:
                self.client.release_lease(self.config.holder, epoch)
            except (RpcError, LeaseLost):
                pass

    def _renew(self) -> None:
        try:
            lease = self.client.renew_lease(self.config.holder,
                                            self.epoch)
        except LeaseLost:
            self._lease_lost_total.inc()
            self._epoch_gauge.set(0)
            raise
        with self._lock:
            self._lease_expires_at = float(lease["expires_at"])

    # -- the round -----------------------------------------------------------
    def _params(self):
        t = self.trainer
        state = getattr(t, "state", None)
        if state is not None and hasattr(state, "params"):
            return state.params
        if callable(t):
            # Bare-callable trainers expose no "current params" — the
            # crash/resume republish invokes the callable once so a
            # restart with durable state has weights to publish.
            return t()
        raise ValueError(
            "trainer has neither state.params nor __call__; the "
            "learner cannot obtain params to publish")

    def _train(self):
        t = self.trainer
        if hasattr(t, "run_round"):
            t.run_round()
            return t.state.params
        return t()

    def run_round(self) -> int:
        """Renew leadership, train one round, publish the new version;
        returns the published version. Raises :class:`LeaseLost` /
        :class:`StalePublishError` when fenced out — the caller must
        stop training, not retry."""
        self._renew()
        params = self._train()
        with self._lock:
            self.version += 1
            version = self.version
        try:
            self._publish(params, version)
        except (LeaseLost, StalePublishError):
            # Fenced out mid-round: roll the version back so a (buggy)
            # caller that keeps going cannot silently skip numbers.
            with self._lock:
                self.version = version - 1
            self._lease_lost_total.inc()
            raise
        with self._lock:
            self.rounds += 1
        self._rounds_total.inc()
        self._save_state()
        self._version_gauge.set(version)
        return version

    def run(self, rounds: int) -> int:
        for _ in range(rounds):
            self.run_round()
        return self.version

    # -- the publish saga ----------------------------------------------------
    def _publish(self, params, version: int) -> None:
        """Stage (idempotent, retry-bounded) then poll to convergence."""
        deadline = self.clock() + self.config.publish_timeout_s
        try:
            self.client.publish(params, epoch=self.epoch,
                                version=version)
        except (LeaseLost, StalePublishError):
            self._publish_failures_total.inc()
            raise
        except RpcError as e:
            self._publish_failures_total.inc()
            raise LearnerPublishError(
                f"publish v{version} failed to stage: {e}") from e
        while True:
            try:
                status = self.client.publish_status()
            except RpcError as e:
                self._publish_failures_total.inc()
                raise LearnerPublishError(
                    f"publish v{version} staged but convergence poll "
                    f"failed: {e}") from e
            if (status.get("converged")
                    and int(status.get("version", -1)) == version
                    and int(status.get("epoch", -1)) == self.epoch):
                break
            if int(status.get("epoch", 0)) > self.epoch:
                # Another learner took over while we rolled.
                self._publish_failures_total.inc()
                raise LeaseLost(
                    f"fleet moved to epoch {status.get('epoch')} while "
                    f"publishing at epoch {self.epoch}")
            if self.clock() >= deadline:
                self._publish_failures_total.inc()
                raise LearnerPublishError(
                    f"publish v{version} staged but did not converge "
                    f"within {self.config.publish_timeout_s}s "
                    f"(status: {status})")
            if self.config.publish_poll_interval_s > 0:
                self.sleep(self.config.publish_poll_interval_s)
        self._publishes_total.inc()
        self._save_state()


# -- streaming (continuous-flow) learner -------------------------------------


class ExperienceClient:
    """Collector-side rpc proxy to an
    :class:`~.learner_server.ExperienceRpcHandler`. Submits episode
    batches under a DETERMINISTIC idempotency key (first episode id +
    count) so a retried submit whose ack frame was lost replays the
    recorded acks instead of re-offering; the learner queue's seen-set
    is the second, incarnation-proof line of defense."""

    def __init__(self, transport, *, name: Optional[str] = None,
                 policy: RetryPolicy = RetryPolicy(max_retries=3,
                                                   base_delay_s=0.05,
                                                   max_delay_s=2.0),
                 clock=time.monotonic, sleep=None, rng=None,
                 registry=None):
        self._rpc = FleetPublishClient(transport, name=name,
                                       policy=policy, clock=clock,
                                       sleep=sleep, rng=rng,
                                       registry=registry)
        self.name = self._rpc.name

    def submit(self, episodes) -> Dict[str, str]:
        """Offer ``episodes`` to the learner; returns
        ``{episode_id: outcome}`` acks (see training/experience.py for
        the vocabulary). Transport errors propagate after the retry
        budget — the caller (:class:`EpisodeStreamer`) keeps the batch
        buffered and tries again later."""
        if not episodes:
            return {}
        key = (f"{self.name}:submit:{episodes[0].episode_id}"
               f"+{len(episodes)}")
        out = self._rpc._call(
            "submit_episodes",
            {"episodes": [ep.to_wire() for ep in episodes]},
            idempotency_key=key)
        return dict(out.get("acks", {}))

    def stream_stats(self) -> Dict[str, Any]:
        return self._rpc._call("stream_stats")


class EpisodeStreamer:
    """Collector-side at-least-once buffer: episodes stay pending until
    the learner acks them (accepted / duplicate / stale all retire the
    id — only ``full`` and transport failures keep it buffered for the
    next flush). Paired with the learner's seen-set dedup this gives
    exactly-once training effect under drops, replays, and learner
    restarts. The stall gauge is the collector half of the headline
    metric: the fraction of flushes that could not fully hand off."""

    def __init__(self, client: ExperienceClient, *, registry=None):
        self.client = client
        self._pending: list = []
        self._flushes = 0
        self._stalls = 0
        if registry is None:
            from ..obs import get_registry
            registry = get_registry()
        self._submitted_total = registry.counter(
            "senweaver_collector_episodes_submitted_total",
            "Episode submissions attempted by the collector "
            "(per flush attempt, so retries count again).")
        self._retired_total = registry.counter(
            "senweaver_collector_episodes_retired_total",
            "Episodes retired from the collector buffer, by learner "
            "ack outcome.", labelnames=("outcome",))
        self._stall_gauge = registry.gauge(
            "senweaver_collector_stall_fraction",
            "Fraction of collector flushes that left episodes pending "
            "(queue full or learner unreachable — backpressure).")
        self._stall_gauge.set(0.0)

    @property
    def pending(self) -> int:
        return len(self._pending)

    def offer(self, episodes) -> None:
        self._pending.extend(episodes)

    def flush(self) -> Dict[str, int]:
        """One submit attempt over everything pending; returns
        ``{"retired": n, "pending": m}``. Never raises — a transport
        failure keeps the batch for the next flush (at-least-once)."""
        if not self._pending:
            return {"retired": 0, "pending": 0}
        self._flushes += 1
        self._submitted_total.inc(len(self._pending))
        try:
            acks = self.client.submit(self._pending)
        except (RpcError, LeaseLost):
            self._stalls += 1
            self._stall_gauge.set(self._stalls / self._flushes)
            return {"retired": 0, "pending": len(self._pending)}
        keep = []
        retired = 0
        for ep in self._pending:
            outcome = acks.get(ep.episode_id)
            if outcome in ("accepted", "duplicate", "stale"):
                self._retired_total.inc(outcome=outcome)
                retired += 1
            else:                   # "full" or missing: resubmit later
                keep.append(ep)
        self._pending = keep
        if keep:
            self._stalls += 1
        self._stall_gauge.set(self._stalls / self._flushes)
        return {"retired": retired, "pending": len(keep)}


@dataclasses.dataclass(frozen=True)
class StreamingLearnerConfig:
    """Knobs for the continuous-flow learner mode."""

    group_size: int = 4
    # Train as soon as this many COMPLETE groups are ready.
    min_groups: int = 1
    # Hard staleness bound: episodes more than this many versions
    # behind are dropped and counted, never trained.
    max_staleness: int = 4
    queue_capacity: int = 1024
    seen_capacity: int = 65536
    # Seen-ids persisted with the durable state (the no-double-train
    # half of crash recovery).
    seen_snapshot_limit: int = 8192
    # Stage publishes as no-drain eager rolls (collection never
    # pauses); the lockstep fallback always publishes draining+blocking
    # regardless.
    eager_publish: bool = True


class StreamingLearnerService(LearnerService):
    """Continuous-flow GRPO learner: train on streamed episode groups
    the moment a staleness-bounded batch is ready; publish WITHOUT
    blocking on roll convergence (the fenced no-drain path), polling
    opportunistically between steps.

    ``trainer`` must expose ``state.params`` and
    ``train_on_batch(episodes) -> metrics`` —
    :class:`~..training.experience.StreamingTrainerAdapter` is the
    concrete GRPO implementation; tests use lighter fakes. When it
    also exposes ``note_published(version)`` the service calls it at
    every accepted stage so the behavior-params cache can serve
    importance-ratio recomputes.

    Correctness story (ISSUE 15): per-episode behavior stamps +
    recorded logps give token-exact importance ratios; the hard
    staleness bound drops (and counts) what correction can't fix; the
    ``staleness_drift`` health detector + mitigation hysteresis can
    veto the async mode back to lockstep (synchronous, blocking
    publishes) until staleness quiets; the queue's seen-set plus the
    collector's resubmit-until-acked buffer give exactly-once training
    effect across crashes and replays."""

    def __init__(self, trainer, client: FleetPublishClient, *,
                 stream_config: StreamingLearnerConfig =
                 StreamingLearnerConfig(),
                 config: LearnerConfig = LearnerConfig(),
                 health_config=None, mitigator=None,
                 clock=time.monotonic, sleep=None, registry=None):
        super().__init__(trainer, client, config=config, clock=clock,
                         sleep=sleep, registry=registry)
        if registry is None:
            from ..obs import get_registry
            registry = get_registry()
        from ..training.experience import ExperienceQueue
        self.stream_config = stream_config
        self.queue = ExperienceQueue(
            group_size=stream_config.group_size,
            capacity=stream_config.queue_capacity,
            max_staleness=stream_config.max_staleness,
            min_groups=stream_config.min_groups,
            seen_capacity=stream_config.seen_capacity,
            registry=registry)
        # staleness_drift detector + lockstep veto (both optional).
        self.health_config = health_config
        self.mitigator = mitigator
        self._outstanding_publish: Optional[int] = None  # guarded-by: _lock
        self._busy_s = 0.0              # guarded-by: _lock
        self._idle_s = 0.0              # guarded-by: _lock
        self._idle_gauge = registry.gauge(
            "senweaver_learner_idle_fraction",
            "Fraction of learner wall time spent waiting for a ready "
            "batch (streamed mode's headline vs lockstep).")
        self._mode_gauge = registry.gauge(
            "senweaver_learner_streaming_mode",
            "1 = streaming (async no-drain publishes), 0 = lockstep "
            "fallback (staleness-drift veto active).")
        self._steps_total = registry.counter(
            "senweaver_learner_stream_steps_total",
            "Streaming train steps, by mode.", labelnames=("mode",))
        self._idle_gauge.set(0.0)
        self._mode_gauge.set(1)

    # -- intake (called by ExperienceRpcHandler) -----------------------------
    def intake(self, episodes) -> Dict[str, Any]:
        with self._lock:
            version = self.version
        return self.queue.offer_many(episodes, current_version=version)

    def stream_stats(self) -> Dict[str, Any]:
        st = dict(self.queue.stats())
        with self._lock:
            st.update({"version": self.version, "epoch": self.epoch,
                       "outstanding_publish": self._outstanding_publish})
        st["mode"] = "lockstep" if self._lockstep() else "streaming"
        return st

    def _lockstep(self) -> bool:
        return (self.mitigator is not None
                and self.mitigator.lockstep_fallback_active())

    # -- durable state (adds the seen-ids snapshot) --------------------------
    def _save_state(self) -> None:
        path = self.config.state_path
        if path is None:
            return
        with self._lock:
            payload = {"weight_version": self.version,
                       "rounds": self.rounds}
        payload["seen_episodes"] = self.queue.seen_snapshot(
            limit=self.stream_config.seen_snapshot_limit)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        os.replace(tmp, path)

    def start(self) -> int:
        # Restore the predecessor's seen-ids BEFORE the lease/republish
        # handshake: collectors may resubmit the moment the endpoint is
        # back, and anything the previous incarnation trained must ack
        # "duplicate", not re-enter the queue.
        saved = self._load_state()
        self.queue.restore_seen(saved.get("seen_episodes", []))
        epoch = super().start()
        self._note_published_to_trainer()
        return epoch

    def _note_published_to_trainer(self) -> None:
        note = getattr(self.trainer, "note_published", None)
        if note is not None:
            with self._lock:
                version = self.version
            note(version)

    # -- the async publish saga ----------------------------------------------
    def pump_publish(self, *, block: bool = False) -> bool:
        """Drive any outstanding staged publish toward convergence;
        returns True when none remains. One non-blocking status poll by
        default (which also pumps a manual fleet one step);
        ``block=True`` polls to the publish deadline — the lockstep
        fallback's synchronous shape. Raises :class:`LeaseLost` when
        the fleet moved to a higher epoch."""
        with self._lock:
            outstanding = self._outstanding_publish
        if outstanding is None:
            return True
        deadline = self.clock() + self.config.publish_timeout_s
        while True:
            try:
                status = self.client.publish_status()
            except RpcError as e:
                if not block:
                    return False
                self._publish_failures_total.inc()
                raise LearnerPublishError(
                    f"publish v{outstanding} staged but convergence "
                    f"poll failed: {e}") from e
            if int(status.get("epoch", 0)) > self.epoch:
                self._publish_failures_total.inc()
                self._lease_lost_total.inc()
                raise LeaseLost(
                    f"fleet moved to epoch {status.get('epoch')} while "
                    f"streaming at epoch {self.epoch}")
            # >= because a superseding stage fast-forwards the roll:
            # convergence at ANY version past the outstanding one
            # retires it.
            if (status.get("converged")
                    and int(status.get("version", -1)) >= outstanding
                    and int(status.get("epoch", -1)) == self.epoch):
                with self._lock:
                    self._outstanding_publish = None
                self._publishes_total.inc()
                return True
            if not block:
                return False
            if self.clock() >= deadline:
                self._publish_failures_total.inc()
                raise LearnerPublishError(
                    f"publish v{outstanding} staged but did not "
                    f"converge within {self.config.publish_timeout_s}s "
                    f"(status: {status})")
            if self.config.publish_poll_interval_s > 0:
                self.sleep(self.config.publish_poll_interval_s)

    def _stage_publish(self, params, version: int) -> None:
        """Stage (idempotent, fenced, no-drain) WITHOUT waiting for the
        roll — the streaming learner keeps training while the fleet
        pump swaps replicas at zero in-flight."""
        try:
            self.client.publish(params, epoch=self.epoch,
                                version=version,
                                eager=self.stream_config.eager_publish)
        except (LeaseLost, StalePublishError):
            self._publish_failures_total.inc()
            raise
        except RpcError as e:
            self._publish_failures_total.inc()
            raise LearnerPublishError(
                f"publish v{version} failed to stage: {e}") from e
        with self._lock:
            self._outstanding_publish = version

    # -- the streaming step --------------------------------------------------
    def run_step(self) -> Optional[Dict[str, Any]]:
        """One continuous-flow step: pump the outstanding publish, pop
        a staleness-bounded batch, train, stage the next version.
        Returns the step record, or None when no batch was ready (the
        idle fraction accounts the wait). Raises :class:`LeaseLost` /
        :class:`StalePublishError` when fenced out."""
        t0 = self.clock()
        lockstep = self._lockstep()
        self._mode_gauge.set(0 if lockstep else 1)
        # Lockstep fallback: block until the previous publish fully
        # landed — zero skew, zero staleness growth — before training.
        self.pump_publish(block=lockstep)
        with self._lock:
            version = self.version
        batch = self.queue.take_batch(
            current_version=version,
            min_groups=self.stream_config.min_groups)
        if batch is None:
            self._note_step_time(t0, busy=False)
            return None
        self._renew()
        metrics = self.trainer.train_on_batch(batch)
        staleness = [max(0, version - ep.version) for ep in batch]
        staleness_mean = sum(staleness) / len(staleness)
        with self._lock:
            self.version += 1
            new_version = self.version
        params = self._params()
        try:
            if lockstep:
                self._publish(params, new_version)
            else:
                self._stage_publish(params, new_version)
        except (LeaseLost, StalePublishError):
            with self._lock:
                self.version = new_version - 1
            self._lease_lost_total.inc()
            raise
        self._note_published_to_trainer()
        with self._lock:
            self.rounds += 1
        self._rounds_total.inc()
        mode = "lockstep" if lockstep else "streaming"
        self._steps_total.inc(mode=mode)
        self._save_state()
        self._version_gauge.set(new_version)
        events = self._observe_health(staleness_mean, len(batch))
        self._note_step_time(t0, busy=True)
        return {"version": new_version, "mode": mode,
                "episodes": len(batch),
                "staleness_mean": staleness_mean,
                "metrics": metrics, "events": events}

    # -- health / accounting -------------------------------------------------
    def _observe_health(self, staleness_mean: float,
                        batch_size: int) -> list:
        """Feed the streaming signals to the staleness_drift detector
        and fold the trigger into the mitigator's streak hysteresis —
        the veto that flips async back to lockstep (and, after quiet
        rounds, back again)."""
        if self.health_config is None and self.mitigator is None:
            return []
        stats = self.queue.stats()
        dropped = stats.get("stale_dropped", 0)
        consumed = dropped + max(1, stats.get("accepted", 1))
        health = {"staleness_mean": float(staleness_mean),
                  "stale_drop_fraction": dropped / consumed}
        triggers = []
        if self.health_config is not None:
            from ..obs.training_health import evaluate_health
            triggers = evaluate_health(health, self.health_config)
        events = []
        if self.mitigator is not None:
            grpo_config = getattr(self.trainer, "grpo_config", None)
            if grpo_config is None:
                from ..training.trainer import GRPOConfig
                grpo_config = GRPOConfig()
            _, events = self.mitigator.apply(grpo_config, triggers)
        return events

    def _note_step_time(self, t0: float, *, busy: bool) -> None:
        dt = max(0.0, self.clock() - t0)
        with self._lock:
            if busy:
                self._busy_s += dt
            else:
                self._idle_s += dt
            total = self._busy_s + self._idle_s
            idle = self._idle_s / total if total > 0 else 0.0
        self._idle_gauge.set(idle)

    def note_idle(self, seconds: float) -> None:
        """Credit learner wall time spent waiting for experience that
        run_step itself didn't see (a driver sleeping between polls)."""
        with self._lock:
            self._idle_s += max(0.0, float(seconds))
            total = self._busy_s + self._idle_s
            idle = self._idle_s / total if total > 0 else 0.0
        self._idle_gauge.set(idle)

    def idle_fraction(self) -> float:
        with self._lock:
            total = self._busy_s + self._idle_s
            return self._idle_s / total if total > 0 else 0.0

    def reset_utilization(self) -> None:
        """Zero the busy/idle accounting. Call after warmup so one-time
        jit compiles don't swamp the steady-state idle fraction."""
        with self._lock:
            self._busy_s = 0.0
            self._idle_s = 0.0
        self._idle_gauge.set(0.0)
