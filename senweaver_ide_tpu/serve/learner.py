"""Disaggregated learner: crash-tolerant training-side weight publication.

PR 6 built the actor half of the Podracer "Sebulba" split — replicas
that serve across a transport. This module is the learner half: a
process that trains (an :class:`~..training.online.OnlineImprovementLoop`
or any round-running trainer) and publishes versioned weights to a
:class:`~.frontend.ServingFleet` over the same rpc transport, surviving
every failure a separate process invites:

- **zombie/duplicate learners** — every publish carries the
  ``(lease_epoch, weight_version)`` fencing token from the fleet-side
  :class:`~..resilience.lease.LeaseStore`; a superseded learner's
  publishes raise :class:`~.weights.StalePublishError` /
  :class:`~..resilience.lease.LeaseLost` fleet-wide instead of applying.
- **crash/resume** — :meth:`LearnerService.start` re-acquires the lease
  (strictly higher epoch) and, when the durable state file records a
  prior publish, REPUBLISHES that version. A publish torn by the crash
  is superseded by the republish (higher epoch), so the fleet converges
  on the learner's last durable weights — serving never runs a policy
  the trainer cannot resume from.
- **partitions mid-publish** — publish is a resumable saga: stage
  (idempotent under retried request ids, bounded by a learner-side
  :class:`~..resilience.retry.RetryBudget`) → the fleet pump rolls →
  the learner polls convergence. A replica unreachable mid-roll is
  quarantined fleet-side and backfills through ``add_replica``; the
  learner's poll still converges on the reachable set.

The transport is injected: ``LoopbackTransport`` for hermetic CPU tests
(with ``NetworkFaultPlan`` chaos), ``HttpTransport`` against
:func:`~.learner_server.serve_fleet_http` for real deployment.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import threading
import time
import uuid
from typing import Any, Callable, Dict, Optional

from ..resilience.lease import LeaseLost
from ..resilience.retry import RetryBudget, RetryPolicy
from .rpc import RpcApplicationError, RpcError
from .weights import StalePublishError

_client_counter = itertools.count()


class LearnerPublishError(RuntimeError):
    """A staged publish failed to converge within the deadline (the
    fleet is unreachable or wedged — NOT a fencing rejection)."""


class FleetPublishClient:
    """Learner-side rpc proxy to a :class:`~.learner_server.FleetRpcHandler`.

    The retry story mirrors ``RemoteEngineClient._call``: transient wire
    errors retry under a shared :class:`RetryPolicy` (the learner-side
    RetryBudget that bounds retry storms), publishes carry stable
    ``(epoch, version)``-keyed request ids so a retried stage REPLAYS
    server-side, and remote application errors re-raise locally as
    their original types (``LeaseLost`` stays ``LeaseLost`` across the
    wire). Lease calls are NOT idempotency-cached server-side —
    re-executing them on retry is safe — so request ids never need to
    survive a client restart; the per-instance nonce in the default
    ``name`` keeps incarnations from sharing an id space regardless."""

    def __init__(self, transport, *, name: Optional[str] = None,
                 policy: RetryPolicy = RetryPolicy(max_retries=3,
                                                   base_delay_s=0.05,
                                                   max_delay_s=2.0),
                 clock=time.monotonic, sleep=None, rng=None,
                 registry=None):
        self.transport = transport
        if name is None:
            # Unique per INSTANCE, not per target: request ids prefixed
            # by a shared target would collide across restarts (seq
            # restarts at 0), and a colliding id must never be able to
            # replay a previous incarnation's cached response.
            target = getattr(transport, "target",
                             f"learner-{next(_client_counter)}")
            name = f"{target}#{uuid.uuid4().hex[:8]}"
        self.name = name
        self.policy = policy
        self.clock = clock
        self.sleep = sleep or time.sleep
        self._rng = rng
        self._seq = itertools.count()
        if registry is None:
            from ..obs import get_registry
            registry = get_registry()
        self._rpcs_total = registry.counter(
            "senweaver_learner_rpcs_total",
            "Learner→fleet RPCs attempted (per attempt, not per call).",
            labelnames=("method",))
        self._retries_total = registry.counter(
            "senweaver_learner_rpc_retries_total",
            "Learner→fleet RPC retries (transient error, budget left).")

    def _call(self, method: str,
              params: Optional[Dict[str, Any]] = None, *,
              idempotency_key: Optional[str] = None,
              timeout_s: Optional[float] = None) -> Any:
        request_id = idempotency_key or f"{self.name}:{next(self._seq)}"
        budget = RetryBudget(self.policy, now=self.clock(), rng=self._rng)
        while True:
            self._rpcs_total.inc(method=method)
            try:
                return self.transport.call(
                    method, params, request_id=request_id,
                    timeout_s=timeout_s)
            except RpcApplicationError as e:
                e.raise_local()     # LeaseLost / StalePublishError / …
            except RpcError as e:
                if not e.retriable:
                    raise
                delay = budget.next_delay(
                    now=self.clock(),
                    retry_after_s=getattr(e, "retry_after_s", None))
                if delay is None:
                    raise
                self._retries_total.inc()
                if delay > 0:
                    self.sleep(delay)

    # -- gateway surface -----------------------------------------------------
    def acquire_lease(self, holder: str, *,
                      steal: bool = False) -> Dict[str, Any]:
        return self._call("acquire_lease",
                          {"holder": holder, "steal": steal})

    def renew_lease(self, holder: str, epoch: int) -> Dict[str, Any]:
        return self._call("renew_lease",
                          {"holder": holder, "epoch": epoch})

    def release_lease(self, holder: str, epoch: int) -> Dict[str, Any]:
        return self._call("release_lease",
                          {"holder": holder, "epoch": epoch})

    def publish(self, params, *, epoch: int, version: int,
                timeout_s: Optional[float] = None) -> Dict[str, Any]:
        # The idempotency key is the fencing token itself: a retried
        # stage of (epoch, version) must replay, never double-stage.
        return self._call(
            "publish",
            {"params": params, "epoch": epoch, "version": version},
            idempotency_key=f"{self.name}:publish:e{epoch}:v{version}",
            timeout_s=timeout_s)

    def publish_adapter(self, tenant_id: str, lora, *, epoch: int,
                        version: Optional[int] = None,
                        timeout_s: Optional[float] = None) -> Dict[str, Any]:
        # Adapter publishes are fenced by (epoch, per-tenant version);
        # the key mirrors publish: a lost response replays the apply
        # (idempotent — the per-tenant watermark rejects the re-stage).
        return self._call(
            "publish_adapter",
            {"tenant_id": tenant_id, "lora": lora, "epoch": epoch,
             "version": version},
            idempotency_key=(f"{self.name}:publish_adapter:{tenant_id}"
                             f":e{epoch}:v{version}"),
            timeout_s=timeout_s)

    def publish_status(self) -> Dict[str, Any]:
        return self._call("publish_status")

    def signals(self) -> Dict[str, Any]:
        return self._call("signals")

    def fleet_stats(self) -> Dict[str, Any]:
        return self._call("fleet_stats")


@dataclasses.dataclass(frozen=True)
class LearnerConfig:
    """Knobs for one learner process."""

    holder: str = "learner-0"
    # Durable (version, rounds) JSON beside the trainer's checkpoints;
    # None = in-memory only (no crash/resume republish).
    state_path: Optional[str] = None
    publish_timeout_s: float = 30.0
    # Sleep between convergence polls; 0 = poll hot (loopback tests —
    # each poll pumps the fleet one step anyway).
    publish_poll_interval_s: float = 0.0
    steal_lease: bool = False


class LearnerService:
    """One GRPO learner: train a round, publish fenced weights, repeat.

    ``trainer`` is either an object with ``run_round()`` + a
    ``state.params`` attribute (the :class:`OnlineImprovementLoop`
    contract) or a bare callable returning fresh params. The service
    owns no training logic — only leadership, versioning, and the
    publish saga."""

    def __init__(self, trainer, client: FleetPublishClient, *,
                 config: LearnerConfig = LearnerConfig(),
                 clock=time.monotonic, sleep=None, registry=None):
        self.trainer = trainer
        self.client = client
        self.config = config
        self.clock = clock
        self.sleep = sleep or time.sleep
        self.epoch = 0              # guarded-by: _lock
        self.version = 0            # guarded-by: _lock
        self.rounds = 0             # guarded-by: _lock
        self._lease_expires_at: Optional[float] = None  # guarded-by: _lock
        self._lock = threading.Lock()
        if registry is None:
            from ..obs import get_registry
            registry = get_registry()
        self._rounds_total = registry.counter(
            "senweaver_learner_rounds_total",
            "Training rounds the learner completed.")
        self._publishes_total = registry.counter(
            "senweaver_learner_publishes_total",
            "Fenced weight publishes that converged fleet-wide.")
        self._publish_failures_total = registry.counter(
            "senweaver_learner_publish_failures_total",
            "Publishes that failed to stage or converge.")
        self._resumes_total = registry.counter(
            "senweaver_learner_resume_republishes_total",
            "Crash/resume republishes of the last durable version.")
        self._lease_lost_total = registry.counter(
            "senweaver_learner_lease_lost_total",
            "Lease losses observed (superseded by another learner).")
        self._epoch_gauge = registry.gauge(
            "senweaver_learner_lease_epoch",
            "This learner's fencing epoch (0 = no lease).")
        self._version_gauge = registry.gauge(
            "senweaver_learner_weight_version",
            "Last weight version this learner published durably.")
        self._epoch_gauge.set(0)
        self._version_gauge.set(0)

    # -- durable state -------------------------------------------------------
    def _load_state(self) -> Dict[str, Any]:
        path = self.config.state_path
        if path is None or not os.path.exists(path):
            return {}
        try:
            with open(path, "r", encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            # Torn write from a crash — treat as no durable state; the
            # save path is atomic, so this only covers external damage.
            return {}

    def _save_state(self) -> None:
        path = self.config.state_path
        if path is None:
            return
        with self._lock:
            payload = {"weight_version": self.version,
                       "rounds": self.rounds}
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        os.replace(tmp, path)

    # -- leadership ----------------------------------------------------------
    def start(self) -> int:
        """Acquire the lease (a strictly higher epoch — fencing out any
        previous incarnation) and reconverge the fleet: republish the
        last durable version if one exists, else adopt the fleet's
        current version so the next publish lands above it. Returns
        the lease epoch."""
        saved = self._load_state()
        lease = self.client.acquire_lease(self.config.holder,
                                          steal=self.config.steal_lease)
        with self._lock:
            self.epoch = int(lease["epoch"])
            self._lease_expires_at = float(lease["expires_at"])
            self.rounds = int(saved.get("rounds", 0))
        self._epoch_gauge.set(self.epoch)
        durable_version = int(saved.get("weight_version", 0))
        if durable_version > 0:
            with self._lock:
                self.version = durable_version
            self._resumes_total.inc()
            self._publish(self._params(), durable_version)
        else:
            fleet_version = int(
                self.client.signals().get("weight_version", 0))
            with self._lock:
                self.version = max(self.version, fleet_version)
        self._version_gauge.set(self.version)
        return self.epoch

    def stop(self) -> None:
        """Voluntary leadership release (best-effort — a crash skips
        this and the TTL/fencing path covers it)."""
        with self._lock:
            epoch = self.epoch
        if epoch > 0:
            try:
                self.client.release_lease(self.config.holder, epoch)
            except (RpcError, LeaseLost):
                pass

    def _renew(self) -> None:
        try:
            lease = self.client.renew_lease(self.config.holder,
                                            self.epoch)
        except LeaseLost:
            self._lease_lost_total.inc()
            self._epoch_gauge.set(0)
            raise
        with self._lock:
            self._lease_expires_at = float(lease["expires_at"])

    # -- the round -----------------------------------------------------------
    def _params(self):
        t = self.trainer
        state = getattr(t, "state", None)
        if state is not None and hasattr(state, "params"):
            return state.params
        if callable(t):
            # Bare-callable trainers expose no "current params" — the
            # crash/resume republish invokes the callable once so a
            # restart with durable state has weights to publish.
            return t()
        raise ValueError(
            "trainer has neither state.params nor __call__; the "
            "learner cannot obtain params to publish")

    def _train(self):
        t = self.trainer
        if hasattr(t, "run_round"):
            t.run_round()
            return t.state.params
        return t()

    def run_round(self) -> int:
        """Renew leadership, train one round, publish the new version;
        returns the published version. Raises :class:`LeaseLost` /
        :class:`StalePublishError` when fenced out — the caller must
        stop training, not retry."""
        self._renew()
        params = self._train()
        with self._lock:
            self.version += 1
            version = self.version
        try:
            self._publish(params, version)
        except (LeaseLost, StalePublishError):
            # Fenced out mid-round: roll the version back so a (buggy)
            # caller that keeps going cannot silently skip numbers.
            with self._lock:
                self.version = version - 1
            self._lease_lost_total.inc()
            raise
        with self._lock:
            self.rounds += 1
        self._rounds_total.inc()
        self._save_state()
        self._version_gauge.set(version)
        return version

    def run(self, rounds: int) -> int:
        for _ in range(rounds):
            self.run_round()
        return self.version

    # -- the publish saga ----------------------------------------------------
    def _publish(self, params, version: int) -> None:
        """Stage (idempotent, retry-bounded) then poll to convergence."""
        deadline = self.clock() + self.config.publish_timeout_s
        try:
            self.client.publish(params, epoch=self.epoch,
                                version=version)
        except (LeaseLost, StalePublishError):
            self._publish_failures_total.inc()
            raise
        except RpcError as e:
            self._publish_failures_total.inc()
            raise LearnerPublishError(
                f"publish v{version} failed to stage: {e}") from e
        while True:
            try:
                status = self.client.publish_status()
            except RpcError as e:
                self._publish_failures_total.inc()
                raise LearnerPublishError(
                    f"publish v{version} staged but convergence poll "
                    f"failed: {e}") from e
            if (status.get("converged")
                    and int(status.get("version", -1)) == version
                    and int(status.get("epoch", -1)) == self.epoch):
                break
            if int(status.get("epoch", 0)) > self.epoch:
                # Another learner took over while we rolled.
                self._publish_failures_total.inc()
                raise LeaseLost(
                    f"fleet moved to epoch {status.get('epoch')} while "
                    f"publishing at epoch {self.epoch}")
            if self.clock() >= deadline:
                self._publish_failures_total.inc()
                raise LearnerPublishError(
                    f"publish v{version} staged but did not converge "
                    f"within {self.config.publish_timeout_s}s "
                    f"(status: {status})")
            if self.config.publish_poll_interval_s > 0:
                self.sleep(self.config.publish_poll_interval_s)
        self._publishes_total.inc()
        self._save_state()
