"""EngineReplica — one RolloutEngine as a member of a serving fleet.

The wrapper owns what the bare engine doesn't know it has: an identity
(``replica_id``), a health state machine (LIVE → DRAINING → LIVE for
weight rolls, anything → DEAD on faults), a weight version, the
in-flight request map the router balances on, and — in threaded mode —
the stepper thread that drives ``engine.step()`` so N replicas decode
concurrently while the fleet's dispatcher admits and routes.

Fault vocabulary is reused from ``resilience.faults`` (REASON_ERROR /
REASON_TIMEOUT): a replica that throws out of submit/step records a
fault, and ``max_consecutive_faults`` of them without a healthy step in
between kill it — the same escalate-after-bounded-retries shape the
episode boundary uses, applied to the serving plane.

State transitions never lose requests: ``kill()`` returns the orphaned
in-flight FleetRequests so the router can resubmit them elsewhere (or
shed them with a typed Rejected when retries are spent).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from ..resilience.faults import REASON_ERROR
from .admission import FleetRequest

LIVE = "live"
DRAINING = "draining"
DEAD = "dead"
_STATE_CODE = {LIVE: 0, DRAINING: 1, DEAD: 2}


class ReplicaDead(RuntimeError):
    """Operation attempted on a DEAD replica."""


class EngineReplica:
    """One engine + its fleet-facing bookkeeping. All mutation is
    serialized under ``self._lock`` (the engine has its own lock; this
    one covers the replica's maps so the dispatcher thread and the
    stepper thread compose)."""

    def __init__(self, replica_id: str, engine, *,
                 max_consecutive_faults: int = 3,
                 host_group: Optional[str] = None,
                 registry=None):
        self.replica_id = replica_id
        self.engine = engine
        # Rack/host placement label for the shared-prefix store's
        # one-donor-per-host fanout. None (the default) means "its own
        # host", which degrades rack-awareness to the original
        # broadcast-to-everyone behavior.
        self.host_group = host_group
        self.state = LIVE                       # guarded-by: _lock
        self.weight_version = 0                 # guarded-by: _lock
        self.max_consecutive_faults = max(1, int(max_consecutive_faults))
        self._consecutive_faults = 0            # guarded-by: _lock
        # engine rid -> FleetRequest, the router's outstanding-work signal
        self.inflight: Dict[int, FleetRequest] = {}  # guarded-by: _lock
        # prefix tokens (tuple) -> engine prefix_id; cleared on weight
        # install (engine.update_params drops old-policy prefix KV)
        self._prefixes: Dict[tuple, int] = {}   # guarded-by: _lock
        self._lock = threading.RLock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        if registry is None:
            from ..obs import get_registry
            registry = get_registry()
        self._state_gauge = registry.gauge(
            "senweaver_serve_replica_state",
            "Replica health (0=live, 1=draining, 2=dead).",
            labelnames=("replica",))
        self._inflight_gauge = registry.gauge(
            "senweaver_serve_replica_inflight",
            "Requests decoding on this replica.",
            labelnames=("replica",))
        self._version_gauge = registry.gauge(
            "senweaver_serve_weight_version",
            "Weight version this replica is serving.",
            labelnames=("replica",))
        self._faults_total = registry.counter(
            "senweaver_serve_replica_faults_total",
            "Faults recorded against fleet replicas.",
            labelnames=("replica", "reason"))
        self._decode_tokens_gauge = registry.gauge(
            "senweaver_serve_replica_decode_tokens",
            "Remaining decode tokens (max_new_tokens - emitted) across "
            "this replica's in-flight requests — the router's "
            "outstanding-work signal.",
            labelnames=("replica",))
        self._state_gauge.set(0, replica=replica_id)
        self._inflight_gauge.set(0, replica=replica_id)
        self._version_gauge.set(0, replica=replica_id)
        self._decode_tokens_gauge.set(0, replica=replica_id)

    # -- capacity / routing signals -----------------------------------------
    @property
    def capacity(self) -> int:
        return int(getattr(self.engine, "num_slots", 8))

    @property
    def outstanding(self) -> int:
        with self._lock:
            return len(self.inflight)

    @property
    def outstanding_decode_tokens(self) -> int:
        """Remaining decode work in TOKENS: Σ max(0, max_new_tokens −
        emitted) over in-flight requests. A replica holding two nearly-
        finished generations has less outstanding work than one holding
        a single fresh 512-token request — in-flight COUNT can't see
        that; this can (the router's primary load signal)."""
        with self._lock:
            return sum(max(0, r.max_new_tokens - r.emitted)
                       for r in self.inflight.values())

    def _update_decode_gauge(self) -> None:
        """Caller holds the lock."""
        tokens = sum(max(0, r.max_new_tokens - r.emitted)
                     for r in self.inflight.values())
        self._decode_tokens_gauge.set(tokens, replica=self.replica_id)
        # Same signal, pushed INTO the engine: the speculation depth
        # controller reads remaining decode work as fleet load (an
        # engine without speculation has no hook; skip silently).
        note = getattr(self.engine, "note_decode_load", None)
        if note is not None:
            note(tokens)

    @property
    def kv_pressure(self) -> float:
        """This replica's KV block-pool utilization (0..1; 0.0 for
        engines without a pool) — the fleet pump aggregates it into the
        senweaver_kv_pressure gauge admission/autoscale watermark on."""
        return float(getattr(self.engine, "kv_pressure", 0.0))

    @property
    def accepting(self) -> bool:
        """Routable: live with a free decode slot."""
        with self._lock:
            return self.state == LIVE and len(self.inflight) < self.capacity

    @property
    def host(self) -> str:
        """Host-group key for rack-aware fanout (falls back to the
        replica id — every unlabeled replica is its own host)."""
        return self.host_group if self.host_group is not None \
            else self.replica_id

    def holds_prefix(self, tokens: Tuple[int, ...]) -> bool:
        with self._lock:
            return tokens in self._prefixes

    def prefix_in_host_tier(self, tokens: Tuple[int, ...]) -> bool:
        """True when this replica holds the prefix but its KV currently
        lives in the engine's host-RAM tier (a donor export from here
        costs zero device traffic — prefix_store counts those backfills
        separately)."""
        with self._lock:
            pid = self._prefixes.get(tuple(tokens))
            if pid is None:
                return False
            probe = getattr(self.engine, "prefix_in_host_tier", None)
            return bool(probe(pid)) if probe is not None else False

    # -- shared prefix broadcast (serve/prefix_store.py) ---------------------
    def register_shared_prefix(self, tokens: List[int]):
        """Donor side of the fleet broadcast: prefill ``tokens`` locally
        (once, content-deduped by the engine) and export the one-slot KV
        buffer. Returns ``(tokens, kv, last_logits)``."""
        with self._lock:
            if self.state == DEAD:
                raise ReplicaDead(self.replica_id)
            key = tuple(tokens)
            prefix_id = self._prefixes.get(key)
            if prefix_id is None:
                prefix_id = self.engine.register_prefix(list(tokens))
                self._prefixes[key] = prefix_id
            return self.engine.export_prefix(prefix_id)

    def export_shared_prefix(self, tokens: List[int]):
        """Re-export an ALREADY-resident prefix (no prefill): the
        nearest-copy backfill path reads a same-host peer's KV instead
        of the store's original donor export. KeyError if this replica
        never installed the prefix."""
        with self._lock:
            if self.state == DEAD:
                raise ReplicaDead(self.replica_id)
            prefix_id = self._prefixes[tuple(tokens)]
            return self.engine.export_prefix(prefix_id)

    def install_shared_prefix(self, tokens: List[int], kv,
                              last_logits=None) -> int:
        """Receive side: adopt a peer's prefix KV without prefilling
        (``engine.import_prefix`` — device-to-device copy, validated,
        LRU-accounted). Raises ``PrefixImportError`` on layout mismatch;
        the store translates that into graceful degradation."""
        with self._lock:
            if self.state == DEAD:
                raise ReplicaDead(self.replica_id)
            prefix_id = self.engine.import_prefix(list(tokens), kv,
                                                  last_logits)
            self._prefixes[tuple(tokens)] = prefix_id
            return prefix_id

    # -- lifecycle -----------------------------------------------------------
    def drain(self) -> None:
        """Stop accepting new work; in-flight decodes run to completion
        (the first half of a rolling weight swap)."""
        with self._lock:
            if self.state == DEAD:
                raise ReplicaDead(self.replica_id)
            self.state = DRAINING
            self._state_gauge.set(_STATE_CODE[DRAINING],
                                  replica=self.replica_id)

    def resume(self) -> None:
        with self._lock:
            if self.state == DEAD:
                raise ReplicaDead(self.replica_id)
            self.state = LIVE
            self._state_gauge.set(_STATE_CODE[LIVE],
                                  replica=self.replica_id)

    def kill(self) -> List[FleetRequest]:
        """Mark DEAD and hand back the orphaned in-flight requests for
        the router to resubmit. Idempotent — a second kill returns []."""
        with self._lock:
            if self.state == DEAD:
                return []
            self.state = DEAD
            self._state_gauge.set(_STATE_CODE[DEAD],
                                  replica=self.replica_id)
            orphans = list(self.inflight.values())
            self.inflight.clear()
            self._inflight_gauge.set(0, replica=self.replica_id)
            self._decode_tokens_gauge.set(0, replica=self.replica_id)
            return orphans

    def record_fault(self, reason: str = REASON_ERROR) -> bool:
        """Count a fault; returns True when this one crossed
        ``max_consecutive_faults`` (the replica is NOT killed here — the
        fleet does that so it can collect the orphans in one place)."""
        with self._lock:
            self._faults_total.inc(replica=self.replica_id, reason=reason)
            self._consecutive_faults += 1
            return self._consecutive_faults >= self.max_consecutive_faults

    # -- serving -------------------------------------------------------------
    def submit(self, req: FleetRequest) -> int:
        """Dispatch one admitted request onto this replica's engine.
        Registers the request's prefix on demand (prefix-affinity means
        the router usually picked a replica that already holds it).
        Raises whatever the engine raises — the fleet translates that
        into a fault + retry."""
        with self._lock:
            if self.state != LIVE:
                raise ReplicaDead(
                    f"{self.replica_id} is {self.state}, not accepting")
            prefix_id = None
            if req.prefix_tokens:
                key = tuple(req.prefix_tokens)
                prefix_id = self._prefixes.get(key)
                if prefix_id is None:
                    prefix_id = self.engine.register_prefix(
                        list(req.prefix_tokens))
                    self._prefixes[key] = prefix_id
            kwargs = dict(max_new_tokens=req.max_new_tokens,
                          prefix_id=prefix_id, eos_id=req.eos_id,
                          hold_slot=req.hold_slot)
            if req.tenant_id is not None and self.has_adapter(
                    req.tenant_id):
                # Tenant with a published adapter: the engine binds its
                # current version at submit. An unpublished tenant
                # decodes base-only — graceful, not an error.
                kwargs["adapter_id"] = req.tenant_id
            if getattr(self.engine, "supports_idempotency", False):
                # Stable per (ticket, dispatch attempt): an in-call
                # retry after a lost response REPLAYS on the server
                # instead of double-executing; a fresh requeue attempt
                # gets a fresh key (a cached transient error must not
                # shadow a later genuine try).
                kwargs["idempotency_key"] = \
                    f"ticket-{req.ticket}-a{req.attempts}"
            t0 = time.perf_counter()
            rid = self.engine.submit(req.prompt, **kwargs)
            # Engine-side submit cost (for a remote replica: RPC +
            # remote prefill) — the timeline's dispatched milestone
            # carries it as an attribute.
            req.submit_ms = (time.perf_counter() - t0) * 1000.0
            self.inflight[rid] = req
            req.replica_id = self.replica_id
            req.engine_rid = rid
            req.version_at_dispatch = self.weight_version
            self._consecutive_faults = 0
            self._inflight_gauge.set(len(self.inflight),
                                     replica=self.replica_id)
            self._update_decode_gauge()
            return rid

    def submit_group(self, reqs: List[FleetRequest]) -> List[int]:
        """Dispatch one GRPO group onto this replica through the
        engine's shared-prefill path (``engine.submit_group``: one
        prefill, the followers fork the donor's KV spine — sharing is
        strictly replica-local). All members land atomically or the
        call raises and the fleet degrades to per-member dispatch.
        Members are tracked individually, so completion, migration,
        and fault handling stay per-leaf."""
        with self._lock:
            if self.state != LIVE:
                raise ReplicaDead(
                    f"{self.replica_id} is {self.state}, not accepting")
            lead = reqs[0]
            kwargs = dict(max_new_tokens=lead.max_new_tokens,
                          eos_id=lead.eos_id)
            if lead.tenant_id is not None and self.has_adapter(
                    lead.tenant_id):
                kwargs["adapter_id"] = lead.tenant_id
            t0 = time.perf_counter()
            rids = self.engine.submit_group(
                list(lead.prompt), len(reqs), **kwargs)
            ms = (time.perf_counter() - t0) * 1000.0
            for rid, req in zip(rids, reqs):
                req.submit_ms = ms
                self.inflight[rid] = req
                req.replica_id = self.replica_id
                req.engine_rid = rid
                req.version_at_dispatch = self.weight_version
            self._consecutive_faults = 0
            self._inflight_gauge.set(len(self.inflight),
                                     replica=self.replica_id)
            self._update_decode_gauge()
            return rids

    def adopt(self, rid: int, req: FleetRequest) -> None:
        """Track an engine rid submitted outside :meth:`submit` (turn
        continuations pin themselves to the held slot's replica and call
        the engine directly)."""
        with self._lock:
            self.inflight[rid] = req
            req.replica_id = self.replica_id
            req.engine_rid = rid
            req.version_at_dispatch = self.weight_version
            self._inflight_gauge.set(len(self.inflight),
                                     replica=self.replica_id)
            self._update_decode_gauge()

    def detach(self, rid: int) -> Optional[FleetRequest]:
        """Stop tracking an engine rid WITHOUT completing it — the
        migration-out half of a live handoff (the engine-side state is
        the coordinator's problem: checkpointed and, after the target
        acks, released). Returns the FleetRequest, or None when the rid
        isn't tracked here (already completed / already detached —
        detach is idempotent so rescue paths can call it blindly)."""
        with self._lock:
            req = self.inflight.pop(rid, None)
            if req is not None:
                self._inflight_gauge.set(len(self.inflight),
                                         replica=self.replica_id)
                self._update_decode_gauge()
            return req

    def step(self) -> Tuple[Dict[int, List[int]], List[FleetRequest]]:
        """One engine step. Returns (emitted {engine_rid: [tokens]},
        completed FleetRequests). Engine exceptions propagate — the
        fleet records the fault and decides whether this kills us."""
        with self._lock:
            if self.state == DEAD:
                return {}, []
            emitted = self.engine.step()
            self._consecutive_faults = 0
            for rid, toks in emitted.items():
                req = self.inflight.get(rid)
                if req is not None:
                    req.emitted += len(toks)
            done: List[FleetRequest] = []
            for rid in list(self.inflight):
                if self.engine.is_done(rid):
                    req = self.inflight.pop(rid)
                    # Capture the finish version here, while we still
                    # hold the lock that install_weights needs: once we
                    # return, in-flight may be zero and the publisher
                    # can swap weights before the fleet records the
                    # completion.
                    req.version_at_finish = self.weight_version
                    done.append(req)
            if done:
                self._inflight_gauge.set(len(self.inflight),
                                         replica=self.replica_id)
            self._update_decode_gauge()
            return emitted, done

    def has_work(self) -> bool:
        with self._lock:
            return self.state != DEAD and bool(
                getattr(self.engine, "has_work", False))

    # -- weights -------------------------------------------------------------
    def install_weights(self, params, version: int,
                        epoch: Optional[int] = None) -> None:
        """Swap in a published weight version. The publisher only calls
        this at zero in-flight (drain-first), which is the whole
        no-mixed-versions guarantee; asserting it here turns a publisher
        bug into a loud error instead of silent off-policy tokens.

        Version-aware engines (``RemoteEngineClient``) get the fencing
        token too, so the REMOTE host enforces its own (epoch, version)
        high-water mark — a stale writer that somehow reaches a replica
        directly is still rejected at the engine boundary."""
        with self._lock:
            if self.inflight:
                raise RuntimeError(
                    f"{self.replica_id}: install_weights with "
                    f"{len(self.inflight)} in flight — drain first")
            if getattr(self.engine, "supports_versioned_update", False):
                self.engine.update_params(params, version=int(version),
                                          epoch=epoch)
            else:
                self.engine.update_params(params)
            self.weight_version = int(version)
            self._prefixes.clear()      # engine dropped old-policy KV
            self._version_gauge.set(version, replica=self.replica_id)

    def mark_draft_stale(self) -> None:
        """Publish-begin hook: the fleet is rolling new policy weights,
        so this replica's speculation draft no longer matches the
        policy being installed — stamp it stale and reset the
        acceptance EMA immediately (engines without speculation have
        no hook; no-op)."""
        with self._lock:
            if self.state == DEAD:
                return
            note = getattr(self.engine, "spec_note_publish_begin", None)
            if note is not None:
                note()

    def install_draft_weights(self, params, version: int) -> bool:
        """Install republished DRAFT weights (the online distiller's
        output). Unlike :meth:`install_weights` this never waits for
        drain: draft weights cannot affect output correctness, only
        acceptance rate, so the swap is safe mid-decode. Returns False
        when the engine has no speculation hook."""
        with self._lock:
            if self.state == DEAD:
                raise ReplicaDead(self.replica_id)
            update = getattr(self.engine, "update_draft_params", None)
            if update is None:
                return False
            update(params, version=int(version))
            return True

    def install_adapter(self, tenant_id: str, lora, version: int) -> bool:
        """Install one tenant's published LoRA adapter into the
        engine's pool. Like :meth:`install_draft_weights` this never
        waits for drain: the engine binds adapter versions at submit
        time, so in-flight decodes (this tenant's included) are
        untouched and only the tenant's NEXT requests see the new
        version. Returns False when the engine has no adapter pool."""
        with self._lock:
            if self.state == DEAD:
                raise ReplicaDead(self.replica_id)
            publish = getattr(self.engine, "publish_adapter", None)
            if publish is None:
                return False
            try:
                publish(tenant_id, lora, version=int(version))
            except RuntimeError:
                return False    # engine without an adapter pool
            return True

    def has_adapter(self, tenant_id: Optional[str]) -> bool:
        """True when this replica's engine can decode under the
        tenant's adapter (a version is published to its pool)."""
        fn = getattr(self.engine, "has_adapter", None)
        return bool(fn(tenant_id)) if fn is not None else False

    def has_adapter_resident(self, tenant_id: Optional[str]) -> bool:
        """True when the tenant's CURRENT adapter version already
        occupies a device slot here — the router's warm-affinity
        signal (no upload on the next submit)."""
        if tenant_id is None:
            return False
        fn = getattr(self.engine, "adapter_resident", None)
        return bool(fn(tenant_id)) if fn is not None else False

    def stamp_version(self, version: int) -> None:
        """Record the fleet's current published version on a replica
        that just joined (no weight transfer — the caller constructed it
        with current params). The fleet must NOT write
        ``weight_version`` directly: that attribute is guarded by THIS
        object's lock, which the fleet's own lock doesn't cover."""
        with self._lock:
            self.weight_version = int(version)
            self._version_gauge.set(version, replica=self.replica_id)

    # -- stepper thread (threaded mode) --------------------------------------
    def start(self, on_step, *, idle_sleep_s: float = 0.001) -> None:
        """Drive ``step()`` in a daemon thread while there is work;
        ``on_step(replica, emitted, done)`` is the fleet's completion
        intake (called outside the replica lock)."""
        if self._thread is not None:
            return
        self._stop.clear()

        def run():
            while not self._stop.is_set():
                if self.state == DEAD:
                    return
                if not self.has_work():
                    time.sleep(idle_sleep_s)
                    continue
                try:
                    emitted, done = self.step()
                except Exception:
                    # The fleet's dispatcher notices via record_fault on
                    # its next touch; the stepper must not die silently
                    # holding requests.
                    self.record_fault()
                    time.sleep(idle_sleep_s)
                    continue
                if emitted or done:
                    on_step(self, emitted, done)

        self._thread = threading.Thread(
            target=run, name=f"serve-step-{self.replica_id}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def stats(self) -> Dict[str, object]:
        with self._lock:
            out = {"state": self.state,
                   "weight_version": self.weight_version,
                   "inflight": len(self.inflight),
                   "capacity": self.capacity}
        try:
            out["engine"] = self.engine.stats()
        except Exception as e:        # a dead engine still reports
            out["engine"] = {"error": str(e)}
        return out
