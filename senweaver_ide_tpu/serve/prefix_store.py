"""SharedPrefixStore — fleet prefix KV: prefill once, broadcast to all.

The fleet's dominant redundant work is N replicas each re-prefilling an
identical multi-hundred-token prefix (the optimized system prompt every
episode shares). Per-replica lazy registration made the FIRST dispatch
to each replica pay the full prefill; this store makes registration
**prefill-once / broadcast-to-all** — the RadixAttention / PagedAttention
economics (PAPERS.md) applied across engines instead of within one:

1. On the first dispatch of a fleet prefix, the chosen replica becomes
   the **donor**: it prefills the tokens (``engine.register_prefix``)
   and exports its one-slot KV buffer (``engine.export_prefix``).
2. The store installs that buffer into every other LIVE replica via
   ``engine.import_prefix`` — validated against the receiver's pool
   layout and accounted in its prefix LRU like a locally-prefilled
   entry. TTFT for prefix-bearing requests on those replicas drops from
   O(prefix FLOPs) to O(HBM bandwidth). Under the slot layout the
   install is a ``jax.device_put`` buffer copy; under the paged layout
   (EngineConfig.kv_layout="paged", the default) it is ONE scatter into
   freshly allocated pool blocks (``senweaver_kv_install_copies_total``)
   — and from then on every request naming the prefix GRAFTS those
   blocks into its own block table (a refcount bump,
   ``senweaver_kv_prefix_grafts_total``, zero KV bytes moved; divergent
   writes copy-on-write only the boundary block). Per-request prefix
   cost on a warm replica is therefore O(table ints), not O(prefix KV).
3. Replicas that join late, resurrect after death, or were DRAINING
   during the broadcast are **backfilled** on their next prefix-bearing
   dispatch (:meth:`ensure` runs in the dispatch path).

Invalidation follows the no-version-mixing rule: the store subscribes
to ``WeightPublisher.begin`` and drops every shared entry the moment a
publish starts — old-policy KV must never serve under new weights. A
stale fleet ``prefix_id`` then raises ``KeyError`` at submit, exactly
the single-engine contract auto_prefix clients already recover from.

Degradation: any export or install failure (chaos engine, layout
mismatch → :class:`~..rollout.engine.PrefixImportError`, OOM) marks the
entry failed and falls back to the pre-store behavior — each replica
lazily prefills on first use (``EngineReplica.submit``). The store can
make serving faster, never wedge it.

Rack-aware fanout: when replicas carry ``host_group`` labels the eager
broadcast installs into ONE replica per host group (the donor covers
its own); the rest of each host backfills lazily from the NEAREST
resident copy — a same-host peer re-exports its installed KV
(``EngineReplica.export_shared_prefix``,
``senweaver_serve_prefix_nearest_backfills_total``) so the donor
buffer crosses each rack boundary once instead of once per replica.
Unlabeled fleets default to one host per replica, which degrades both
paths to the original broadcast-to-all behavior exactly.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from .replica import LIVE, EngineReplica


@dataclasses.dataclass
class _SharedPrefix:
    """One fleet prefix and its broadcast state."""

    pid: int
    tokens: List[int]
    version: int                      # publisher version at registration
    donor_id: Optional[str] = None    # replica that paid the one prefill
    kv: Any = None                    # exported one-slot KVCache
    last_logits: Any = None           # donor's final-token logits (host)
    installed: Set[str] = dataclasses.field(default_factory=set)
    failed: bool = False              # degraded to per-replica lazy path


class SharedPrefixStore:
    """Fleet-level prefix registry + one-prefill broadcast protocol.

    Owns the pid namespace the :class:`ServingFleet` hands to clients.
    ``replicas`` is the fleet's LIVE list object (shared, not copied) so
    replicas added after construction participate automatically. All
    calls happen under the fleet's lock — no locking of its own."""

    def __init__(self, replicas: Sequence[EngineReplica], publisher, *,
                 registry=None, enabled: bool = True):
        self.replicas = replicas
        self.publisher = publisher
        self.enabled = bool(enabled)
        self._entries: Dict[int, _SharedPrefix] = {}
        # (tuple(tokens), version) -> pid: O(1) content dedup, replacing
        # the fleet's former O(pids) linear scan per register call.
        self._by_key: Dict[Tuple[tuple, int], int] = {}
        self._next_pid = 0
        publisher.subscribe_begin(self._on_publish)
        if registry is None:
            from ..obs import get_registry
            registry = get_registry()
        self._broadcasts_total = registry.counter(
            "senweaver_serve_prefix_broadcasts_total",
            "Shared-prefix KV buffers installed into non-donor replicas.")
        self._avoided_total = registry.counter(
            "senweaver_serve_prefix_prefills_avoided_total",
            "Prefix prefills avoided by serving an installed copy "
            "instead of recomputing.")
        self._failures_total = registry.counter(
            "senweaver_serve_prefix_broadcast_failures_total",
            "Shared-prefix exports/installs that failed (entry degrades "
            "to per-replica lazy prefill).")
        self._invalidations_total = registry.counter(
            "senweaver_serve_prefix_invalidations_total",
            "Shared prefixes dropped by a weight publish.")
        self._install_ms = registry.histogram(
            "senweaver_serve_prefix_install_ms",
            "Wall time of one shared-prefix install (device-to-device "
            "KV copy + validation).")
        self._shared_gauge = registry.gauge(
            "senweaver_serve_prefix_shared",
            "Shared prefixes currently registered in the store.")
        self._host_backfills_total = registry.counter(
            "senweaver_serve_prefix_host_backfills_total",
            "Donor exports served from the engine's host-RAM KV tier "
            "(the prefix had been swapped out — the broadcast cost "
            "zero donor device traffic and no re-prefill).")
        self._nearest_backfills_total = registry.counter(
            "senweaver_serve_prefix_nearest_backfills_total",
            "Late-replica prefix backfills served from a same-host "
            "resident copy (peer re-export) instead of the original "
            "donor buffer crossing the rack boundary again.")
        self._shared_gauge.set(0)

    # -- registry ------------------------------------------------------------
    def register(self, tokens: List[int]) -> int:
        """Fleet prefix id for ``tokens`` under the current weight
        version. Content-identical registrations dedup to one pid; the
        KV materializes lazily at first dispatch (donor prefill +
        broadcast)."""
        if not tokens:
            raise ValueError("empty prefix")
        key = (tuple(tokens), self.publisher.version)
        pid = self._by_key.get(key)
        if pid is not None:
            return pid
        pid = self._next_pid
        self._next_pid += 1
        self._entries[pid] = _SharedPrefix(
            pid=pid, tokens=list(tokens), version=self.publisher.version)
        self._by_key[key] = pid
        self._shared_gauge.set(len(self._entries))
        return pid

    def lookup(self, pid: int) -> Optional[_SharedPrefix]:
        """The entry behind ``pid`` — None when unknown or stale (its
        registration version predates the current weights)."""
        entry = self._entries.get(pid)
        if entry is None or entry.version != self.publisher.version:
            return None
        return entry

    def stats(self) -> Dict[str, Any]:
        out = {
            "shared_prefixes": len(self._entries),
            "prefixes_materialized": sum(
                e.kv is not None for e in self._entries.values()),
            "prefixes_failed": sum(
                e.failed for e in self._entries.values()),
        }
        # Graft-vs-copy economics across the fleet: paged replicas report
        # kv_prefix_grafts / kv_install_copies in engine stats — aggregate
        # them so one number answers "are imports actually zero-copy?".
        grafts = copies = 0
        paged_any = False
        for rep in self.replicas:
            st = getattr(rep, "engine", None)
            st = st.stats() if st is not None else {}
            if st.get("kv_paged"):
                paged_any = True
                grafts += st.get("kv_grafts", 0)
                copies += st.get("kv_install_copies", 0)
        if paged_any:
            out["kv_prefix_grafts"] = grafts
            out["kv_install_copies"] = copies
        return out

    # -- broadcast protocol --------------------------------------------------
    def ensure(self, replica: EngineReplica,
               tokens: List[int]) -> Optional[str]:
        """Dispatch-path hook: make ``replica`` warm for ``tokens``
        before the request lands on it. Never raises — every failure
        path degrades to the replica's own lazy prefill in
        ``EngineReplica.submit``. Returns how the replica got (or will
        get) warm — ``"donor"`` (paid the one prefill), ``"import"``
        (broadcast/backfill install), ``"warm"`` (already held it),
        ``"lazy"`` (degraded to the per-replica path) or None (not a
        fleet prefix) — the request timeline records it as the
        prefill-mode attribute."""
        if not self.enabled or not tokens:
            return None
        key = (tuple(tokens), self.publisher.version)
        pid = self._by_key.get(key)
        if pid is None:
            return None                  # not a fleet-registered prefix
        entry = self._entries[pid]
        if entry.failed:
            return "lazy"                # degraded: lazy per-replica
        if replica.holds_prefix(tuple(tokens)):
            entry.installed.add(replica.replica_id)
            return "warm"
        if entry.kv is None:
            self._donate(entry, replica)
            return ("donor" if entry.donor_id == replica.replica_id
                    else "lazy")
        # Late joiner / resurrected replica / was DRAINING during the
        # broadcast / non-seed member of a labeled host group: backfill
        # from the NEAREST resident copy — a same-host peer re-exports
        # its installed KV so the donor buffer doesn't cross the rack
        # boundary twice — falling back to the stored donor buffer.
        nearest = self._nearest_source(entry, replica)
        if nearest is not None:
            try:
                _, kv, last = nearest.export_shared_prefix(entry.tokens)
            except Exception:
                self._failures_total.inc()
            else:
                if self._install(entry, replica, kv=kv, last_logits=last):
                    self._nearest_backfills_total.inc()
                    return "import"
                if entry.failed:
                    return "lazy"
        return "import" if self._install(entry, replica) else "lazy"

    def _nearest_source(self, entry: _SharedPrefix,
                        replica: EngineReplica
                        ) -> Optional[EngineReplica]:
        """A LIVE same-host peer that already installed the entry (the
        cheapest backfill source). None when the replica's host has no
        resident copy — including every unlabeled fleet, where each
        replica is its own host."""
        for peer in self.replicas:
            if (peer.replica_id == replica.replica_id
                    or peer.state != LIVE
                    or peer.replica_id not in entry.installed):
                continue
            if peer.host == replica.host:
                return peer
        return None

    def _donate(self, entry: _SharedPrefix,
                replica: EngineReplica) -> None:
        """First dispatch: ``replica`` pays the ONE prefill, then its
        buffer broadcasts to every other live replica. A donor whose
        engine had already tiered the prefix to host RAM serves the
        export straight from its host buffers — counted separately,
        since the fleet then backfilled without any prefill OR device
        readback."""
        try:
            probe = getattr(replica, "prefix_in_host_tier", None)
            from_host = bool(probe(tuple(entry.tokens))) if probe else False
            tokens, kv, last = replica.register_shared_prefix(
                entry.tokens)
            if from_host:
                self._host_backfills_total.inc()
        except Exception:
            # Donor prefill failed (chaos / OOM): leave kv unset so the
            # next dispatch elects a new donor; repeated failure is the
            # replica fault path's problem, not the store's.
            self._failures_total.inc()
            return
        entry.donor_id = replica.replica_id
        entry.kv = kv
        entry.last_logits = last
        entry.installed.add(replica.replica_id)
        # Rack-aware fanout: ONE eager install per host group (the
        # donor already covers its own); the rest of each host
        # backfills from its seeded peer via the nearest-copy path in
        # :meth:`ensure`. Unlabeled replicas are each their own host,
        # so this is broadcast-to-all exactly as before.
        covered = {replica.host}
        for peer in self.replicas:
            if (peer.replica_id == replica.replica_id
                    or peer.state != LIVE
                    or peer.host in covered):
                continue
            if self._install(entry, peer):
                covered.add(peer.host)
            elif entry.failed:
                break

    def _install(self, entry: _SharedPrefix,
                 replica: EngineReplica, *, kv=None,
                 last_logits=None) -> bool:
        from ..rollout.engine import PrefixImportError
        t0 = time.perf_counter()
        if kv is None:
            kv, last_logits = entry.kv, entry.last_logits
        try:
            replica.install_shared_prefix(entry.tokens, kv, last_logits)
        except PrefixImportError:
            # Import refused: the buffer doesn't fit this pool's layout.
            # That's a fleet-config property, not a transient — it would
            # repeat on every replica, so degrade the whole entry to the
            # lazy per-replica path.
            self._failures_total.inc()
            entry.failed = True
            return False
        except Exception:
            # Replica-local blow-up (chaos / OOM): this replica serves
            # via its own lazy prefill; the entry keeps broadcasting to
            # the others.
            self._failures_total.inc()
            return False
        entry.installed.add(replica.replica_id)
        self._broadcasts_total.inc()
        self._avoided_total.inc()
        self._install_ms.observe((time.perf_counter() - t0) * 1000.0)
        return True

    def forget_replica(self, replica_id: str) -> None:
        """Drop ``replica_id`` from every entry's installed/donor
        bookkeeping — a replica id being resurrected under a FRESH
        engine holds none of the KV its predecessor did, so it must
        fall back into the lazy-backfill set (``ensure`` reinstalls on
        its next prefix-bearing dispatch). Retained donor buffers stay:
        they are host/device copies, valid independent of the donor."""
        for entry in self._entries.values():
            entry.installed.discard(replica_id)
            if entry.donor_id == replica_id:
                entry.donor_id = None

    # -- invalidation --------------------------------------------------------
    def _on_publish(self, version: int) -> None:
        """WeightPublisher.begin hook: every shared entry's KV belongs
        to the OLD policy — drop them all (no version mixing). Stale
        pids then fail :meth:`lookup` and submit raises KeyError."""
        dropped = len(self._entries)
        self._entries.clear()
        self._by_key.clear()
        if dropped:
            self._invalidations_total.inc(dropped)
        self._shared_gauge.set(0)
