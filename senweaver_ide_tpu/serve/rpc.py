"""Remote-replica RPC: typed fault taxonomy, wire codec, transports.

The instant a replica lives across a socket, every engine interaction
gains failure modes the in-process fleet treats as impossible. This
module names them as a TYPED taxonomy so callers can decide per class
instead of catching Exception:

====================  =========  =============================================
error                 retriable  meaning
====================  =========  =============================================
RpcTransportError     yes        the request never reached the server
                                 (refused / reset / DNS) — definitely not
                                 executed, retry freely
RpcTimeout            yes        no response within the deadline — the server
                                 MAY have executed it; only safe to retry
                                 because the server's idempotent request-id
                                 cache replays instead of re-executing
RpcServerError        yes        server answered 5xx before doing the work
RpcProtocolError      no         malformed frame — a bug, not weather
RpcApplicationError   no         the remote ENGINE raised (KeyError /
                                 ValueError / QueueFull…); re-raised locally
                                 as the original type so fleet semantics are
                                 transparent to distance
RpcCircuitOpen        no         the local circuit breaker is refusing calls
                                 to this peer (failing fast, not a wire error)
====================  =========  =============================================

Two transports speak the same ``call(method, params)`` surface:

- :class:`HttpTransport` — stdlib urllib POST of a JSON frame to
  ``{base_url}/rpc`` (the ``traces.http_trace_transport`` idiom; no SDK
  dependency). Arrays and pytrees cross the wire via :func:`encode` /
  :func:`decode` (JSON + tagged base64 ndarrays; pickle fallback for
  exotica — the fleet protocol is TRUSTED-PEER, same trust model as
  shipping raw weights).
- :class:`LoopbackTransport` — in-process delivery to an
  ``EngineRpcHandler``, consulting a
  :class:`~..resilience.chaos.NetworkFaultPlan` on every call. This is
  how ALL remote-fleet tests run hermetically on CPU: same taxonomy,
  same retry/idempotency paths, zero sockets, fake clocks.
"""

from __future__ import annotations

import base64
import json
import pickle
import threading
from typing import Any, Dict, Optional

RPC_PATH = "/rpc"


# -- fault taxonomy ----------------------------------------------------------
class RpcError(RuntimeError):
    """Base for every remote-call failure."""

    retriable = False


class RpcTransportError(RpcError):
    """Connection-level failure before the server saw the request."""

    retriable = True


class RpcTimeout(RpcError):
    """No response within the deadline (the server may have executed)."""

    retriable = True


class RpcServerError(RpcError):
    """Server-side 5xx before the call did its work."""

    retriable = True


class RpcProtocolError(RpcError):
    """Malformed request or response frame."""


class RpcCircuitOpen(RpcError):
    """Local circuit breaker is refusing calls to this peer."""


class RpcApplicationError(RpcError):
    """The remote engine raised; carries the original type name so the
    client re-raises it LOCALLY (KeyError stays KeyError across the
    wire — ``EnginePolicyClient`` recovery paths must not notice the
    network)."""

    def __init__(self, error_type: str, message: str):
        super().__init__(f"{error_type}: {message}")
        self.error_type = error_type
        self.message = message

    def raise_local(self):
        """Re-raise as the original exception type where that type is
        part of the engine contract; unknown types stay RpcApplication-
        Error (still typed, still not retried)."""
        builtin = {"KeyError": KeyError, "ValueError": ValueError,
                   "RuntimeError": RuntimeError, "TypeError": TypeError,
                   "IndexError": IndexError}.get(self.error_type)
        if builtin is not None:
            raise builtin(self.message) from self
        if self.error_type == "QueueFull":
            from ..rollout.engine import QueueFull
            raise QueueFull(self.message) from self
        if self.error_type == "PrefixImportError":
            from ..rollout.engine import PrefixImportError
            raise PrefixImportError(self.message) from self
        if self.error_type == "StalePublishError":
            from .weights import StalePublishError
            raise StalePublishError(self.message) from self
        if self.error_type == "LeaseLost":
            from ..resilience.lease import LeaseLost
            raise LeaseLost(self.message) from self
        if self.error_type == "LeaseUnavailable":
            from ..resilience.lease import LeaseUnavailable
            raise LeaseUnavailable(self.message) from self
        raise self


# -- wire codec --------------------------------------------------------------
def encode(obj: Any) -> Any:
    """JSON-able encoding of engine call payloads. Scalars/str/None pass
    through; containers recurse; arrays (numpy or jax) become tagged
    base64 buffers; namedtuples (KVCache) are rebuilt by import path;
    anything else rides a tagged pickle (trusted-peer protocol)."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, dict):
        if all(isinstance(k, str) for k in obj):
            return {"__d__": {k: encode(v) for k, v in obj.items()}}
        return _encode_pickle(obj)
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):
        cls = type(obj)
        return {"__nt__": f"{cls.__module__}:{cls.__qualname__}",
                "f": {name: encode(getattr(obj, name))
                      for name in obj._fields}}
    if isinstance(obj, list):
        return [encode(v) for v in obj]
    if isinstance(obj, tuple):
        return {"__t__": [encode(v) for v in obj]}
    if hasattr(obj, "dtype") and hasattr(obj, "shape"):
        import numpy as np
        arr = np.asarray(obj)
        return {"__nd__": {"dtype": str(arr.dtype),
                           "shape": list(arr.shape),
                           "data": base64.b64encode(
                               np.ascontiguousarray(arr).tobytes()
                           ).decode("ascii")}}
    return _encode_pickle(obj)


def _encode_pickle(obj: Any) -> Dict[str, str]:
    return {"__py__": base64.b64encode(
        pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    ).decode("ascii")}


def decode(obj: Any) -> Any:
    """Inverse of :func:`encode`. Arrays come back as numpy (jax ops and
    ``jax.device_put`` consume them directly)."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, list):
        return [decode(v) for v in obj]
    if isinstance(obj, dict):
        if "__d__" in obj:
            return {k: decode(v) for k, v in obj["__d__"].items()}
        if "__t__" in obj:
            return tuple(decode(v) for v in obj["__t__"])
        if "__nt__" in obj:
            import importlib
            mod_name, qualname = obj["__nt__"].split(":")
            cls = importlib.import_module(mod_name)
            for part in qualname.split("."):
                cls = getattr(cls, part)
            return cls(**{k: decode(v) for k, v in obj["f"].items()})
        if "__nd__" in obj:
            import numpy as np
            spec = obj["__nd__"]
            buf = base64.b64decode(spec["data"])
            return np.frombuffer(buf, dtype=np.dtype(spec["dtype"])
                                 ).reshape(spec["shape"]).copy()
        if "__py__" in obj:
            return pickle.loads(base64.b64decode(obj["__py__"]))
        raise RpcProtocolError(f"unknown frame tags: {sorted(obj)}")
    raise RpcProtocolError(f"unencodable frame element: {type(obj)!r}")


# -- transports --------------------------------------------------------------
def _inject_trace() -> Optional[Dict[str, Any]]:
    """The frame's ``trace`` field (W3C-traceparent style + clock
    anchors) for the ACTIVE span context — None when tracing is off or
    no span is open, so the common untraced path costs one branch and
    sends nothing. Never raises into a transport."""
    try:
        from ..obs.propagation import inject
        return inject()
    except Exception:
        return None


class HttpTransport:
    """urllib POST of one JSON frame per call to ``{base_url}/rpc``.

    Maps wire weather onto the taxonomy: connection errors →
    :class:`RpcTransportError`, deadline → :class:`RpcTimeout`, 5xx →
    :class:`RpcServerError` (with any ``Retry-After`` parsed onto
    ``.retry_after_s``), and an ``ok=false`` body →
    :class:`RpcApplicationError`. No retrying here — the client's
    RetryPolicy owns that.
    """

    def __init__(self, base_url: str, *, timeout_s: float = 5.0,
                 target: Optional[str] = None):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        self.target = target or self.base_url

    def call(self, method: str, params: Optional[Dict[str, Any]] = None,
             *, request_id: Optional[str] = None,
             timeout_s: Optional[float] = None) -> Any:
        import socket
        import urllib.error
        import urllib.request

        frame = {"method": method, "params": encode(params or {})}
        if request_id is not None:
            frame["request_id"] = request_id
        trace = _inject_trace()
        if trace is not None:
            frame["trace"] = trace
        body = json.dumps(frame).encode("utf-8")
        req = urllib.request.Request(
            self.base_url + RPC_PATH, data=body, method="POST",
            headers={"Content-Type": "application/json"})
        timeout = self.timeout_s if timeout_s is None else timeout_s
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                payload = json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            if e.code >= 500:
                err = RpcServerError(f"{method}: HTTP {e.code}")
                err.retry_after_s = _header_retry_after(e)
                raise err from e
            raise RpcProtocolError(f"{method}: HTTP {e.code}") from e
        except (socket.timeout, TimeoutError) as e:
            raise RpcTimeout(f"{method}: no response in {timeout}s") from e
        except urllib.error.URLError as e:
            if isinstance(getattr(e, "reason", None),
                          (socket.timeout, TimeoutError)):
                raise RpcTimeout(
                    f"{method}: no response in {timeout}s") from e
            raise RpcTransportError(f"{method}: {e.reason}") from e
        except OSError as e:
            raise RpcTransportError(f"{method}: {e}") from e
        except json.JSONDecodeError as e:
            raise RpcProtocolError(f"{method}: bad response body") from e
        return _unwrap(method, payload)


def _header_retry_after(e) -> Optional[float]:
    from ..resilience.retry import parse_retry_after
    headers = getattr(e, "headers", None)
    if headers is None:
        return None
    return parse_retry_after(headers.get("Retry-After"))


def _unwrap(method: str, payload: Any) -> Any:
    if not isinstance(payload, dict) or "ok" not in payload:
        raise RpcProtocolError(f"{method}: malformed response frame")
    if payload["ok"]:
        return decode(payload.get("result"))
    raise RpcApplicationError(payload.get("error_type", "RuntimeError"),
                              payload.get("message", ""))


class LoopbackTransport:
    """In-process transport: the hermetic twin of :class:`HttpTransport`.

    Delivers calls straight into a handler's ``handle()`` (values pass
    by reference — no serialization cost — unless ``wire_codec=True``,
    which round-trips every frame through encode/decode to exercise the
    codec without sockets). A :class:`NetworkFaultPlan` injects the full
    weather taxonomy deterministically; ``clock`` only matters for
    bookkeeping, so chaos tests run on fake clocks with zero sleeps.

    Fault semantics (see ``NetworkFault``): ``drop``/``http_500``/
    ``partition`` fail BEFORE the handler runs; ``drop_response`` runs
    the handler then loses the answer (RpcTimeout — the retry must hit
    the server's idempotency cache, not a second execution); ``delay``
    executes and then times out only when ``delay_s`` >= the call's
    timeout, otherwise it just records latency.
    """

    def __init__(self, handler, *, target: str = "loopback",
                 fault_plan=None, timeout_s: float = 5.0,
                 wire_codec: bool = False):
        self.handler = handler
        self.target = target
        self.fault_plan = fault_plan
        self.timeout_s = timeout_s
        self.wire_codec = wire_codec
        self.calls = 0                      # guarded-by: _lock
        self.simulated_latency_s = 0.0      # guarded-by: _lock
        self._lock = threading.Lock()

    def call(self, method: str, params: Optional[Dict[str, Any]] = None,
             *, request_id: Optional[str] = None,
             timeout_s: Optional[float] = None) -> Any:
        with self._lock:
            self.calls += 1
        timeout = self.timeout_s if timeout_s is None else timeout_s
        fault = (self.fault_plan.take(self.target, method)
                 if self.fault_plan is not None else None)
        if fault is not None:
            if fault.kind == "partition":
                raise RpcTransportError(
                    f"{method}: {self.target} partitioned")
            if fault.kind == "drop":
                raise RpcTransportError(
                    f"{method}: connection reset by chaos")
            if fault.kind == "http_500":
                raise RpcServerError(f"{method}: injected HTTP 500")
        trace = _inject_trace()
        try:
            if trace is not None:
                result = self.handler.handle(method, dict(params or {}),
                                             request_id=request_id,
                                             trace=trace)
            else:
                result = self.handler.handle(method, dict(params or {}),
                                             request_id=request_id)
        except RpcError:
            raise
        except Exception as e:     # handler bug = server crash mid-call
            raise RpcServerError(f"{method}: server crashed: {e}") from e
        if fault is not None:
            if fault.kind == "drop_response":
                raise RpcTimeout(
                    f"{method}: executed but response lost")
            if fault.kind == "delay":
                with self._lock:
                    self.simulated_latency_s += fault.delay_s
                if fault.delay_s >= timeout:
                    raise RpcTimeout(
                        f"{method}: response after {fault.delay_s}s "
                        f"> timeout {timeout}s")
        if self.wire_codec:
            result = _unwrap(method, json.loads(json.dumps(
                {"ok": True, "result": encode(result)})))
        return result
