"""RemoteReplica — a fleet member that lives across a transport.

Two layers, both deliberately thin:

- :class:`RemoteEngineClient` is an ENGINE-SHAPED proxy: it exposes the
  exact single-engine surface (``submit / step / is_done / result /
  result_logps / release_slot / register_prefix / export_prefix /
  import_prefix / update_params / stats / has_work / num_slots /
  context_bound``) over an rpc transport, adding the robustness the
  wire demands — per-call retry under a shared
  :class:`~..resilience.retry.RetryPolicy`, idempotent request ids on
  every mutating call (a retried dispatch replays on the server instead
  of double-executing), and a per-peer
  :class:`~..resilience.retry.CircuitBreaker` so a dead host fails fast
  instead of burning a timeout per touch. Remote APPLICATION errors
  (KeyError / ValueError / QueueFull…) re-raise locally as the original
  types — fleet semantics are transparent to distance.

- :class:`RemoteReplica` is ``EngineReplica`` with that client as its
  engine — the health state machine, in-flight map, and stepper thread
  are REUSED VERBATIM, which is the point: Router / WeightPublisher /
  ServingFleet cannot tell a remote replica from a local one. What it
  adds is what only the network needs: breaker-gated ``accepting`` and
  **hedged health probes** (:meth:`RemoteReplica.probe`) that
  distinguish a SLOW peer (first probe times out, hedge answers — back
  off, don't kill) from a DEAD one (nothing answers — feed the existing
  LIVE→DEAD fault escalation).

When an RpcError survives the client's whole retry budget it propagates
to the fleet exactly like a local engine exception, landing in the same
fault/requeue/shed triage — the failure PATHS are shared; only the
failure SOURCES are new.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Dict, List, Optional

from ..resilience.retry import CircuitBreaker, RetryBudget, RetryPolicy
from .replica import EngineReplica
from .rpc import (RpcApplicationError, RpcCircuitOpen, RpcError,
                  RpcTimeout)

_client_counter = itertools.count()


class RemoteEngineClient:
    """Engine-shaped rpc proxy with retries, idempotency, breaker."""

    # EngineReplica.submit checks this before passing idempotency_key.
    supports_idempotency = True
    # EngineReplica.install_weights checks this before passing the
    # (epoch, version) fencing token — the remote handler keeps its own
    # high-water mark and rejects stale writers at the host boundary.
    supports_versioned_update = True

    def __init__(self, transport, *, name: Optional[str] = None,
                 policy: RetryPolicy = RetryPolicy(max_retries=2,
                                                   base_delay_s=0.05,
                                                   max_delay_s=1.0),
                 breaker: Optional[CircuitBreaker] = None,
                 clock=time.monotonic, sleep=None, rng=None,
                 registry=None):
        self.transport = transport
        self.name = name or getattr(transport, "target",
                                    f"remote-{next(_client_counter)}")
        self.policy = policy
        self.breaker = breaker
        self.clock = clock
        self.sleep = sleep or time.sleep
        self._rng = rng
        self._seq = itertools.count()
        self._opens_seen = 0
        self._meta: Optional[Dict[str, Any]] = None     # guarded-by: _lock
        self._lock = threading.Lock()
        if registry is None:
            from ..obs import get_registry
            registry = get_registry()
        self._rpcs_total = registry.counter(
            "senweaver_serve_remote_rpcs_total",
            "Remote engine RPCs attempted (per attempt, not per call).",
            labelnames=("replica", "method"))
        self._retries_total = registry.counter(
            "senweaver_serve_remote_rpc_retries_total",
            "Remote engine RPC retries (transient error, budget left).",
            labelnames=("replica",))
        self._errors_total = registry.counter(
            "senweaver_serve_remote_rpc_errors_total",
            "Remote engine RPCs that exhausted their retry budget.",
            labelnames=("replica", "kind"))
        self._breaker_gauge = registry.gauge(
            "senweaver_serve_remote_breaker_state",
            "Circuit breaker state per remote replica "
            "(0=closed, 1=half-open, 2=open).",
            labelnames=("replica",))
        self._breaker_opens_total = registry.counter(
            "senweaver_serve_remote_breaker_opens_total",
            "Circuit breaker open transitions per remote replica.",
            labelnames=("replica",))
        self._breaker_gauge.set(0, replica=self.name)

    # -- call machinery ------------------------------------------------------
    def _request_id(self) -> str:
        return f"{self.name}:{next(self._seq)}"

    def _sync_breaker_gauge(self) -> None:
        if self.breaker is None:
            return
        self._breaker_gauge.set(self.breaker.state_code,
                                replica=self.name)
        opens = self.breaker.opens_total
        while self._opens_seen < opens:
            self._opens_seen += 1
            self._breaker_opens_total.inc(replica=self.name)

    def _call(self, method: str,
              params: Optional[Dict[str, Any]] = None, *,
              idempotency_key: Optional[str] = None,
              timeout_s: Optional[float] = None) -> Any:
        """One logical call = up to 1 + max_retries attempts. Mutating
        methods always carry a request id so a retry after a lost
        response REPLAYS server-side instead of re-executing."""
        now = self.clock()
        if self.breaker is not None and not self.breaker.allow(now):
            self._sync_breaker_gauge()
            raise RpcCircuitOpen(
                f"{self.name}: circuit open, refusing {method}")
        request_id = idempotency_key or self._request_id()
        budget = RetryBudget(self.policy, now=now, rng=self._rng)
        from ..obs import get_tracer
        tracer = get_tracer()
        attempt = 0
        while True:
            self._rpcs_total.inc(replica=self.name, method=method)
            try:
                # One client span per ATTEMPT (retries are annotated,
                # not hidden); the transport injects this span's context
                # into the frame, so the server span stitches under it.
                with tracer.span(f"rpc.client.{method}",
                                 replica=self.name, method=method,
                                 request_id=request_id,
                                 attempt=attempt) as sp:
                    if sp is not None and attempt > 0:
                        sp.set_attr("retry", True)
                    result = self.transport.call(
                        method, params, request_id=request_id,
                        timeout_s=timeout_s)
            except RpcApplicationError as e:
                # The SERVER answered — the peer is healthy; only the
                # request is bad. Never retried, never a breaker strike.
                if self.breaker is not None:
                    self.breaker.record_success(self.clock())
                    self._sync_breaker_gauge()
                e.raise_local()
            except RpcError as e:
                if self.breaker is not None:
                    self.breaker.record_failure(self.clock())
                    self._sync_breaker_gauge()
                if not e.retriable:
                    self._errors_total.inc(replica=self.name,
                                           kind=type(e).__name__)
                    raise
                delay = budget.next_delay(
                    now=self.clock(),
                    retry_after_s=getattr(e, "retry_after_s", None))
                if delay is None:
                    self._errors_total.inc(replica=self.name,
                                           kind=type(e).__name__)
                    raise
                self._retries_total.inc(replica=self.name)
                attempt += 1
                if delay > 0:
                    self.sleep(delay)
                continue
            if self.breaker is not None:
                self.breaker.record_success(self.clock())
                self._sync_breaker_gauge()
            return result

    # -- engine surface ------------------------------------------------------
    def submit(self, prompt: List[int], *, max_new_tokens: int = 128,
               prefix_id: Optional[int] = None,
               eos_id: Optional[int] = None, hold_slot: bool = False,
               continue_from: Optional[int] = None,
               idempotency_key: Optional[str] = None) -> int:
        return int(self._call("submit", {
            "prompt": list(prompt), "max_new_tokens": max_new_tokens,
            "prefix_id": prefix_id, "eos_id": eos_id,
            "hold_slot": hold_slot, "continue_from": continue_from},
            idempotency_key=idempotency_key))

    def step(self) -> Dict[int, List[int]]:
        emitted = self._call("step")
        return {int(rid): list(toks) for rid, toks in emitted.items()}

    def is_done(self, rid: int) -> bool:
        return bool(self._call("is_done", {"rid": rid}))

    def result(self, rid: int) -> List[int]:
        return list(self._call("result", {"rid": rid}))

    def result_logps(self, rid: int) -> List[float]:
        return list(self._call("result_logps", {"rid": rid}))

    def release_slot(self, rid: int) -> None:
        self._call("release_slot", {"rid": rid})

    def register_prefix(self, tokens: List[int]) -> int:
        return int(self._call("register_prefix",
                              {"tokens": list(tokens)}))

    def export_prefix(self, prefix_id: int):
        return self._call("export_prefix", {"prefix_id": prefix_id})

    def import_prefix(self, tokens: List[int], kv,
                      last_logits=None) -> int:
        return int(self._call("import_prefix", {
            "tokens": list(tokens), "kv": kv,
            "last_logits": last_logits}))

    def release_prefix(self, prefix_id: int) -> None:
        self._call("release_prefix", {"prefix_id": prefix_id})

    def update_params(self, params, *, version: Optional[int] = None,
                      epoch: Optional[int] = None) -> None:
        if epoch is not None and version is None:
            # The host-side high-water mark is (epoch, version); an
            # epoch alone cannot be fenced and silently dropping it
            # would hand a caller unfenced writes it thinks are fenced.
            raise ValueError(
                "update_params: epoch requires version — the remote "
                "fencing mark is (epoch, version)")
        call_params: Dict[str, Any] = {"params": params}
        if version is not None:
            call_params["version"] = int(version)
            call_params["epoch"] = 0 if epoch is None else int(epoch)
        self._call("update_params", call_params)

    # -- live migration (serve/scheduler.py) ---------------------------------
    def checkpoint_request(self, rid: int, *, pause: bool = True):
        """Snapshot an in-flight decode on the remote host; returns the
        decoded :class:`~..rollout.migration.DecodeCheckpoint`. The
        snapshot also FREEZES the row (pause=True), so a lost-response
        retry replays the cached checkpoint rather than cutting a
        second, later one."""
        from ..rollout.migration import DecodeCheckpoint
        wire = self._call("checkpoint_request",
                          {"rid": int(rid), "pause": bool(pause)})
        return DecodeCheckpoint.from_wire(wire)

    def restore_checkpoint(self, ckpt, *,
                           idempotency_key: Optional[str] = None) -> int:
        """Install a checkpoint on the remote host; returns the new
        engine rid. The coordinator passes a stable idempotency key so
        the install is at-least-once on the wire but exactly-once on
        the engine (the server's idempotency cache replays the first
        rid instead of double-installing)."""
        if hasattr(ckpt, "to_wire"):
            ckpt = ckpt.to_wire()
        return int(self._call("restore_checkpoint", {"ckpt": ckpt},
                              idempotency_key=idempotency_key))

    def resume_request(self, rid: int) -> None:
        self._call("resume_request", {"rid": int(rid)})

    def release_request(self, rid: int) -> bool:
        return bool(self._call("release_request", {"rid": int(rid)}))

    def stats(self) -> Dict[str, Any]:
        return dict(self._call("stats"))

    def health(self, *, timeout_s: Optional[float] = None,
               hedged: bool = False) -> Dict[str, Any]:
        """One UNRETRIED health probe (the prober owns hedging — a probe
        that internally retried could not distinguish slow from dead)."""
        now = self.clock()
        if (not hedged and self.breaker is not None
                and not self.breaker.allow(now)):
            raise RpcCircuitOpen(f"{self.name}: circuit open")
        from ..obs import get_tracer
        try:
            # Probes skip _call, so they get their client span here —
            # otherwise the server-side health span has no parent and
            # shows up as an orphan root in stitched traces.
            with get_tracer().span("rpc.client.health",
                                   replica=self.name, method="health",
                                   hedged=hedged):
                out = self.transport.call("health", request_id=None,
                                          timeout_s=timeout_s)
        except RpcError:
            if self.breaker is not None:
                self.breaker.record_failure(self.clock())
                self._sync_breaker_gauge()
            raise
        if self.breaker is not None:
            self.breaker.record_success(self.clock())
            self._sync_breaker_gauge()
        return out

    @property
    def has_work(self) -> bool:
        """Polled every pump; a dead peer must answer False fast (via
        the open breaker), never raise out of a property."""
        try:
            return bool(self.health().get("has_work", False))
        except (RpcError, KeyError, ValueError, TypeError):
            return False

    def _meta_cached(self) -> Dict[str, Any]:
        with self._lock:
            if self._meta is not None:
                return self._meta
        try:
            meta = self._call("meta")
        except RpcError:
            # Conservative fallbacks (EngineReplica's own defaults);
            # NOT cached — the next touch retries the real values.
            return {"num_slots": 8, "context_bound": 1 << 30}
        with self._lock:
            self._meta = meta
        return meta

    @property
    def num_slots(self) -> int:
        return int(self._meta_cached()["num_slots"])

    @property
    def context_bound(self) -> int:
        return int(self._meta_cached()["context_bound"])


PROBE_OK = "ok"
PROBE_SLOW = "slow"
PROBE_DEAD = "dead"


class RemoteReplica(EngineReplica):
    """EngineReplica over a transport: same health machine, same fleet
    surface, plus breaker-gated accepting and hedged probing."""

    def __init__(self, replica_id: str, transport, *,
                 max_consecutive_faults: int = 3,
                 registry=None,
                 policy: Optional[RetryPolicy] = None,
                 breaker_failure_threshold: int = 5,
                 breaker_reset_timeout_s: float = 5.0,
                 probe_timeout_s: float = 0.5,
                 probe_hedges: int = 1,
                 clock=time.monotonic, sleep=None, rng=None):
        if registry is None:
            from ..obs import get_registry
            registry = get_registry()
        breaker = CircuitBreaker(
            failure_threshold=breaker_failure_threshold,
            reset_timeout_s=breaker_reset_timeout_s)
        client = RemoteEngineClient(
            transport, name=replica_id,
            policy=policy or RetryPolicy(max_retries=2,
                                         base_delay_s=0.05,
                                         max_delay_s=1.0),
            breaker=breaker, clock=clock, sleep=sleep, rng=rng,
            registry=registry)
        super().__init__(replica_id, client,
                         max_consecutive_faults=max_consecutive_faults,
                         registry=registry)
        self.client = client
        self.breaker = breaker
        self.clock = clock
        self.probe_timeout_s = probe_timeout_s
        self.probe_hedges = max(0, int(probe_hedges))
        self._probe_total = registry.counter(
            "senweaver_serve_remote_probes_total",
            "Hedged health probes by outcome (ok / slow / dead).",
            labelnames=("replica", "result"))

    @property
    def accepting(self) -> bool:
        """Routable = the EngineReplica contract AND a breaker willing
        to carry the dispatch — routing at a host the breaker already
        condemned just converts admitted requests into retries."""
        if not self.breaker.would_allow(self.clock()):
            return False
        return super().accepting

    def probe(self, now: Optional[float] = None) -> str:
        """Hedged health probe: PROBE_OK (first attempt answered),
        PROBE_SLOW (an attempt timed out but a hedge answered — latency,
        not death; do NOT kill), PROBE_DEAD (every attempt failed —
        feeds the fleet's fault escalation). Each attempt is a single
        un-retried rpc on a short timeout."""
        saw_timeout = False
        for attempt in range(1 + self.probe_hedges):
            try:
                self.client.health(timeout_s=self.probe_timeout_s,
                                   hedged=attempt > 0)
            except RpcTimeout:
                saw_timeout = True
                continue
            except RpcError:
                continue
            result = PROBE_SLOW if attempt > 0 else PROBE_OK
            self._probe_total.inc(replica=self.replica_id, result=result)
            return result
        # All attempts failed. A pure-timeout pattern still reads dead —
        # the distinguishing signal is "a hedge eventually answered",
        # not the error class.
        del saw_timeout
        self._probe_total.inc(replica=self.replica_id, result=PROBE_DEAD)
        return PROBE_DEAD
