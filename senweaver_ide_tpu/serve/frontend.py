"""ServingFleet — N rollout engines behind one submit/result/stream API.

The facade composes the other serve/ pieces into a drop-in SUPERSET of
the single-engine surface (``submit / step / is_done / result /
result_logps / register_prefix / release_slot / update_params / stats /
context_bound``), which is exactly what ``EnginePolicyClient`` and
``OnlineImprovementLoop`` program against — point them at a fleet and
they scale from one engine to N without code changes. On top of that it
adds what only a fleet can have: priority classes and deadlines at
submit, typed :class:`Rejected` outcomes, replica failover, and rolling
weight publication.

Request lifecycle::

    submit() ── admission (bound/rate/deadline) ──┐
        │                                         ├─ Rejected (typed)
        ▼                                         │
    class queue ── pump(): router.pick ───────────┘
        │              │
        │              ▼
        │         replica.submit → decode steps → Completed
        │              │ (replica dies)
        └──── requeue with backoff (resilience shape) ── retries spent ──▶
                                                          Rejected

Drive it either way:

- **manually**: ``step()`` (one pump: publish-roll advance, deadline
  sweep, dispatch, one decode step per replica) — deterministic, what
  the tests and single-threaded callers use; ``run()`` pumps until idle.
- **threaded**: ``start()`` gives every replica its stepper thread and
  the fleet a dispatcher thread — N engines decode concurrently.

Time is an injectable ``clock`` (monotonic seconds) so admission and
retry backoff run on a fake clock in tests.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Sequence, Union

from ..obs.slo import SLOConfig, SLOTracker
from ..obs.timeline import TimelineRecorder
from ..resilience.faults import REASON_ERROR, REASON_TIMEOUT
from .admission import (AdmissionConfig, AdmissionQueue, FleetRequest,
                        REJECT_NO_REPLICAS, REJECT_REPLICA_FAILURE,
                        Rejected, RequestRejected, TRAIN_ROLLOUT)
from .prefix_store import SharedPrefixStore
from .replica import DEAD, EngineReplica, ReplicaDead
from .router import Router
from .rpc import RpcError
from .weights import WeightPublisher


@dataclasses.dataclass(frozen=True)
class Completed:
    """Terminal success outcome for one fleet request."""

    ticket: int
    priority: str
    tokens: List[int]
    logps: List[float]
    replica_id: str
    weight_version: int             # replica version when dispatched
    weight_version_at_finish: int   # and when it finished (must match —
                                    # the no-mixed-versions invariant)
    attempts: int
    ttft_ms: Optional[float]
    e2e_ms: float


class ServingFleet:
    """N EngineReplicas + admission + router + publisher, one facade."""

    def __init__(self, engines: Sequence[Any], *,
                 admission: AdmissionConfig = AdmissionConfig(),
                 clock=time.monotonic,
                 registry=None,
                 max_retries: int = 2,
                 retry_base_delay_s: float = 0.05,
                 retry_max_delay_s: float = 2.0,
                 max_consecutive_faults: int = 3,
                 metrics_service=None,
                 shared_prefix_broadcast: bool = True,
                 probe_interval_s: float = 1.0,
                 host_groups: Optional[Sequence[Optional[str]]] = None,
                 slo: Optional[SLOConfig] = None,
                 peer_id: Optional[str] = None):
        if not engines:
            raise ValueError("a fleet needs at least one engine")
        if host_groups is not None and len(host_groups) != len(engines):
            raise ValueError(
                f"host_groups has {len(host_groups)} entries for "
                f"{len(engines)} engines")
        if registry is None:
            from ..obs import get_registry
            registry = get_registry()
        self.registry = registry
        self.clock = clock
        self.metrics_service = metrics_service
        # host_groups labels engines by rack/host for the prefix
        # store's one-donor-per-host fanout; None entries (and wrapped
        # EngineReplica instances, which carry their own label) keep
        # the every-replica-its-own-host default.
        self.replicas: List[EngineReplica] = [
            e if isinstance(e, EngineReplica) else EngineReplica(
                f"replica-{i}", e,
                max_consecutive_faults=max_consecutive_faults,
                host_group=(host_groups[i] if host_groups else None),
                registry=registry)
            for i, e in enumerate(engines)]
        self.admission = AdmissionQueue(admission, registry=registry,
                                        now=clock())
        self.router = Router(self.replicas, max_retries=max_retries,
                             retry_base_delay_s=retry_base_delay_s,
                             retry_max_delay_s=retry_max_delay_s,
                             registry=registry)
        self.publisher = WeightPublisher(self.replicas, registry=registry)
        # Fleet prefix ids + the one-prefill broadcast protocol. The
        # store sees ``self.replicas`` by reference, so add_replica'd
        # members participate; a publisher begin() invalidates every
        # shared entry (stale pids raise KeyError at submit, mirroring
        # engine semantics so auto_prefix clients re-register).
        self.prefix_store = SharedPrefixStore(
            self.replicas, self.publisher, registry=registry,
            enabled=shared_prefix_broadcast)
        self._lock = threading.RLock()
        self._next_ticket = 0                   # guarded-by: _lock
        self._requests: Dict[int, FleetRequest] = {}    # guarded-by: _lock
        self._outcomes: Dict[int, Union[Completed, Rejected]] = {}  # guarded-by: _lock
        self._dispatcher: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._requests_total = registry.counter(
            "senweaver_serve_requests_total",
            "Requests submitted to the fleet.",
            labelnames=("priority",))
        self._completed_total = registry.counter(
            "senweaver_serve_completed_total",
            "Requests completed by the fleet.",
            labelnames=("priority",))
        self._shed_total = registry.counter(
            "senweaver_serve_shed_total",
            "Requests shed by admission control (typed Rejected).",
            labelnames=("priority", "reason"))
        self._ttft_ms = registry.histogram(
            "senweaver_serve_ttft_ms",
            "Submit-to-first-token latency (ms).",
            labelnames=("priority",))
        self._e2e_ms = registry.histogram(
            "senweaver_serve_e2e_ms",
            "Submit-to-completion latency (ms).",
            labelnames=("priority",))
        self._replicas_live = registry.gauge(
            "senweaver_serve_replicas_live",
            "Replicas not DEAD.")
        self._replicas_live.set(len(self.replicas))
        # Fleet KV pool pressure: min over placeable (accepting)
        # replicas — if ANY replica still has block headroom the fleet
        # can route there, so that is the honest backpressure signal.
        # Admission watermarks and the autoscaler both read this.
        self._kv_pressure_gauge = registry.gauge(
            "senweaver_kv_pressure",
            "Fleet KV pool pressure (0..1): the least-pressured "
            "placeable replica's block-pool utilization.")
        self._kv_pressure_gauge.set(0.0)
        self._group_submits = registry.counter(
            "senweaver_serve_group_submits_total",
            "GRPO groups dispatched through the replica-local "
            "shared-prefill path (one prefill, KV forked on-replica).")
        self._group_degrades = registry.counter(
            "senweaver_serve_group_degrades_total",
            "GRPO groups that fell back to independent per-member "
            "submits (no live replica with a group-capable engine).")
        self._continuation_replays = registry.counter(
            "senweaver_serve_continuation_replays_total",
            "Held-slot turn continuations replayed on a survivor after "
            "their replica died (full re-prefill of the transcript "
            "instead of the ValueError fallback).")
        # Hedged health probing of replicas that support it (remote
        # ones); local replicas have no probe() and are skipped.
        self.probe_interval_s = float(probe_interval_s)
        self._last_probe_at: Optional[float] = None  # guarded-by: _lock
        # Optional admission-driven autoscaler (attach_autoscaler);
        # evaluated once per pump, inside the fleet lock.
        self.autoscaler = None                       # guarded-by: _lock
        # Optional live-migration plane (attach_migration): a
        # MigrationCoordinator pumped right after the autoscaler, so
        # pressure offers and eager-publish relief act on this pump's
        # signals.
        self.migrator = None                         # guarded-by: _lock
        # Request-level SLO layer: milestone timelines feeding the
        # per-priority seconds histograms, violation counters, and the
        # K-worst exemplar ring (always on — dict writes per request).
        # peer_id names THIS process in the federated fleet — stamped
        # into timelines/exemplars so incident stitching can attribute
        # them, and used as the scrape identity when federated.
        self.peer_id = peer_id
        self.slo = SLOTracker(slo, registry=registry, peer_id=peer_id)
        self.timelines = TimelineRecorder(clock=clock, slo=self.slo,
                                          registry=registry,
                                          peer_id=peer_id)
        # Optional fleet observability plane (attach_federation):
        # a MetricsFederator polled once per pump + an AlertManager
        # evaluated right after, so federated rollups are fresh for
        # both the alert rules and the autoscaler.
        self.federation = None                       # guarded-by: _lock
        self.alerts = None                           # guarded-by: _lock
        # Open publish-pause window (begin seen, roll not converged) —
        # closed windows are pushed onto the timeline recorder so a
        # finished request knows how much of its e2e was publish pause.
        self._publish_started_at: Optional[float] = None  # guarded-by: _lock
        # Exact window edges: the publisher fires these on the very
        # begin/land transitions (the pump's polling calls below are a
        # no-op backstop once these have run).
        self.publisher.subscribe_begin(
            lambda _v: self._track_publish_window(self.clock()))
        self.publisher.subscribe_end(
            lambda _v: self._track_publish_window(self.clock()))

    # -- single-engine API superset ------------------------------------------
    @property
    def context_bound(self) -> int:
        """Longest servable context — the most conservative replica's
        bound (a request must be servable wherever routing lands it)."""
        return min(int(getattr(r.engine, "context_bound", 1 << 30))
                   for r in self.replicas)

    @property
    def num_slots(self) -> int:
        return sum(r.capacity for r in self.replicas)

    def submit(self, prompt: List[int], *, max_new_tokens: int = 128,
               priority: str = TRAIN_ROLLOUT,
               deadline_s: Optional[float] = None,
               prefix_id: Optional[int] = None,
               eos_id: Optional[int] = None,
               hold_slot: bool = False,
               continue_from: Optional[int] = None,
               tenant_id: Optional[str] = None) -> int:
        """Admit a generation request; returns a fleet ticket.

        Sheds (queue full / rate limit) are NOT exceptions: the ticket's
        outcome is a typed :class:`Rejected` and ``is_done`` is
        immediately True — the caller always gets an answer. KeyError
        (stale ``prefix_id`` after a weight publish) and ValueError (bad
        continuation) match engine semantics so ``EnginePolicyClient``'s
        recovery paths work unchanged."""
        with self._lock:
            now = self.clock()
            ticket = self._next_ticket
            self._next_ticket += 1
            self._requests_total.inc(priority=priority)
            if continue_from is not None:
                return self._submit_continuation(
                    ticket, prompt, max_new_tokens=max_new_tokens,
                    eos_id=eos_id, hold_slot=hold_slot,
                    continue_from=continue_from, priority=priority)
            prefix_tokens = None
            if prefix_id is not None:
                entry = self.prefix_store.lookup(prefix_id)
                if entry is None:
                    raise KeyError(
                        f"unknown or stale fleet prefix_id {prefix_id}")
                prefix_tokens = list(entry.tokens)
                if prompt[:len(prefix_tokens)] != prefix_tokens:
                    raise ValueError(
                        "prompt does not start with the registered "
                        f"prefix (prefix_id {prefix_id})")
            req = FleetRequest(
                ticket=ticket, prompt=list(prompt),
                max_new_tokens=max_new_tokens, priority=priority,
                eos_id=eos_id, prefix_tokens=prefix_tokens,
                hold_slot=hold_slot, tenant_id=tenant_id,
                deadline=None if deadline_s is None else now + deadline_s,
                submitted_at=now)
            self._requests[ticket] = req
            self.timelines.begin(ticket, priority, now)
            rejected = self.admission.offer(req, now)
            if rejected is not None:
                self._outcomes[ticket] = rejected
                self.timelines.finish_rejected(ticket, now,
                                               reason=rejected.reason)
            return ticket

    def submit_group(self, prompt: List[int], group_size: int, *,
                     max_new_tokens: int = 128,
                     priority: str = TRAIN_ROLLOUT,
                     eos_id: Optional[int] = None,
                     tenant_id: Optional[str] = None) -> List[int]:
        """GRPO group submit: ``group_size`` decodes of one shared
        prompt, dispatched to ONE router-picked replica so the engine's
        shared-prefill path applies (one prefill, KV block tables
        forked replica-locally — fork sharing never crosses a replica
        boundary, and a migration checkpoint of any member gathers an
        unshared payload, so per-leaf migration stays legal). Group
        submits are the training plane's own rollouts and dispatch
        immediately, like continuations; when no live replica offers a
        group-capable engine, members degrade to ``group_size``
        independent submits through normal admission — slower, never
        inexact. Returns one fleet ticket per member, donor first."""
        if group_size < 1:
            raise ValueError(f"group_size {group_size} < 1")
        with self._lock:
            now = self.clock()
            reqs: List[FleetRequest] = []
            for _ in range(group_size):
                ticket = self._next_ticket
                self._next_ticket += 1
                self._requests_total.inc(priority=priority)
                req = FleetRequest(
                    ticket=ticket, prompt=list(prompt),
                    max_new_tokens=max_new_tokens, priority=priority,
                    eos_id=eos_id, tenant_id=tenant_id,
                    submitted_at=now)
                self._requests[ticket] = req
                self.timelines.begin(ticket, priority, now)
                reqs.append(req)
            tickets = [r.ticket for r in reqs]
            replica = self.router.pick(reqs[0])
            if (replica is not None
                    and hasattr(replica, "submit_group")
                    and hasattr(replica.engine, "submit_group")):
                try:
                    replica.submit_group(reqs)
                except (ValueError, KeyError, RpcError, ReplicaDead):
                    pass    # degrade below — members still dispatch
                else:
                    for req in reqs:
                        req.dispatched_at = now
                        self.timelines.mark(
                            req.ticket, "dispatched", now,
                            replica=replica.replica_id, group=True)
                    self._group_submits.inc()
                    return tickets
            self._group_degrades.inc()
            for req in reqs:
                rejected = self.admission.offer(req, now)
                if rejected is not None:
                    self._outcomes[req.ticket] = rejected
                    self.timelines.finish_rejected(
                        req.ticket, now, reason=rejected.reason)
            return tickets

    def _submit_continuation(self, ticket: int, prompt: List[int], *,
                             max_new_tokens: int, eos_id: Optional[int],
                             hold_slot: bool, continue_from: int,
                             priority: str) -> int:
        # guarded-by: caller
        """Turn continuation: pinned to the replica holding the slot's
        KV, dispatched immediately (it extends a conversation that
        already passed admission).

        When the holding replica is dead/gone (or alive but its slot is
        lost — e.g. the id was resurrected under a fresh engine), the
        conversation is NOT lost: the engine's continuation contract
        passes the FULL token stream, so ``prompt`` is the complete
        transcript — the fleet re-prefills it on a survivor and re-pins
        the ticket there (``senweaver_serve_continuation_replays_total``).
        ValueError only when no survivor can take it — the same contract
        as the engine, so clients still have their full-prefill
        fallback."""
        prev = self._requests.get(continue_from)
        if prev is None or prev.replica_id is None:
            raise ValueError(
                f"continue_from={continue_from}: unknown ticket")
        replica = next((r for r in self.replicas
                        if r.replica_id == prev.replica_id), None)
        now = self.clock()
        req = FleetRequest(
            ticket=ticket, prompt=list(prompt),
            max_new_tokens=max_new_tokens, priority=priority,
            eos_id=eos_id, hold_slot=hold_slot, submitted_at=now)
        if replica is not None and replica.state != DEAD:
            try:
                rid = replica.engine.submit(
                    prompt, max_new_tokens=max_new_tokens,
                    continue_from=prev.engine_rid, hold_slot=hold_slot,
                    eos_id=eos_id)
            except (ValueError, KeyError, RpcError):
                # The slot is gone even though the replica id answers
                # (fresh engine behind a resurrected id), or a remote
                # holder is unreachable: survivor replay below.
                replica = None
            else:
                self._requests[ticket] = req
                replica.adopt(rid, req)
                req.dispatched_at = now
                self.timelines.begin(ticket, priority, now)
                self.timelines.mark(ticket, "dispatched", now,
                                    replica=replica.replica_id,
                                    continuation=True)
                return ticket
        # Survivor replay: full re-prefill of the recorded transcript,
        # slot re-held on whichever live replica the router picks.
        survivor = self.router.pick(req)
        if survivor is None:
            raise ValueError(
                f"continue_from={continue_from}: replica "
                f"{prev.replica_id} is gone and no survivor accepts; "
                f"slot released")
        kwargs = dict(max_new_tokens=max_new_tokens,
                      hold_slot=hold_slot, eos_id=eos_id)
        if getattr(survivor.engine, "supports_idempotency", False):
            kwargs["idempotency_key"] = f"cont-{ticket}"
        rid = survivor.engine.submit(list(prompt), **kwargs)
        self._requests[ticket] = req
        survivor.adopt(rid, req)
        req.dispatched_at = now
        self._continuation_replays.inc()
        self.timelines.begin(ticket, priority, now)
        self.timelines.mark(ticket, "dispatched", now,
                            replica=survivor.replica_id,
                            continuation=True)
        self.timelines.event(ticket, "continuation_replay", now,
                             source=prev.replica_id,
                             replica=survivor.replica_id)
        return ticket

    def register_prefix(self, tokens: List[int]) -> int:
        """Fleet-level prefix id. The KV materializes at first dispatch
        via the one-prefill broadcast: the picked replica prefills ONCE
        and the store installs its buffer into every other live replica
        (device-to-device copy), so the whole fleet is warm — the
        router's prefix affinity becomes a tiebreak, not a necessity.
        Invalidated by the next weight publish — submit() raises
        KeyError then, and auto_prefix clients re-register."""
        with self._lock:
            return self.prefix_store.register(tokens)

    def is_done(self, ticket: int) -> bool:
        with self._lock:
            self._require(ticket)
            return ticket in self._outcomes

    def outcome(self, ticket: int
                ) -> Optional[Union[Completed, Rejected]]:
        with self._lock:
            self._require(ticket)
            return self._outcomes.get(ticket)

    def result(self, ticket: int) -> List[int]:
        """Tokens so far (live view while decoding, final list once
        completed). Raises :class:`RequestRejected` for shed requests —
        a typed error, never a silently empty generation."""
        with self._lock:
            out = self._outcomes.get(ticket)
            if isinstance(out, Completed):
                return list(out.tokens)
            if isinstance(out, Rejected):
                raise RequestRejected(out)
            req = self._require(ticket)
            if req.engine_rid is not None and req.replica_id is not None:
                replica = self._replica_by_id(req.replica_id)
                if replica is not None and replica.state != DEAD:
                    return replica.engine.result(req.engine_rid)
            return []

    def result_logps(self, ticket: int) -> List[float]:
        with self._lock:
            out = self._outcomes.get(ticket)
            if isinstance(out, Completed):
                return list(out.logps)
            if isinstance(out, Rejected):
                raise RequestRejected(out)
            req = self._require(ticket)
            if req.engine_rid is not None and req.replica_id is not None:
                replica = self._replica_by_id(req.replica_id)
                if replica is not None and replica.state != DEAD:
                    return replica.engine.result_logps(req.engine_rid)
            return []

    def release_slot(self, ticket: int) -> None:
        """Free a held decode slot (turn continuation ended)."""
        with self._lock:
            req = self._requests.get(ticket)
            if req is None or req.replica_id is None \
                    or req.engine_rid is None:
                return
            replica = self._replica_by_id(req.replica_id)
            if replica is not None and replica.state != DEAD:
                replica.engine.release_slot(req.engine_rid)

    # -- pump ----------------------------------------------------------------
    def step(self) -> Dict[int, List[int]]:
        """One scheduling + decode round; returns {ticket: [tokens]}
        emitted this step (the engine.step contract, ticket-keyed)."""
        with self._lock:
            now = self.clock()
            self.publisher.advance()
            self._track_publish_window(now)
            self._reap_quarantined(now)
            self._probe_replicas(now)
            self._note_kv_pressure()
            for rej in self.admission.shed_expired(now):
                self._record_rejection(rej)
            self._pump_federation(now)
            if self.autoscaler is not None:
                self.autoscaler.evaluate(now)
            if self.migrator is not None:
                self.migrator.pump(now)
            self._dispatch(now)
            emitted_by_ticket: Dict[int, List[int]] = {}
            for replica in list(self.replicas):
                if replica.state == DEAD or not replica.has_work():
                    continue
                try:
                    emitted, done = replica.step()
                except Exception:
                    self._record_fault(replica, now)
                    continue
                self._ingest(replica, emitted, done, emitted_by_ticket)
            return emitted_by_ticket

    def run(self) -> Dict[int, List[int]]:
        """Pump until every submitted request has an outcome. Returns
        {ticket: tokens} for the COMPLETED ones (rejected tickets carry
        their outcome, reachable via ``outcome()``)."""
        while self.pending():
            self.step()
        with self._lock:
            return {t: list(o.tokens)
                    for t, o in self._outcomes.items()
                    if isinstance(o, Completed)}

    def pending(self) -> int:
        with self._lock:
            return len(self._requests) - len(self._outcomes)

    def stream(self, ticket: int) -> Iterator[int]:
        """Yield ``ticket``'s tokens as they decode, pumping the fleet
        (manual mode) until the request finishes."""
        sent = 0
        while True:
            done = self.is_done(ticket)
            toks = self.result(ticket)      # raises if rejected
            while sent < len(toks):
                yield toks[sent]
                sent += 1
            if done:
                return
            self.step()

    # -- weights -------------------------------------------------------------
    def update_params(self, params, *, epoch: Optional[int] = None,
                      version: Optional[int] = None) -> int:
        """Versioned rolling publish (the ``engine.update_params``
        drop-in the online loop calls). Blocks until every live replica
        serves the new version, pumping the fleet meanwhile — serving
        never stops, generations never mix versions.

        ``(epoch, version)`` is the optional fencing token (see
        :meth:`WeightPublisher.begin`); a stale pair raises
        :class:`~.weights.StalePublishError` without touching any
        replica."""
        v = self.begin_publish(params, epoch=epoch, version=version)
        if self._dispatcher is not None:
            # Threaded mode: the dispatcher pumps the roll forward.
            while self.publisher.in_progress:
                time.sleep(0.001)
        else:
            while self.publisher.in_progress:
                self.step()
        return v

    def begin_publish(self, params, *, epoch: Optional[int] = None,
                      version: Optional[int] = None,
                      eager: bool = False) -> int:
        """Stage a fenced publish WITHOUT blocking on the roll — the
        learner-gateway path: the fleet's own pump (manual ``step()``
        or the dispatcher thread) rolls it forward while the learner
        polls convergence over rpc. ``eager=True`` requests the
        no-drain roll (replicas swap opportunistically at zero
        in-flight; see :meth:`WeightPublisher.begin`) — the streaming
        learner's default, so collection never pauses for a publish."""
        with self._lock:
            v = self.publisher.begin(params, epoch=epoch,
                                     version=version, eager=eager)
            self._track_publish_window(self.clock())
            return v

    def publish_draft(self, params, *, epoch: Optional[int] = None,
                      version: Optional[int] = None) -> int:
        """Publish speculation-DRAFT weights to every live replica
        (the online distiller's fleet entry point). Same
        ``(epoch, version)`` fence as :meth:`update_params`, but
        applied immediately — no drain, because draft weights only
        move the acceptance rate, never the outputs."""
        with self._lock:
            return self.publisher.publish_draft(params, epoch=epoch,
                                                version=version)

    def publish_adapter(self, tenant_id: str, lora, *,
                        epoch: Optional[int] = None,
                        version: Optional[int] = None) -> int:
        """Publish one tenant's LoRA adapter to every live replica
        (the per-tenant learner's fleet entry point). Same
        ``(epoch, version)`` fence as :meth:`begin_publish`, but
        applied immediately with no drain: adapter versions bind at
        submit time, so in-flight decodes — including this tenant's —
        finish untouched and only the tenant's next requests see the
        new version. Other tenants never notice."""
        with self._lock:
            return self.publisher.publish_adapter(
                tenant_id, lora, epoch=epoch, version=version)

    @property
    def threaded(self) -> bool:
        """True when the dispatcher thread owns the pump (start()ed)."""
        return self._dispatcher is not None

    # -- chaos / operations --------------------------------------------------
    def add_replica(self, engine, *,
                    replica_id: Optional[str] = None,
                    host_group: Optional[str] = None) -> EngineReplica:
        """Grow the fleet with a new (or resurrected) replica. The
        engine must already hold the CURRENT published params — the
        fleet stamps it with the publisher's version rather than
        replaying the publish. Shared prefixes are NOT pushed eagerly;
        the store backfills on the replica's first prefix-bearing
        dispatch (the lazy half of the broadcast protocol)."""
        with self._lock:
            if replica_id is None:
                replica_id = f"replica-{len(self.replicas)}"
            existing = self._replica_by_id(replica_id)
            if existing is not None:
                if existing.state != DEAD:
                    raise ValueError(f"replica id {replica_id!r} taken")
                # Resurrection: the id's previous incarnation is DEAD —
                # drop the carcass from every membership list (fleet,
                # router load tracking, publisher roll set) and from the
                # prefix store's installed sets, so the new engine is
                # lazily backfilled instead of assumed warm.
                self.replicas.remove(existing)
                self.router.replicas.remove(existing)
                if existing in self.publisher.replicas:
                    self.publisher.replicas.remove(existing)
                self.prefix_store.forget_replica(replica_id)
            replica = (engine if isinstance(engine, EngineReplica)
                       else EngineReplica(replica_id, engine,
                                          host_group=host_group,
                                          registry=self.registry))
            # Through the replica's own locked mutator: weight_version
            # is guarded by replica._lock, not ours (analysis LOCK102).
            replica.stamp_version(self.publisher.version)
            if self.migrator is not None \
                    and hasattr(replica.engine, "migrate_on_pressure"):
                replica.engine.migrate_on_pressure = True
            # router and publisher hold their own list copies; the
            # prefix store shares self.replicas by reference.
            self.replicas.append(replica)
            self.router.replicas.append(replica)
            self.publisher.replicas.append(replica)
            self._replicas_live.set(
                sum(r.state != DEAD for r in self.replicas))
        if self._dispatcher is not None:        # threaded mode
            replica.start(self._on_replica_step)
        return replica

    def attach_autoscaler(self, spawn_engine, *, config=None):
        """Wire the admission-driven autoscaler: queue-depth and
        shed-rate signals drive ``add_replica``/drain through a
        hysteresis controller evaluated once per pump.
        ``spawn_engine()`` must return an engine already holding the
        CURRENT published params (``add_replica`` stamps the version);
        it runs under the fleet lock, so keep it cheap or pre-built.
        When federation is attached (before or after), the controller
        reads FLEET-WIDE rollups instead of this process's gauges."""
        from .autoscale import AutoscaleConfig, AutoscaleController
        with self._lock:
            self.autoscaler = AutoscaleController(
                self, spawn_engine,
                config=config or AutoscaleConfig(),
                registry=self.registry,
                fleet_store=(self.federation.store
                             if self.federation is not None else None))
            if self.migrator is not None:
                self.autoscaler.migrator = self.migrator
            return self.autoscaler

    def attach_migration(self, *, min_headroom: Optional[float] = None):
        """Wire the live-migration plane (serve/scheduler.py): a
        :class:`~.scheduler.MigrationCoordinator` pumped once per fleet
        tick turns the three request-hurting degrade paths into
        placement decisions — KV-pressure preempt caps migrate instead
        of truncate-finishing, blocked eager publishes migrate work off
        instead of degrading to a drain, and autoscale scale-down
        evacuates instead of draining out. Local engines get
        ``migrate_on_pressure`` flipped on; remote engines keep the
        legacy truncate ladder (the flag is host-local — their own
        fleet process flips it)."""
        from .scheduler import GlobalScheduler, MigrationCoordinator
        with self._lock:
            store = (self.federation.store
                     if self.federation is not None else None)
            kwargs = {}
            if min_headroom is not None:
                kwargs["min_headroom"] = float(min_headroom)
            # router.replicas by reference: add_replica appends there,
            # so autoscaled joiners are migration targets immediately.
            scheduler = GlobalScheduler(self.router.replicas,
                                        fleet_store=store, **kwargs)
            self.migrator = MigrationCoordinator(
                self.router, self.publisher, scheduler=scheduler,
                registry=self.registry)
            if self.autoscaler is not None:
                self.autoscaler.migrator = self.migrator
            for r in self.replicas:
                if hasattr(r.engine, "migrate_on_pressure"):
                    r.engine.migrate_on_pressure = True
            return self.migrator

    def attach_federation(self, federator, *, alert_manager=None):
        """Wire the fleet observability plane into the pump: the
        :class:`~..obs.federation.MetricsFederator` polls every peer on
        its own cadence and an optional
        :class:`~..obs.alerts.AlertManager` is evaluated right after,
        both once per pump under the fleet lock. An already-attached
        autoscaler is pointed at the federated store so capacity
        decisions see fleet-wide pressure."""
        with self._lock:
            self.federation = federator
            self.alerts = alert_manager
            if self.autoscaler is not None:
                self.autoscaler.fleet_store = federator.store
            return federator

    def _pump_federation(self, now: float) -> None:
        # guarded-by: _lock
        if self.federation is not None:
            self.federation.poll(now)
            if self.alerts is not None:
                self.alerts.evaluate(now)

    def kill_replica(self, replica_id: str) -> None:
        """Declare a replica dead (chaos hook / operator action); its
        in-flight requests are retried elsewhere or shed explicitly."""
        with self._lock:
            replica = self._replica_by_id(replica_id)
            if replica is None:
                raise KeyError(f"no replica {replica_id!r}")
            self._handle_death(replica, self.clock())

    # -- threaded mode -------------------------------------------------------
    def start(self, *, dispatch_interval_s: float = 0.001) -> None:
        """Threaded serving: per-replica stepper threads + a dispatcher
        thread running admission/routing/publish; ``submit``/``result``
        stay safe from any thread."""
        if self._dispatcher is not None:
            return
        self._stop.clear()
        for replica in self.replicas:
            replica.start(self._on_replica_step)

        def dispatch_loop():
            while not self._stop.is_set():
                with self._lock:
                    now = self.clock()
                    self.publisher.advance()
                    self._track_publish_window(now)
                    self._reap_quarantined(now)
                    self._probe_replicas(now)
                    self._note_kv_pressure()
                    for rej in self.admission.shed_expired(now):
                        self._record_rejection(rej)
                    self._pump_federation(now)
                    if self.autoscaler is not None:
                        self.autoscaler.evaluate(now)
                    if self.migrator is not None:
                        self.migrator.pump(now)
                    self._dispatch(now)
                    self._reap_faulted(now)
                time.sleep(dispatch_interval_s)

        self._dispatcher = threading.Thread(
            target=dispatch_loop, name="serve-dispatch", daemon=True)
        self._dispatcher.start()

    def stop(self) -> None:
        self._stop.set()
        if self._dispatcher is not None:
            self._dispatcher.join(timeout=5)
            self._dispatcher = None
        for replica in self.replicas:
            replica.stop()

    def _note_kv_pressure(self) -> None:
        # guarded-by: _lock
        """Sample fleet KV pool pressure and feed the admission gate.

        The aggregate is the MIN over accepting live replicas (any
        headroom anywhere means the fleet can still place work); with
        none accepting, the min over all live ones. Runs every pump,
        BEFORE autoscaler.evaluate and _dispatch, so both planes act on
        this pump's signal rather than last pump's."""
        live = [r for r in self.replicas if r.state != DEAD]
        pool = [r for r in live if r.accepting] or live
        pressure = min((float(getattr(r, "kv_pressure", 0.0))
                        for r in pool), default=0.0)
        self._kv_pressure_gauge.set(pressure)
        self.admission.note_kv_pressure(pressure)

    def _on_replica_step(self, replica: EngineReplica,
                         emitted: Dict[int, List[int]],
                         done: List[FleetRequest]) -> None:
        """Stepper-thread completion intake (threaded mode)."""
        with self._lock:
            self._ingest(replica, emitted, done, {})

    # -- stats ---------------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            completed = sum(isinstance(o, Completed)
                            for o in self._outcomes.values())
            rejected = sum(isinstance(o, Rejected)
                           for o in self._outcomes.values())
            out: Dict[str, Any] = {
                "replicas": {r.replica_id: r.stats()
                             for r in self.replicas},
                "replicas_live": sum(r.state != DEAD
                                     for r in self.replicas),
                "queue_depth": self.admission.depth(),
                **self.admission.stats(),
                "pending": len(self._requests) - len(self._outcomes),
                "completed": completed,
                "rejected": rejected,
                "weight_version": self.publisher.version,
                "publish_epoch": self.publisher.epoch,
                "weight_version_skew": self.publisher.skew(),
                "publish_in_progress": self.publisher.in_progress,
                "adapter_versions": dict(self.publisher.adapter_versions),
                **self.prefix_store.stats(),
                **self.timelines.stats(),
                "slo": self.slo.summary(),
            }
            return out

    def snapshot_event(self) -> Dict[str, Any]:
        """Flat serving snapshot for the metrics JSONL (the shape
        ``scripts/serve_report.py`` renders). Captured via the wired
        ``metrics_service`` when :meth:`record_snapshot` is called."""
        with self._lock:
            def hsnap(name):
                h = self.registry.get(name)
                if h is None:
                    return 0.0, 0
                total_sum = total_count = 0.0
                for cell in h.samples().values():
                    total_sum += cell[-2]
                    total_count += cell[-1]
                return total_sum, int(total_count)

            def ctotal(name):
                m = self.registry.get(name)
                if m is None:
                    return 0
                return sum(float(v) for v in m.samples().values())

            ttft_sum, ttft_n = hsnap("senweaver_serve_ttft_ms")
            e2e_sum, e2e_n = hsnap("senweaver_serve_e2e_ms")
            inst_sum, inst_n = hsnap("senweaver_serve_prefix_install_ms")

            def ttft_buckets():
                # Per-priority cumulative TTFT buckets — what
                # scripts/prefix_report.py derives p50/p95 from.
                h = self.registry.get("senweaver_serve_ttft_ms")
                if h is None or not hasattr(h, "snapshot"):
                    return {}
                out = {}
                from .admission import PRIORITY_CLASSES
                for p in PRIORITY_CLASSES:
                    snap = h.snapshot(priority=p)
                    if snap["count"]:
                        out[p] = {
                            "buckets": {str(k): v for k, v
                                        in snap["buckets"].items()},
                            "sum": snap["sum"],
                            "count": snap["count"]}
                return out

            return {
                "replicas_live": sum(r.state != DEAD
                                     for r in self.replicas),
                "queue_depth": self.admission.depth(),
                "completed": ctotal("senweaver_serve_completed_total"),
                "shed": ctotal("senweaver_serve_shed_total"),
                "retries": ctotal("senweaver_serve_retries_total"),
                "publishes": ctotal("senweaver_serve_publishes_total"),
                "weight_version_skew": self.publisher.skew(),
                "ttft_ms_sum": ttft_sum, "ttft_count": ttft_n,
                "e2e_ms_sum": e2e_sum, "e2e_count": e2e_n,
                "prefix_broadcasts": ctotal(
                    "senweaver_serve_prefix_broadcasts_total"),
                "prefix_prefills_avoided": ctotal(
                    "senweaver_serve_prefix_prefills_avoided_total"),
                "prefix_broadcast_failures": ctotal(
                    "senweaver_serve_prefix_broadcast_failures_total"),
                "prefix_invalidations": ctotal(
                    "senweaver_serve_prefix_invalidations_total"),
                "prefix_install_ms_sum": inst_sum,
                "prefix_install_count": inst_n,
                "remote_rpcs": ctotal(
                    "senweaver_serve_remote_rpcs_total"),
                "remote_rpc_retries": ctotal(
                    "senweaver_serve_remote_rpc_retries_total"),
                "remote_rpc_errors": ctotal(
                    "senweaver_serve_remote_rpc_errors_total"),
                "breaker_opens": ctotal(
                    "senweaver_serve_remote_breaker_opens_total"),
                "continuation_replays": ctotal(
                    "senweaver_serve_continuation_replays_total"),
                "publish_quarantined": ctotal(
                    "senweaver_serve_publish_quarantined_total"),
                "weight_version": self.publisher.version,
                "publish_epoch": self.publisher.epoch,
                "stale_publishes": ctotal(
                    "senweaver_serve_stale_publish_total"),
                "autoscale_actions": ctotal(
                    "senweaver_serve_autoscale_actions_total"),
                "learner_publishes": ctotal(
                    "senweaver_learner_publishes_total"),
                "adapter_publishes": ctotal(
                    "senweaver_serve_adapter_fleet_publishes_total"),
                "adapter_install_failures": ctotal(
                    "senweaver_serve_adapter_install_failures_total"),
                "adapter_affinity_hits": ctotal(
                    "senweaver_serve_adapter_affinity_hits_total"),
                "ttft_by_priority": ttft_buckets(),
                "slo_requests": ctotal(
                    "senweaver_serve_slo_requests_total"),
                "slo_violations": ctotal(
                    "senweaver_serve_slo_violations_total"),
                "slo": self.slo.summary(),
            }

    def record_snapshot(self) -> None:
        """Capture a "Serving Snapshot" event on the wired metrics
        service (no-op without one)."""
        if self.metrics_service is not None:
            self.metrics_service.capture("Serving Snapshot",
                                         self.snapshot_event())

    # -- internals -----------------------------------------------------------
    def _require(self, ticket: int) -> FleetRequest:
        req = self._requests.get(ticket)
        if req is None:
            raise KeyError(f"unknown ticket {ticket}")
        return req

    def _replica_by_id(self, replica_id: str
                       ) -> Optional[EngineReplica]:
        return next((r for r in self.replicas
                     if r.replica_id == replica_id), None)

    def _dispatch(self, now: float) -> None:
        """Move admitted requests onto accepting replicas, priority
        first, until nothing is ready or nothing accepts."""
        while True:
            req, sheds = self.admission.pop_ready(now)
            for rej in sheds:
                self._record_rejection(rej)
            if req is None:
                return
            replica = self.router.pick(req)
            if replica is None:
                self.admission.requeue(req)     # nothing accepting now
                return
            self.timelines.mark(
                req.ticket, "queue_exit",
                req.queue_exit_at if req.queue_exit_at is not None
                else now,
                **({"routed_by": req.routed_by} if req.routed_by
                   else {}))
            prefill_mode = None
            if req.prefix_tokens:
                # Warm the picked replica BEFORE dispatch: donor prefill
                # + fleet broadcast on first touch, backfill install for
                # late joiners — never raises; on failure the replica's
                # own lazy register_prefix path inside submit() covers.
                prefill_mode = self.prefix_store.ensure(
                    replica, req.prefix_tokens) or "lazy"
            from ..obs import get_tracer
            tracer = get_tracer()
            try:
                # The dispatch span is the trace ROOT the remote side
                # stitches under: the client-attempt spans open inside
                # it (same thread), transports inject its context, and
                # the server spans attach to it across the wire.
                with tracer.span("fleet.dispatch", ticket=req.ticket,
                                 replica=replica.replica_id,
                                 priority=req.priority,
                                 attempt=req.attempts):
                    ctx = tracer.capture()
                    if ctx is not None:
                        self.timelines.set_trace(req.ticket, ctx[0])
                    self.timelines.mark(
                        req.ticket, "prefill_start", now,
                        **({"mode": prefill_mode} if prefill_mode
                           else {}))
                    replica.submit(req)
                req.dispatched_at = now
                self.timelines.mark(req.ticket, "prefill_done",
                                    self.clock())
                dispatch_attrs: Dict[str, Any] = {
                    "replica": replica.replica_id}
                if req.submit_ms is not None:
                    dispatch_attrs["submit_ms"] = round(req.submit_ms, 3)
                self.timelines.mark(req.ticket, "dispatched", now,
                                    **dispatch_attrs)
            except Exception:
                self.timelines.event(req.ticket, "retry", now,
                                     reason="submit_failed",
                                     replica=replica.replica_id)
                # Submit blew up (chaos engine, OOM, wedged pool):
                # fault the replica; the request goes back through the
                # router's retry/shed triage like an orphan.
                if replica.record_fault(REASON_ERROR):
                    self.admission.requeue(req)
                    self._handle_death(replica, now)
                else:
                    req.attempts += 1
                    if req.attempts > self.router.max_retries:
                        self._record_rejection(Rejected(
                            ticket=req.ticket, priority=req.priority,
                            reason=REJECT_REPLICA_FAILURE,
                            detail=f"submit failed "
                                   f"{req.attempts} times"))
                    else:
                        req.not_before = now + self.router.retry.backoff_s(
                            req.attempts)
                        self.admission.requeue(req)

    def _ingest(self, replica: EngineReplica,
                emitted: Dict[int, List[int]],
                done: List[FleetRequest],
                emitted_by_ticket: Dict[int, List[int]]) -> None:
        """Book token emissions (TTFT) and completions (outcomes)."""
        now = self.clock()
        done_by_rid = {r.engine_rid: r for r in done}
        for rid, toks in emitted.items():
            req = replica.inflight.get(rid) or done_by_rid.get(rid)
            if req is None:
                continue                # e.g. pre-kill stragglers
            emitted_by_ticket.setdefault(req.ticket, []).extend(toks)
            if req.first_token_at is None and toks:
                req.first_token_at = now
                self._ttft_ms.observe(
                    (now - req.submitted_at) * 1000.0,
                    priority=req.priority)
                # First-wins: after a mid-decode failover the engine
                # re-emits, but the timeline keeps the FIRST time any
                # token reached the caller.
                self.timelines.mark(req.ticket, "first_token", now,
                                    replica=replica.replica_id)
            if toks and self.migrator is not None:
                # First post-migration token = the handoff ack: the
                # target demonstrably owns the decode, so the frozen
                # source copy can be released (no-op for unmigrated
                # requests).
                self.migrator.note_progress(req, now)
        for req in done:
            self._complete(replica, req, now)

    def _complete(self, replica: EngineReplica, req: FleetRequest,
                  now: float) -> None:
        # guarded-by: caller
        try:
            tokens = replica.engine.result(req.engine_rid)
            logps = replica.engine.result_logps(req.engine_rid)
        except Exception:
            # The replica vanished between emitting ``done`` and the
            # result fetch (a remote holder partitioned mid-handoff, or
            # its breaker opened). The finished tokens died with it —
            # route the request through the SAME retry/shed triage as a
            # death orphan instead of losing an admitted ticket.
            self._record_fault(replica, now)
            self.timelines.event(req.ticket, "retry", now,
                                 reason="result_lost",
                                 replica=replica.replica_id)
            if self.migrator is not None \
                    and self.migrator.rescue_request(req, now):
                # The request was a pre-ack migration target whose
                # result vanished — its frozen source copy resumed, so
                # this is a zero-loss failover, not a retry.
                return
            self.router.on_request_departure(req)
            if not self.router.live_replicas():
                self._record_rejection(Rejected(
                    ticket=req.ticket, priority=req.priority,
                    reason=REJECT_NO_REPLICAS,
                    detail="result lost and no live replicas"))
            elif req.attempts > self.router.max_retries:
                self._record_rejection(Rejected(
                    ticket=req.ticket, priority=req.priority,
                    reason=REJECT_REPLICA_FAILURE,
                    detail=f"result fetch failed after "
                           f"{req.attempts - 1} retries"))
            else:
                req.not_before = now + self.router.retry.backoff_s(
                    req.attempts)
                self.admission.requeue(req)
            return
        if self.migrator is not None:
            # Defensive ack: a decode that finishes on its migration
            # target in the very step it was installed never passes
            # through _ingest with the pending entry open.
            self.migrator.note_complete(req, now)
        e2e_ms = (now - req.submitted_at) * 1000.0
        self._outcomes[req.ticket] = Completed(
            ticket=req.ticket, priority=req.priority,
            tokens=list(tokens), logps=list(logps),
            replica_id=replica.replica_id,
            weight_version=(req.version_at_dispatch
                            if req.version_at_dispatch is not None
                            else replica.weight_version),
            weight_version_at_finish=(req.version_at_finish
                                      if req.version_at_finish is not None
                                      else replica.weight_version),
            attempts=req.attempts,
            ttft_ms=(None if req.first_token_at is None
                     else (req.first_token_at - req.submitted_at)
                     * 1000.0),
            e2e_ms=e2e_ms)
        self._completed_total.inc(priority=req.priority)
        self._e2e_ms.observe(e2e_ms, priority=req.priority)
        # Exactly-once by construction: finishing pops the live
        # timeline, so a chaos-retried path cannot produce a second one.
        self.timelines.finish_completed(
            req.ticket, now, tokens=len(tokens),
            replica_id=replica.replica_id, attempts=req.attempts)

    def _record_rejection(self, rej: Rejected) -> None:
        # Admission already counted its own sheds; router/fleet-origin
        # rejections (replica_failure / no_replicas) are counted here —
        # same counter, so the shed rate is one number.
        # guarded-by: caller
        if rej.reason in (REJECT_REPLICA_FAILURE, REJECT_NO_REPLICAS):
            self._shed_total.inc(priority=rej.priority,
                                 reason=rej.reason)
        self._outcomes[rej.ticket] = rej
        self.timelines.finish_rejected(rej.ticket, self.clock(),
                                       reason=rej.reason)

    def _record_fault(self, replica: EngineReplica, now: float) -> None:
        if replica.record_fault(REASON_ERROR):
            self._handle_death(replica, now)

    def _handle_death(self, replica: EngineReplica, now: float) -> None:
        if self.migrator is not None:
            # BEFORE orphan triage: pre-ack migration targets hand
            # their requests back to the frozen source copies (token-
            # exact, not a retry); pre-ack sources just drop out of the
            # pending ledger. Either way the router below never sees
            # those requests as orphans.
            self.migrator.on_replica_death(replica, now)
        requeue, shed = self.router.on_replica_death(replica, now)
        self._replicas_live.set(
            sum(r.state != DEAD for r in self.replicas))
        for rej in shed:
            self._record_rejection(rej)
        for req in requeue:
            self.timelines.event(req.ticket, "failover", now,
                                 replica=replica.replica_id,
                                 attempt=req.attempts)
            self.admission.requeue(req)
        if not self.router.live_replicas():
            for rej in self.admission.shed_all(
                    REJECT_NO_REPLICAS, "no live replicas"):
                self._record_rejection(rej)

    def _track_publish_window(self, now: float) -> None:
        """Turn publisher in_progress transitions into publish-pause
        windows on the timeline recorder, so a request completed during
        (or across) a rolling publish can account for the stall."""
        # guarded-by: caller
        in_progress = self.publisher.in_progress
        if in_progress and self._publish_started_at is None:
            self._publish_started_at = now
        elif not in_progress and self._publish_started_at is not None:
            self.timelines.publish_window(self._publish_started_at, now)
            self._publish_started_at = None

    def _reap_quarantined(self, now: float) -> None:
        """Turn publish-quarantined replicas (install unreachable mid-
        roll) into proper deaths: the publisher has no router, so orphan
        triage and live-count bookkeeping happen here."""
        # guarded-by: caller
        for replica in self.publisher.take_quarantined():
            if replica.state != DEAD:
                self._handle_death(replica, now)

    def _probe_replicas(self, now: float) -> None:
        """Hedged health probing of probe-capable (remote) replicas.
        A PROBE_DEAD outcome records a timeout fault — the SAME
        escalation budget real dispatch faults use — so a host that
        stops answering dies through the one LIVE→DEAD path; PROBE_SLOW
        is latency, counted but never lethal."""
        # guarded-by: caller
        if self.probe_interval_s <= 0:
            return
        if (self._last_probe_at is not None
                and now - self._last_probe_at < self.probe_interval_s):
            return
        self._last_probe_at = now
        for replica in list(self.replicas):
            probe = getattr(replica, "probe", None)
            if probe is None or replica.state == DEAD:
                continue
            if probe(now) == "dead":
                if replica.record_fault(REASON_TIMEOUT):
                    self._handle_death(replica, now)

    def _reap_faulted(self, now: float) -> None:
        """Threaded mode: stepper threads can only RECORD faults; the
        dispatcher turns a replica whose fault budget is spent into a
        proper death (orphan triage included)."""
        for replica in self.replicas:
            if (replica.state != DEAD
                    and replica._consecutive_faults
                    >= replica.max_consecutive_faults):
                self._handle_death(replica, now)
